"""Container — dependency-injection root owning all shared state.

Capability parity with ``pkg/gofr/container/container.go`` (Container struct
27-46; ``Create`` composition root 63-146: remote logger, metrics manager +
framework metrics, Redis, SQL, pub/sub backend switch from env, File;
framework metric catalog 158-190) and ``container/health.go`` (aggregated
deep health 8-66).

TPU addition (north star): the container owns a ``tpu`` executor datasource —
models resident in device HBM, AOT-compiled XLA executables, per-device
health — created when ``TPU_ENABLED`` is truthy, with a CPU-backed executor
as the test double (the "miniredis of XLA", SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from gofr_tpu.config import Config, MapConfig
from gofr_tpu.logging import Level, Logger, new_logger, new_silent_logger
from gofr_tpu.metrics import Manager, new_manager
from gofr_tpu.slo import SLOTracker
from gofr_tpu.trace import Tracer, new_tracer
from gofr_tpu.version import FRAMEWORK_VERSION


class Container:
    def __init__(self, config: Optional[Config] = None,
                 logger: Optional[Logger] = None):
        self.config: Config = config if config is not None else MapConfig()
        self.app_name = self.config.get_or_default("APP_NAME", "gofr-tpu-app")
        self.app_version = self.config.get_or_default("APP_VERSION", "dev")
        self.logger: Logger = logger if logger is not None else new_logger()
        self.metrics: Manager = new_manager(self.logger)
        self.tracer: Tracer = Tracer()
        self.services: Dict[str, Any] = {}
        # SLO accounting (windowed goodput/TTFT) + degradation watchdog;
        # the watchdog is created by App.start (it needs the event loop)
        self.slo = SLOTracker(self.metrics)
        self.watchdog = None

        # datasources (all optional; wired by create())
        self.sql = None
        self.redis = None
        self.pubsub = None
        self.mongo = None
        self.cassandra = None
        self.clickhouse = None
        self.file = None
        self.tpu = None
        self.tpu_batcher = None  # created by App.start when tpu is wired
        self.batch_lane = None   # pub/sub generation lane (BATCH_LANE_TOPIC)
        # disaggregated serving (ISSUE 8): ClusterRegistry of replica
        # roles, wired by the example/app when CLUSTER_ROLE/CLUSTER_PEERS
        # configure a prefill/decode split; folds into health() below
        self.cluster = None
        # the DisaggRouter serving that cluster, when one exists — the
        # clusterz/tracez pages discover it here (ISSUE 10)
        self.cluster_router = None
        # continuous telemetry plane (ISSUE 16): the bounded time-series
        # store + anomaly detector, created by App.start (TELEMETRY_*);
        # /debug/timez and the statusz sparkline section read it here
        self.telemetry = None
        # workload capture plane (ISSUE 17): the bounded shape-only
        # TrafficRecorder, created by App.start (TRAFFIC_REC_*);
        # /debug/workloadz and the replay harness read it here
        self.workload = None
        # SLO error-budget burn-rate plane (ISSUE 18): created by
        # App.start (SLO_BUDGET_*/SLO_OBJECTIVE_*); /debug/sloz and the
        # watchdog's budget_fn read it here
        self.slo_budget = None
        # worst-offender ring (ISSUE 18): top-K slowest requests per
        # window with finish-time diagnoses, created by App.start
        # (WHYZ_*); /debug/whyz and /debug/sloz read it here
        self.offenders = None
        # online operating-point auto-tuner (ISSUE 19): the cron-driven
        # controller, created by App.start (AUTOTUNE_*, opt-in);
        # /debug/tunez and the statusz autotune section read it here
        self.autotune = None

        self._start_time = time.time()

    # -- composition root (container.go:63-146) -----------------------------
    @classmethod
    def create(cls, config: Config, logger: Optional[Logger] = None) -> "Container":
        level = Level.parse(config.get_or_default("LOG_LEVEL", "INFO"))
        log = logger if logger is not None else new_logger(level)
        container = cls(config=config, logger=log)
        container.tracer = new_tracer(config, log)
        container.register_framework_metrics()

        # remote log level poller (container.go:73-75; remotelogger)
        remote_url = config.get("REMOTE_LOG_URL")
        if remote_url:
            from gofr_tpu.logging.remote_level import start_remote_level_poller
            interval = config.get_float("REMOTE_LOG_FETCH_INTERVAL", 15.0)
            start_remote_level_poller(log, remote_url, interval)

        # SQL (container.go:90)
        dialect = config.get("DB_DIALECT")
        if dialect:
            from gofr_tpu.datasource.sql import new_sql
            container.sql = new_sql(config, log, container.metrics)

        # Redis (container.go:88)
        if config.get("REDIS_HOST"):
            from gofr_tpu.datasource.redisx import new_redis
            container.redis = new_redis(config, log, container.metrics)

        # pub/sub backend switch (container.go:92-143)
        backend = (config.get("PUBSUB_BACKEND") or "").upper()
        if backend:
            from gofr_tpu.datasource.pubsub import new_pubsub
            container.pubsub = new_pubsub(backend, config, log,
                                          container.metrics,
                                          tracer=container.tracer)

        # file datasource (container.go:145)
        from gofr_tpu.datasource.file import LocalFileSystem
        container.file = LocalFileSystem(log)

        # TPU executor (north star; no reference analog)
        if config.get_bool("TPU_ENABLED", False):
            from gofr_tpu.tpu import new_executor
            container.tpu = new_executor(config, log, container.metrics)

        log.debug("container created for app %s@%s (framework %s)",
                  container.app_name, container.app_version, FRAMEWORK_VERSION)
        return container

    # -- framework metric catalog (container.go:158-190) --------------------
    def register_framework_metrics(self) -> None:
        metrics = self.metrics
        metrics.new_gauge("app_info", "application name/version info")
        metrics.new_gauge("threads_total", "live Python threads")
        metrics.new_gauge("memory_rss_bytes", "resident set size")
        metrics.new_gauge("gc_objects", "gen-0 tracked objects")
        metrics.new_gauge("uptime_seconds", "process uptime")
        metrics.new_histogram("app_http_response",
                              "inbound HTTP response time (s)",
                              (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10, 30))
        metrics.new_histogram("app_http_service_response",
                              "outbound HTTP call time (s)",
                              (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10, 30))
        metrics.new_histogram("app_redis_stats", "redis op time (s)",
                              (0.00005, 0.0001, 0.0003, 0.001, 0.003))
        metrics.new_histogram("app_sql_stats", "sql query time (s)",
                              (0.00005, 0.0001, 0.0005, 0.001, 0.01))
        # pushed by the SQL maintenance loop (sql.go:189-202 analog)
        metrics.new_gauge("app_sql_open_connections", "SQL connection up 0/1")
        metrics.new_gauge("app_sql_inuse_connections",
                          "SQL statements currently executing")
        metrics.new_counter("app_pubsub_publish_total_count", "publish attempts")
        metrics.new_counter("app_pubsub_publish_success_count", "publishes ok")
        metrics.new_counter("app_pubsub_subscribe_total_count", "receive attempts")
        metrics.new_counter("app_pubsub_subscribe_success_count", "receives ok")
        metrics.new_counter(
            "app_pubsub_consumer_paused_total",
            "consumer pause transitions per (topic, reason) — backpressure "
            "from the batch lane (admission_depth|kv_pages|degraded) or an "
            "explicit fetcher pause")
        # TPU catalog (north star: chip liveness + HBM pressure via metrics)
        metrics.new_histogram("app_tpu_execute", "XLA execute wall time (s)",
                              (0.0005, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1))
        metrics.new_histogram("app_tpu_batch_size", "dynamic batch sizes",
                              (1, 2, 4, 8, 16, 32, 64, 128, 256))
        metrics.new_gauge("app_tpu_hbm_bytes_in_use", "HBM bytes in use per device")
        metrics.new_gauge("app_tpu_device_up", "per-device liveness 0/1")
        metrics.new_counter("app_tpu_requests_total", "TPU predict requests")
        metrics.new_gauge("app_tpu_attention_window",
                          "decode attention window rung (fill-bounded)")
        metrics.new_histogram(
            "app_tpu_ttft",
            "time to first generated token (s): admission wait + prefill "
            "(the first token is sampled inside the prefill executable)",
            (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0))
        # SLO & saturation catalog (ISSUE 2): goodput vs raw throughput,
        # deadline outcome counts, device utilization, health transitions
        metrics.new_counter(
            "app_tpu_slo_total",
            "terminal requests by deadline outcome (ok|violated|expired)")
        # error-budget burn plane (ISSUE 18): derived from the labelled
        # app_tpu_slo_total series through the telemetry store — no
        # second counting path
        metrics.new_gauge(
            "app_tpu_slo_budget_remaining",
            "fraction of the (model, class) error budget left over the "
            "accounting window")
        metrics.new_gauge(
            "app_tpu_slo_burn_rate",
            "error-budget burn multiple per (model, class, window); 1.0 "
            "spends exactly the budget over the objective period")
        metrics.new_histogram(
            "app_tpu_deadline_violation_seconds",
            "how late past its deadline a violated request finished (s); "
            "bucket exemplars carry the trace id for /debug/whyz",
            (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0))
        metrics.new_gauge("app_tpu_tokens_per_s",
                          "raw generated tokens/s over the rolling window")
        metrics.new_gauge(
            "app_tpu_goodput_tokens_per_s",
            "tokens/s of requests that completed within deadline")
        metrics.new_gauge(
            "app_tpu_slo_attainment",
            "fraction of windowed terminal requests that met their deadline")
        metrics.new_gauge(
            "app_tpu_duty_cycle",
            "fraction of the rolling window the device spent executing")
        metrics.new_gauge(
            "app_tpu_mfu",
            "model flops utilization vs TPU_PEAK_FLOPS over the window")
        metrics.new_gauge("app_tpu_hbm_occupancy",
                          "HBM bytes_in_use / bytes_limit per device")
        metrics.new_counter(
            "app_health_transitions_total",
            "watchdog READY<->DEGRADED flips, labeled by target state")
        # compile-plane & shape catalog (ISSUE 3): recompiles, padding
        # waste, bucket fit, flush causes, step-phase anatomy
        metrics.new_counter(
            "app_tpu_compile_total",
            "XLA compiles by cause (warmup|serving) and model — any "
            "cause=serving increment is a cold compile on the hot path")
        metrics.new_histogram(
            "app_tpu_compile_seconds", "one XLA lower+compile wall time (s)",
            (0.1, 0.3, 1, 3, 10, 30, 100, 300))
        metrics.new_gauge(
            "app_tpu_padding_ratio",
            "fraction of executed device rows that were padding, over the "
            "rolling window")
        metrics.new_gauge(
            "app_tpu_effective_mfu",
            "MFU counting only real (non-padding) rows' FLOPs")
        metrics.new_counter(
            "app_tpu_bucket_hits_total",
            "executes per (model, bucket) — the observed bucket ladder fit")
        metrics.new_counter(
            "app_tpu_flush_total",
            "dynamic-batcher flushes by cause (full|timer) and model")
        metrics.new_histogram(
            "app_tpu_batch_fill",
            "flushed batch size / max_batch per flush",
            (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        metrics.new_histogram(
            "app_tpu_step_phase_seconds",
            "device-step phase split: serialize | stage | upload | enqueue "
            "| device_wait (host_prep replaces the first three with "
            "EXEC_STAGING=0)",
            (0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3))
        # zero-copy data plane (ISSUE 9): every host→device transfer —
        # staged dispatch uploads, coalesced tick inputs, adopted KV —
        # lands here, so the bench's relay gap is attributable per path
        metrics.new_updown_counter(
            "app_tpu_h2d_bytes_total",
            "host→device bytes shipped, per path "
            "(dispatch|rows|coalesced|mask|kv)")
        metrics.new_histogram(
            "app_tpu_h2d_seconds",
            "host→device transfer wall time, per path "
            "(dispatch|rows|coalesced|mask|kv)",
            (0.00003, 0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1))
        # prefix-KV reuse catalog (ISSUE 4): radix-cache hit rates and the
        # prompt tokens whose prefill FLOPs the cache avoided
        metrics.new_counter(
            "app_tpu_prefix_lookup_total",
            "prefix-cache lookups by result (hit|partial|miss)")
        metrics.new_updown_counter(
            "app_tpu_prefix_tokens_saved_total",
            "prompt tokens served from cached prefix KV instead of prefill")
        metrics.new_gauge(
            "app_tpu_prefix_cache_occupancy",
            "prefix-KV page pool: used pages / total pages")
        # unified paged KV catalog (ISSUE 6): one page pool backs prefill
        # output, the prefix cache, and decode — pool pressure and the
        # raggedness of what slots actually hold
        metrics.new_gauge("app_tpu_kv_pages_used",
                          "KV page pool: pages currently referenced")
        metrics.new_gauge("app_tpu_kv_pages_capacity",
                          "KV page pool: total pages in the pool")
        metrics.new_updown_counter(
            "app_tpu_kv_pages_written_total",
            "pool pages written by prefill/publish scatters — a prefix "
            "hit admits with zero new writes")
        metrics.new_counter(
            "app_tpu_kv_pages_stalled_total",
            "page allocations that failed after reclaim (admission "
            "backpressure / decode-growth stalls)")
        metrics.new_gauge(
            "app_tpu_kv_ragged_fill_ratio",
            "live tokens / (pages held x page size) across decoding "
            "slots — how ragged the paged KV actually is")
        metrics.new_counter(
            "app_tpu_attn_kernel_total",
            "decode/verify dispatches per attention path "
            "(ragged|gather|dense) — which formulation served the tick")
        # speculative decode catalog (ISSUE 7): draft-verify acceptance —
        # goodput comes from accepted draft tokens, so the acceptance rate
        # and the adaptive gamma it drives are the first dashboards to read
        metrics.new_updown_counter(
            "app_tpu_spec_proposed_total",
            "draft tokens proposed to the target verify step, per model")
        metrics.new_updown_counter(
            "app_tpu_spec_accepted_total",
            "draft tokens the target verify step accepted, per model")
        metrics.new_gauge(
            "app_tpu_spec_acceptance_rate",
            "accepted/proposed draft tokens over the adaptive-gamma window")
        metrics.new_gauge(
            "app_tpu_spec_gamma",
            "speculative draft length: per-tick gamma rung and the "
            "adaptive cap it is chosen under")
        # multi-model SLO-class scheduling catalog (ISSUE 7): weighted-fair
        # admission by deadline class, per-class shed, model lifecycle
        metrics.new_updown_counter(
            "app_tpu_sched_tokens_total",
            "generated tokens per (model, SLO class) — per-class goodput")
        metrics.new_counter(
            "app_tpu_sched_shed_total",
            "admissions shed at overflow, per (model, SLO class) — "
            "shedding is strictly within-class (newest first) before "
            "any cross-class impact")
        metrics.new_gauge(
            "app_tpu_admission_queue_depth",
            "admission backlog (pending + overflow) per (model, SLO class)")
        metrics.new_gauge(
            "app_tpu_model_state",
            "registry lifecycle per model: 0 LOADING, 1 WARMING, 2 READY, "
            "3 DRAINING, 4 UNLOADED")
        metrics.new_counter(
            "app_tpu_model_fallback_total",
            "requests routed to a fallback model (by source model and "
            "fallback taken) — non-zero means degraded or non-READY "
            "routing is active")
        # disaggregated serving catalog (ISSUE 8): the prefill→decode KV
        # handoff — how long the wire leg takes, how many bytes it ships,
        # and how many migrated requests each decode replica admitted
        metrics.new_histogram(
            "app_tpu_kv_transfer_seconds",
            "prefill→decode KV handoff wall time (pack + wire + unpack), "
            "by transport (inproc|http)",
            (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10))
        metrics.new_updown_counter(
            "app_tpu_kv_transfer_bytes_total",
            "KV bytes adopted from remote prefill, per model")
        metrics.new_counter(
            "app_tpu_kv_adoptions_total",
            "migrated requests whose KV was admitted as page-table "
            "entries (zero decode-side prefill), per model")
        metrics.new_gauge(
            "app_tpu_replica_state",
            "cluster replica lifecycle per (replica, role): 2 READY, "
            "3 DRAINING — same encoding as app_tpu_model_state")
        metrics.new_gauge(
            "app_tpu_replica_inflight",
            "router-level in-flight requests per replica — what drain "
            "waits on")
        # fleet observability catalog (ISSUE 10): handoff-expiry loss,
        # device-time attribution, and the hbmz reconciliation gauges
        metrics.new_counter(
            "app_tpu_kv_handoff_expired_total",
            "packed KV handoffs dropped unclaimed from the prefill "
            "replica's table, by reason (expired = TTL lapsed, evicted = "
            "capacity pressure) — each one is a wasted prompt forward")
        metrics.new_updown_counter(
            "app_tpu_device_seconds_total",
            "dispatch→publish device step wall time attributed per "
            "(model, SLO class), split evenly across a step's "
            "participants — attribution, not utilization: pipelined "
            "ticks overlap")
        metrics.new_gauge(
            "app_tpu_hbm_attributed_bytes",
            "device bytes the serving stack accounts for (params + KV "
            "page pool + staging slabs)")
        metrics.new_gauge(
            "app_tpu_hbm_unattributed_bytes",
            "backend bytes_in_use minus attributed bytes — XLA "
            "temporaries, executables, fragmentation; watch its growth")
        # fleet control plane catalog (ISSUE 12): prefix-affinity routing,
        # live decode→decode migration, and the cron autoscaler
        metrics.new_counter(
            "app_tpu_fleet_route_total",
            "decode routing decisions by result (affinity = longest "
            "resident prefix won, fallback = least-inflight pick)")
        metrics.new_histogram(
            "app_tpu_fleet_affinity_pages",
            "resident-prefix depth (pages) of each affinity-routed "
            "request — how much prefill the fleet index saved",
            (1, 2, 4, 8, 16, 32, 64))
        metrics.new_counter(
            "app_tpu_fleet_migrations_total",
            "live decode→decode session migrations by result (ok|error)")
        metrics.new_histogram(
            "app_tpu_fleet_migration_seconds",
            "migration downtime: source export start → target adopt done "
            "(the client stream's splice gap)",
            (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10))
        metrics.new_counter(
            "app_tpu_fleet_autoscale_total",
            "autoscaler decisions by result (up|down|hold|cooldown|"
            "compile_guard|overlap)")
        metrics.new_gauge(
            "app_tpu_fleet_decode_replicas",
            "READY decode-serving replicas the autoscaler last observed")
        # online operating-point auto-tuner (ISSUE 19): guarded cron
        # controller retuning serving knobs from shadow-replay scores
        metrics.new_counter(
            "app_tpu_autotune_total",
            "auto-tuner decisions by result (applied|rejected|"
            "rolled_back|hold|proposed|probation|no_trace|cooldown|"
            "compile_guard|overlap|refused_brownout|refused_fast_burn|"
            "rollback_blocked|probation_ok)")
        metrics.new_gauge(
            "app_tpu_autotune_score",
            "shadow-replay score of the last APPLIED operating point "
            "(deterministic goodput-per-cost proxy over the recorded "
            "trace)")
        metrics.new_gauge(
            "app_tpu_autotune_generation",
            "operating-point generation counter on the engine — bumps "
            "on every guarded apply, including rollbacks")
        metrics.new_counter(
            "app_tpu_engine_compiles_total",
            "engine-side executable compiles per (cls, model): cls is "
            "warmup (charged inside warmup/prewarm) or serving (a "
            "jit-cache miss on the hot path — the recompile-storm "
            "signal the auto-tuner guard reads)")
        # chaos plane catalog (ISSUE 14): seeded fault injection and the
        # recovery machinery it exercises — retries, hedges, circuit
        # trials, resumable decode, quarantine, and the brownout ladder
        metrics.new_counter(
            "app_tpu_fault_injected_total",
            "seeded faults the FAULT_PLAN actually fired, by site — "
            "zero outside chaos runs")
        metrics.new_counter(
            "app_tpu_disagg_retry_total",
            "disaggregated-serving retries by leg (prefill|fetch) — "
            "each one is a transient failure the budget absorbed")
        metrics.new_counter(
            "app_tpu_disagg_hedge_total",
            "hedged backup dispatches by leg — the primary blew the "
            "hedge deadline and an idempotent backup raced it")
        metrics.new_counter(
            "app_tpu_circuit_state_total",
            "circuit-breaker transitions by state entered "
            "(open|half_open|closed) — half_open admits one trial "
            "in flight, its outcome closes or re-opens")
        metrics.new_gauge(
            "app_tpu_brownout_level",
            "brownout ladder rung per role: 0 healthy, 1 shed batch, "
            "2 cap speculation, 3 speculation off")
        metrics.new_counter(
            "app_tpu_slot_quarantine_total",
            "poisoned slots excised mid-tick per (model, reason) — "
            "reason is grammar (walker raised) or nan_logits "
            "(out-of-vocab token ids); the rest of the batch proceeds")
        metrics.new_counter(
            "app_tpu_adopt_dedup_total",
            "replayed KV adoptions answered from the dedupe ledger, "
            "per model — a retry/hedge landed twice and was deduped")
        # continuous telemetry plane (ISSUE 16): change-point detector
        # verdicts over the in-process time-series store — one increment
        # per anomaly *raised* (not per sample), so the counter rate is
        # the replica's regime-change rate, not its sampling rate
        metrics.new_counter(
            "app_tpu_anomaly_total",
            "telemetry anomalies raised by the change-point detector, "
            "per (signal, direction) — a goodput cliff or padding spike "
            "that survived the detector's hysteresis")
        metrics.new_counter(
            "app_tpu_fleet_resume_total",
            "mid-stream decode resumes by result (ok|no_ctx|budget|"
            "exhausted|no_replica|error) — ok means the stream was "
            "rebuilt from prompt + emitted tokens on a live replica")
        # workload capture & roofline attribution (ISSUE 17): the
        # shape-only traffic recorder's admission pulse, and the
        # per-executable-family twin of app_tpu_device_seconds_total —
        # same elapsed windows, keyed by compiled executable instead of
        # SLO class, so the two totals agree by construction
        metrics.new_counter(
            "app_tpu_workload_events_total",
            "requests admitted into the workload recorder's shape-only "
            "ring, per (model, SLO class) — token lengths and timings "
            "only, never token content")
        metrics.new_updown_counter(
            "app_tpu_executable_device_seconds_total",
            "dispatch→publish device step wall time per (model, "
            "compiled executable family) — the roofline-attribution "
            "twin of app_tpu_device_seconds_total; their totals match")
        metrics.new_updown_counter("app_http_inflight",
                                   "inbound HTTP requests currently in flight")
        metrics.new_histogram("app_cron_duration", "cron job run time (s)",
                              (0.001, 0.01, 0.1, 1, 10, 60, 300))
        metrics.new_counter("app_cron_runs_total",
                            "cron job runs by job name and result")
        # async-task discipline (ISSUE 5 / graftcheck GT002): every
        # fire-and-forget spawn goes through gofr_tpu.aio.spawn_logged,
        # which counts tasks that died with an escaped exception here —
        # a crashed subscriber/serve/cron loop becomes a dashboard line
        metrics.new_counter(
            "app_async_task_failures_total",
            "background asyncio tasks that died with an escaped "
            "exception, by task name")
        # async inference lane (ISSUE 11): pub/sub batch generation jobs
        # into the WFQ batch class — job outcomes, host-side in-flight
        # bound, and whether backpressure currently has the lane paused
        metrics.new_counter(
            "app_tpu_batch_lane_jobs_total",
            "batch-lane jobs by outcome (ok|dead_letter) — a dead_letter "
            "is a committed job whose error envelope went to the "
            "dead-letter topic")
        metrics.new_gauge(
            "app_tpu_batch_lane_inflight",
            "batch-lane jobs currently generating, per topic (bounded by "
            "BATCH_LANE_MAX_INFLIGHT)")
        metrics.new_gauge(
            "app_tpu_batch_lane_paused",
            "1 while backpressure has the lane's consumer paused, per "
            "topic")

    # -- outbound services (container.go:150-152) ---------------------------
    def add_http_service(self, name: str, service: Any) -> None:
        self.services[name] = service

    def get_http_service(self, name: str) -> Any:
        return self.services.get(name)

    # -- aggregated health (container/health.go:8-66) -----------------------
    def health(self) -> Dict[str, Any]:
        details: Dict[str, Any] = {
            "name": self.app_name,
            "version": self.app_version,
            "framework": FRAMEWORK_VERSION,
            "uptime_seconds": round(time.time() - self._start_time, 3),
        }
        statuses = []
        for name in ("sql", "redis", "pubsub", "mongo", "cassandra",
                     "clickhouse", "tpu", "cluster"):
            source = getattr(self, name)
            if source is None:
                continue
            try:
                health = source.health_check()
            except Exception as exc:
                health = {"status": "DOWN", "details": {"error": repr(exc)}}
            details[name] = health
            statuses.append(health.get("status", "DOWN"))
        for name, service in self.services.items():
            try:
                health = service.health_check()
            except Exception as exc:
                health = {"status": "DOWN", "details": {"error": repr(exc)}}
            details.setdefault("services", {})[name] = health
            statuses.append(health.get("status", "DOWN"))
        details["status"] = "DEGRADED" if "DOWN" in statuses else "UP"
        # SLO watchdog override: a replica whose rolling-window attainment
        # or p99 TTFT crossed its thresholds reports DEGRADED so load
        # balancers drain it even while every datasource is UP
        if self.watchdog is not None:
            details["watchdog"] = self.watchdog.statusz()
            if self.watchdog.state == "DEGRADED":
                details["status"] = "DEGRADED"
        return details

    async def close(self) -> None:
        for name in ("sql", "redis", "pubsub", "tpu"):
            source = getattr(self, name)
            closer = getattr(source, "close", None)
            if closer is not None:
                try:
                    result = closer()
                    if hasattr(result, "__await__"):
                        await result
                except Exception:
                    pass
        # flush spans finished during shutdown (tracer.shutdown drains the
        # export queue before closing the exporter)
        try:
            self.tracer.shutdown()
        except Exception:
            pass


def new_mock_container(config: Optional[Dict[str, str]] = None) -> Container:
    """One-call test fixture: silent logger + in-memory everything
    (reference: container/mock_container.go:21-42 ``NewMockContainer``)."""
    container = Container(config=MapConfig(config or {}),
                         logger=new_silent_logger())
    container.register_framework_metrics()
    from gofr_tpu.datasource.file import LocalFileSystem
    from gofr_tpu.datasource.pubsub.inmem import InMemoryBroker
    from gofr_tpu.datasource.redisx import InMemoryRedis
    from gofr_tpu.datasource.sql import new_sql
    container.pubsub = InMemoryBroker(container.logger, container.metrics,
                                      tracer=container.tracer)
    # unsandboxed: tests hand the fixture absolute tmp paths; production
    # Container.create keeps the sandboxed default
    container.file = LocalFileSystem(container.logger, sandbox=False)
    container.redis = InMemoryRedis(container.logger, container.metrics)
    container.sql = new_sql(MapConfig({"DB_DIALECT": "sqlite",
                                       "DB_NAME": ":memory:"}),
                            container.logger, container.metrics)
    return container
