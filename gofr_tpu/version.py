"""Framework version stamp.

Mirrors the reference's ``pkg/gofr/version`` (version/version.go:3): a single
constant stamped into logs, metrics resources, and tracer names.
"""

FRAMEWORK_VERSION = "0.1.0"
