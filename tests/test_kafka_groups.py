"""Kafka consumer-group coordination: partition split across members,
rebalance on member death, generation-fenced commits (VERDICT r3 missing
#2 — reference semantics: kafka.go:167-220 per-topic consumer-group
reader, 234-242 group-based horizontal scaling)."""

import asyncio
import struct
import time

import pytest

from gofr_tpu.config import MapConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.datasource.pubsub.kafka import (
    KafkaClient,
    KafkaRebalance,
    decode_member_assignment,
    range_assign,
)
from tests.test_pubsub_wire import FakeKafkaBroker


# -- range assignment math ---------------------------------------------------

def test_range_assign_even_split():
    out = range_assign({"a": ["t"], "b": ["t"]}, {"t": [0, 1, 2, 3]})
    assert out["a"]["t"] == [0, 1]
    assert out["b"]["t"] == [2, 3]


def test_range_assign_uneven_extras_to_first():
    out = range_assign({"a": ["t"], "b": ["t"], "c": ["t"]},
                       {"t": [0, 1, 2, 3, 4]})
    assert out["a"]["t"] == [0, 1]
    assert out["b"]["t"] == [2, 3]
    assert out["c"]["t"] == [4]


def test_range_assign_per_topic_subscribers():
    out = range_assign({"a": ["x"], "b": ["x", "y"]},
                       {"x": [0, 1], "y": [0]})
    assert out["a"] == {"x": [0]}
    assert out["b"] == {"x": [1], "y": [0]}


def test_range_assign_more_members_than_partitions():
    out = range_assign({"a": ["t"], "b": ["t"]}, {"t": [0]})
    assert out["a"]["t"] == [0]
    assert "t" not in out["b"]


# -- helpers -----------------------------------------------------------------

def _make_client(broker, name):
    container = new_mock_container()
    return KafkaClient(
        MapConfig({"PUBSUB_BROKER": f"127.0.0.1:{broker.port}",
                   "CONSUMER_ID": "workers",
                   "APP_NAME": name,
                   "KAFKA_FETCH_MAX_WAIT_MS": "20",
                   "KAFKA_HEARTBEAT_INTERVAL_MS": "100"}),
        container.logger, container.metrics)


def _wait_stable(broker, group="workers", members=2, timeout=10.0):
    """Wait until the coordinator reports a stable generation with the
    expected member count; returns {member_id: {topic: [partitions]}}."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with broker.gcond:
            state = broker.groups.get(group)
            if (state and state["state"] == "stable"
                    and len(state["members"]) == members
                    and len(state["assignments"]) == members):
                return {mid: decode_member_assignment(blob)
                        for mid, blob in state["assignments"].items()}
        time.sleep(0.05)
    raise AssertionError(f"group never stabilized with {members} members")


async def _drain(client, topic, sink, expect, deadline=20.0, grace=0.6):
    """Consume + commit until ``expect`` messages arrived, then keep
    listening ``grace`` seconds longer so duplicates would still be
    caught; ``deadline`` bounds the whole call on a slow machine.

    Never cancels ``subscribe``: a cancelled ``wait_for`` abandons the
    executor thread blocked on queue.get, and that orphaned get would
    swallow the NEXT real message (the source of this module's original
    flakiness under load). Instead a timer feeds the queue a ``None``
    sentinel and the subscribe returns normally."""
    loop = asyncio.get_running_loop()
    end = time.monotonic() + deadline
    while True:
        remaining = end - time.monotonic()
        if remaining <= 0:
            return
        timeout = grace if len(sink) >= expect else remaining

        def poke():
            q = client._queues.get(topic)
            if q is not None:
                q.put_nowait(None)

        handle = loop.call_later(timeout, poke)
        message = await client.subscribe(topic)
        handle.cancel()
        if message is None:            # sentinel: idle window elapsed
            return
        sink.append(message)
        message.commit()


# -- end-to-end group behaviour ---------------------------------------------

def test_two_members_split_partitions_no_double_processing():
    """Two clients in one group must split a 4-partition topic and consume
    each message exactly once between them (the r3 static mode would
    double-process everything)."""
    broker = FakeKafkaBroker(join_window=0.5)
    broker.partitions["jobs"] = 4
    for p in range(4):
        broker.logs[("jobs", p)] = []
    c1 = _make_client(broker, "c1")
    c2 = _make_client(broker, "c2")
    got1, got2 = [], []

    async def scenario():
        task1 = asyncio.ensure_future(_drain(c1, "jobs", got1, expect=6))
        task2 = asyncio.ensure_future(_drain(c2, "jobs", got2, expect=6))
        assignments = await asyncio.get_running_loop().run_in_executor(
            None, _wait_stable, broker)
        # the split itself: disjoint, covering all four partitions
        partition_sets = [set(a.get("jobs", []))
                          for a in assignments.values()]
        assert partition_sets[0] & partition_sets[1] == set()
        assert partition_sets[0] | partition_sets[1] == {0, 1, 2, 3}
        assert all(len(s) == 2 for s in partition_sets)
        for p in range(4):
            for i in range(3):
                broker.logs[("jobs", p)].append(
                    (b"", f"p{p}-m{i}".encode()))
        await asyncio.gather(task1, task2)

    try:
        asyncio.run(scenario())
        values = [m.value for m in got1 + got2]
        expected = {f"p{p}-m{i}".encode() for p in range(4)
                    for i in range(3)}
        assert len(values) == 12, values          # no duplication
        assert set(values) == expected            # no loss
        assert got1 and got2                      # both members worked
        # each member saw only its assigned partitions
        parts1 = {m.metadata["partition"] for m in got1}
        parts2 = {m.metadata["partition"] for m in got2}
        assert parts1 & parts2 == set()
    finally:
        c1.close()
        c2.close()
        broker.stop()


def test_member_death_survivor_reclaims_partitions():
    """When one member dies its partitions move to the survivor, which
    resumes from the committed offsets — no message loss, no
    reprocessing of committed messages (kafka.go:234-242 analog)."""
    broker = FakeKafkaBroker(join_window=0.5)
    broker.partitions["jobs"] = 4
    for p in range(4):
        broker.logs[("jobs", p)] = []
    c1 = _make_client(broker, "c1")
    c2 = _make_client(broker, "c2")
    phase1, phase2 = [], []

    async def scenario():
        task1 = asyncio.ensure_future(_drain(c1, "jobs", phase1, expect=2))
        task2 = asyncio.ensure_future(_drain(c2, "jobs", phase2, expect=2))
        await asyncio.get_running_loop().run_in_executor(
            None, _wait_stable, broker)
        for p in range(4):
            broker.logs[("jobs", p)].append((b"", f"first-p{p}".encode()))
        await asyncio.gather(task1, task2)   # both drain + commit phase 1

        # kill c1: its sockets die, the coordinator evicts it and the
        # survivor rebalances to own all four partitions
        c1.close()
        await asyncio.get_running_loop().run_in_executor(
            None, _wait_stable, broker, "workers", 1)
        for p in range(4):
            broker.logs[("jobs", p)].append((b"", f"second-p{p}".encode()))
        survivor = []
        await _drain(c2, "jobs", survivor, expect=4)
        return survivor

    try:
        survivor = asyncio.run(scenario())
        firsts = [m.value for m in phase1 + phase2]
        assert set(firsts) == {f"first-p{p}".encode() for p in range(4)}
        assert phase1 and phase2             # both participated pre-death
        # the survivor picked up ALL partitions' new messages, exactly
        # once, without replaying the committed phase-1 messages
        assert sorted(m.value for m in survivor) == \
            sorted(f"second-p{p}".encode() for p in range(4))
        assert {m.metadata["partition"] for m in survivor} == {0, 1, 2, 3}
    finally:
        c2.close()
        broker.stop()


def test_stale_generation_commit_is_fenced():
    """A commit carrying a superseded generation must be rejected by the
    coordinator and surface as KafkaRebalance — a zombie member cannot
    clobber the new owner's progress."""
    broker = FakeKafkaBroker(join_window=0.3)
    broker.partitions["jobs"] = 2
    broker.logs[("jobs", 0)] = [(b"", b"m0")]
    broker.logs[("jobs", 1)] = []
    c1 = _make_client(broker, "c1")
    held = []

    async def scenario():
        message = await asyncio.wait_for(c1.subscribe("jobs"), 10.0)
        held.append(message)
        # second member joins → generation bumps past the held message's
        c2 = _make_client(broker, "c2")
        try:
            consume = asyncio.ensure_future(
                _drain(c2, "jobs", [], expect=10**6, deadline=30.0))
            await asyncio.get_running_loop().run_in_executor(
                None, _wait_stable, broker)
            with pytest.raises(KafkaRebalance):
                held[0].commit()
        finally:
            # end the drain via its own sentinel — cancelling would orphan
            # an executor thread blocked on queue.get and hang asyncio.run
            q = c2._queues.get("jobs")
            if q is not None:
                q.put_nowait(None)
            await consume
            c2.close()

    try:
        asyncio.run(scenario())
    finally:
        c1.close()
        broker.stop()


def test_static_mode_fetches_all_partitions():
    """KAFKA_GROUP_MODE=static keeps the r3 behaviour: one consumer sees
    every partition without any group coordination."""
    broker = FakeKafkaBroker()
    broker.partitions["jobs"] = 3
    for p in range(3):
        broker.logs[("jobs", p)] = [(b"", f"p{p}".encode())]
    container = new_mock_container()
    client = KafkaClient(
        MapConfig({"PUBSUB_BROKER": f"127.0.0.1:{broker.port}",
                   "CONSUMER_ID": "solo",
                   "KAFKA_GROUP_MODE": "static",
                   "KAFKA_FETCH_MAX_WAIT_MS": "20"}),
        container.logger, container.metrics)
    got = []

    async def scenario():
        await _drain(client, "jobs", got, expect=3)

    try:
        asyncio.run(scenario())
        assert sorted(m.value for m in got) == [b"p0", b"p1", b"p2"]
        with broker.gcond:
            assert "solo" not in broker.groups   # no coordinator traffic
    finally:
        client.close()
        broker.stop()
