"""Pallas decode-attention kernel numerics vs the dense reference
(interpret mode on CPU), across fill levels, GQA groupings, and the
zero-length fresh-slot edge."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import decode_attention_cached
from gofr_tpu.ops.pallas import flash_decode_attention


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32) * 0.3


@pytest.mark.parametrize("q_heads,kv_heads", [(4, 4), (8, 2)])
@pytest.mark.parametrize("fills", [[0, 1, 64, 200], [128, 512, 37, 300]])
def test_kernel_matches_dense(q_heads, kv_heads, fills):
    batch, t_max, head_dim = 4, 512, 128
    keys = jax.random.split(jax.random.PRNGKey(0), 5)
    q = _rand(keys[0], batch, 1, q_heads, head_dim)
    k_cache = _rand(keys[1], batch, t_max, kv_heads, head_dim)
    v_cache = _rand(keys[2], batch, t_max, kv_heads, head_dim)
    k_new = _rand(keys[3], batch, kv_heads, head_dim)
    v_new = _rand(keys[4], batch, kv_heads, head_dim)
    cache_len = jnp.asarray(fills, jnp.int32)

    dense = decode_attention_cached(q, k_cache, v_cache, k_new, v_new,
                                    cache_len)
    kernel = flash_decode_attention(q, k_cache, v_cache, k_new, v_new,
                                    cache_len, interpret=True)
    np.testing.assert_allclose(np.asarray(kernel), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_untileable_shapes_fall_back():
    batch, t_max, heads, head_dim = 2, 32, 4, 16   # tiny preset geometry
    keys = jax.random.split(jax.random.PRNGKey(1), 5)
    q = _rand(keys[0], batch, 1, heads, head_dim)
    k_cache = _rand(keys[1], batch, t_max, heads, head_dim)
    v_cache = _rand(keys[2], batch, t_max, heads, head_dim)
    k_new = _rand(keys[3], batch, heads, head_dim)
    v_new = _rand(keys[4], batch, heads, head_dim)
    cache_len = jnp.asarray([0, 17], jnp.int32)
    out = flash_decode_attention(q, k_cache, v_cache, k_new, v_new,
                                 cache_len)
    ref = decode_attention_cached(q, k_cache, v_cache, k_new, v_new,
                                  cache_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)
