"""GT005 metric discipline: the static metric-name lint as a graftcheck
rule.

Formerly ``scripts/lint_metrics.py`` (that script is now a thin shim over
this module). The checks are unchanged:

1. every literal metric name matches the OpenMetrics charset
   ``[a-zA-Z_][a-zA-Z0-9_]*``;
2. every name carries the ``app_`` namespace prefix, except the
   intentionally-unprefixed process runtime gauges in
   ``ALLOW_UNPREFIXED``;
3. every observed name is registered somewhere in the scanned tree — a
   typo'd observation is silently dropped at runtime by Manager's
   error-log-and-continue policy, so it must fail CI instead;
4. every registered ``app_``-prefixed name appears in the metrics
   catalog (``docs/quick-start/observability.md`` by default) — the
   docs-drift gate.

Checks 1-2 are per-file findings (pragma-suppressible); 3-4 need the
whole tree and run in ``finalize``.
"""

from __future__ import annotations

import argparse
import ast
import pathlib
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from gofr_tpu.analysis.engine import (
    Finding,
    ModuleInfo,
    ROOT,
    Rule,
)

DOCS_CATALOG = ROOT / "docs" / "quick-start" / "observability.md"

NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# any app_-namespaced token in the docs counts as "documented" — rows in
# the catalog table, prose mentions, and code samples all qualify
DOC_NAME_RE = re.compile(r"\bapp_[a-zA-Z0-9_]+\b")

# process-runtime gauges predating the app_ namespace convention; kept
# unprefixed for parity with common node-exporter dashboards
ALLOW_UNPREFIXED = {
    "threads_total",
    "memory_rss_bytes",
    "gc_objects",
    "uptime_seconds",
}

REGISTER_METHODS = {
    "new_counter",
    "new_updown_counter",
    "new_histogram",
    "new_gauge",
}
OBSERVE_METHODS = {
    "increment_counter",
    "delta_updown_counter",
    "record_histogram",
    "set_gauge",
}


def _metric_calls(tree: ast.AST):
    """Yield (method, name, lineno) for metrics calls with a literal
    first argument. Non-literal names (dynamic dispatch) are skipped —
    the lint is intentionally conservative."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        method = func.attr
        if method not in REGISTER_METHODS | OBSERVE_METHODS:
            continue
        if not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            yield method, first.value, node.lineno


class MetricDisciplineRule(Rule):
    rule_id = "GT005"
    title = "metric-discipline"
    severity = "error"
    cross_file = True  # finalize joins registered vs observed repo-wide

    def __init__(self, docs_catalog: Optional[pathlib.Path] = None):
        self.docs_catalog = pathlib.Path(docs_catalog or DOCS_CATALOG)
        self._registered: Set[str] = set()
        self._observed: List[Tuple[str, int, str]] = []  # (path, line, name)

    def config_fingerprint(self) -> str:
        # findings depend on the docs catalog, not just scanned sources
        try:
            import hashlib
            digest = hashlib.sha256(
                self.docs_catalog.read_bytes()).hexdigest()[:16]
        except OSError:
            digest = "missing"
        return f"{self.rule_id}:{digest}"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for method, name, lineno in _metric_calls(module.tree):
            if not NAME_RE.match(name):
                findings.append(Finding(
                    rule=self.rule_id, path=module.relpath, line=lineno,
                    message=(f"metric {name!r} violates the OpenMetrics "
                             f"charset [a-zA-Z_][a-zA-Z0-9_]*"),
                    key=f"charset {name}"))
            if not name.startswith("app_") and name not in ALLOW_UNPREFIXED:
                findings.append(Finding(
                    rule=self.rule_id, path=module.relpath, line=lineno,
                    message=(f"metric {name!r} missing the app_ namespace "
                             f"prefix (or add it to ALLOW_UNPREFIXED)"),
                    key=f"prefix {name}"))
            if method in REGISTER_METHODS:
                self._registered.add(name)
            else:
                self._observed.append((module.relpath, lineno, name))
        return findings

    def finalize(self, modules) -> Iterable[Finding]:
        findings: List[Finding] = []
        for rel, lineno, name in self._observed:
            if name not in self._registered:
                findings.append(Finding(
                    rule=self.rule_id, path=rel, line=lineno,
                    message=(f"metric {name!r} is observed but never "
                             f"registered — Manager drops it at runtime"),
                    key=f"unregistered {name}"))
        try:
            documented = set(DOC_NAME_RE.findall(
                self.docs_catalog.read_text(encoding="utf-8")))
        except OSError as exc:
            docs_rel = self._docs_rel()
            return findings + [Finding(
                rule=self.rule_id, path=docs_rel, line=1,
                message=f"unreadable metrics catalog: {exc}",
                key="catalog unreadable")]
        docs_rel = self._docs_rel()
        for name in sorted(self._registered):
            if name.startswith("app_") and name not in documented:
                findings.append(Finding(
                    rule=self.rule_id, path=docs_rel, line=1,
                    message=(f"metric {name!r} is registered in source "
                             f"but missing from the metrics catalog — "
                             f"document it (or remove the registration)"),
                    key=f"undocumented {name}"))
        return findings

    def _docs_rel(self) -> str:
        try:
            return self.docs_catalog.resolve().relative_to(ROOT).as_posix()
        except ValueError:
            return str(self.docs_catalog)

    @property
    def registered_count(self) -> int:
        return len(self._registered)


def main(argv=None) -> int:
    """Standalone entry preserving the historical ``scripts/lint_metrics.py``
    interface: same flags, same messages, exit 0 clean / 1 violations."""
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--docs", type=pathlib.Path, default=DOCS_CATALOG,
        help="metrics catalog to check app_ names against "
             "(default: docs/quick-start/observability.md)")
    opts = parser.parse_args(argv)

    from gofr_tpu.analysis import engine
    rule = MetricDisciplineRule(docs_catalog=opts.docs)
    report = engine.run(paths=[engine.PACKAGE], rules=[rule], baseline={})
    problems = [f.render().replace(f"{rule.rule_id} ", "", 1)
                for f in report.new_findings] + report.parse_errors
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(f"lint_metrics: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print(f"lint_metrics: OK ({rule.registered_count} registered metric "
          f"names)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
