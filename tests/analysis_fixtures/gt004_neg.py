"""GT004 negative fixture: trace-safe effects and static-only branches.

Parsed by graftcheck in tests, never imported.
"""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def debug_printed(x):
    jax.debug.print("x = {}", x)
    return x * 2


@jax.jit
def structural(x):
    if x is None:
        return jnp.zeros((4,))
    if x.ndim == 2:
        return x.sum(axis=-1)
    return x


@functools.partial(jax.jit, static_argnames=("mode",))
def static_branch(x, mode):
    if mode == "fast":
        return x
    return x * 2


def host_side(logger, x):
    # not a traced body: loggers and branches are fine out here
    logger.info("dispatching %s", x)
    if x:
        return 1
    return 0
