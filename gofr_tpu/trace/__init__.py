from gofr_tpu.trace.tracer import (
    Span,
    Tracer,
    current_span,
    extract_traceparent,
    format_traceparent,
    new_tracer,
)

__all__ = [
    "Span",
    "Tracer",
    "current_span",
    "extract_traceparent",
    "format_traceparent",
    "new_tracer",
]
