"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

The image boots with ``JAX_PLATFORMS=axon`` (one real TPU chip behind a
relay); unit tests must instead exercise the multi-chip sharding paths
(gofr_tpu.parallel) on a virtual 8-device CPU mesh — the "miniredis of
XLA" strategy from SURVEY.md §4.  ``jax.config.update`` beats the env var
even though the axon sitecustomize imported jax at interpreter start.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def mock_container():
    from gofr_tpu.container import new_mock_container
    return new_mock_container()


@pytest.fixture(scope="session")
def cpu_mesh():
    """2×4 dp×tp mesh over the 8 virtual CPU devices."""
    return jax.make_mesh((2, 4), ("dp", "tp"))


@pytest.fixture(scope="session")
def graftcheck_repo_scan(tmp_path_factory):
    """One cold full-repo graftcheck scan, shared by every test that
    needs a no-baseline repo report or a warm cache — the scan is the
    single most expensive fixture in the suite, so pay it exactly once.
    Returns ``(cache_path, cold_report, cold_seconds)``; the cache file
    is a throwaway so the repo's own ``.graftcheck_cache.json`` (and the
    committed baseline) stay untouched."""
    import time as _time

    from gofr_tpu.analysis import engine
    from gofr_tpu.analysis.rules import default_rules

    cache = tmp_path_factory.mktemp("graftcheck") / "cache.json"
    t0 = _time.perf_counter()
    cold = engine.run(paths=[engine.PACKAGE], rules=default_rules(),
                      baseline={}, cache_path=cache)
    cold_secs = _time.perf_counter() - t0
    return cache, cold, cold_secs
