"""Workload capture & replay plane tests (ISSUE 17).

Load-bearing contracts:
- the TrafficRecorder ring is bounded (deque(maxlen)) under sustained
  traffic and stores shape only — never token ids or strings;
- exported traces round-trip through JSON bit-faithfully and a
  kind/version skew raises TraceVersionError instead of replaying;
- replay_trace is deterministic: two replays of the same trace through
  a live engine produce identical admitted-token counts, per-class
  outcome tallies, and digests;
- the xlaz suggested-ladder DP re-weights by recorded traffic shape
  when a recorder is attached (ladder_source flips);
- charge_device_time keeps the per-class aggregate and the
  per-executable family ledger in agreement by construction.
"""

import asyncio
import json

import jax
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.tpu.compile_ledger import ExecutableLedger, charge_device_time
from gofr_tpu.tpu.flightrecorder import RequestRecord
from gofr_tpu.tpu.generate import GenerationEngine
from gofr_tpu.tpu.workload import (TraceVersionError, TrafficRecorder,
                                   WorkloadTrace, _request_seed,
                                   _synth_prompt, load_trace,
                                   new_traffic_recorder, replay_trace)


class _Metrics:
    """Counts increment_counter / delta_updown_counter calls by name."""

    def __init__(self):
        self.counts = {}
        self.sums = {}

    def increment_counter(self, name, **labels):
        key = (name, tuple(sorted(labels.items())))
        self.counts[key] = self.counts.get(key, 0) + 1

    def delta_updown_counter(self, name, value, **labels):
        key = (name, tuple(sorted(labels.items())))
        self.sums[key] = self.sums.get(key, 0.0) + value

    def count(self, name):
        return sum(v for (n, _), v in self.counts.items() if n == name)

    def total(self, name):
        return sum(v for (n, _), v in self.sums.items() if n == name)


def _record(model="generate", prompt_len=8, budget=4):
    return RequestRecord(model=model, prompt_len=prompt_len, budget=budget)


def _admit_n(rec, n, prompt_len=8, cls="standard", start=100.0, step=0.01):
    records = []
    for i in range(n):
        record = _record(prompt_len=prompt_len)
        rec.admit(record, cls, now=start + i * step)
        records.append(record)
    return records


# -- recorder ring -----------------------------------------------------------
def test_ring_bounded_under_sustained_traffic():
    metrics = _Metrics()
    rec = TrafficRecorder(capacity=32, metrics=metrics)
    _admit_n(rec, 500)
    snap = rec.snapshot()
    assert snap["window_events"] == 32          # ring stayed bounded
    assert snap["admitted_total"] == 500        # totals kept counting
    assert metrics.count("app_tpu_workload_events_total") == 500
    # the batcher plane is bounded by the same capacity
    for i in range(500):
        rec.note_enqueue("classify", now=200.0 + i * 0.001)
    assert len(rec._enqueue_dt) == 32


def test_finish_closes_event_once():
    rec = TrafficRecorder(capacity=8)
    record = _record(prompt_len=5, budget=7)
    event = rec.admit(record, "interactive", now=10.0)
    assert record.wevent is event
    assert event.finish is None
    record.tokens = 7
    record.cached_prefix_len = 3
    record.status = "done"
    rec.finish(record)
    assert event.output_len == 7
    assert event.cached_prefix_len == 3
    assert event.finish == "done"
    assert record.wevent is None                # parked event cleared
    # second finish (e.g. a cancelled-then-drained race) is a no-op
    record.status = "cancelled"
    rec.finish(record)
    assert event.finish == "done"
    assert rec.snapshot()["finished_total"] == 1


def test_snapshot_mixes_and_prefix_reuse():
    rec = TrafficRecorder(capacity=64)
    for i, (cls, cached) in enumerate(
            [("interactive", 4), ("standard", 0), ("standard", 2),
             ("batch", 0)]):
        record = _record(prompt_len=8)
        rec.admit(record, cls, now=50.0 + i)
        record.tokens = 3
        record.cached_prefix_len = cached
        record.status = "done"
        rec.finish(record)
    snap = rec.snapshot()
    assert snap["class_mix"] == {"interactive": 1, "standard": 2, "batch": 1}
    assert snap["finish_mix"] == {"done": 4}
    reuse = snap["prefix_reuse"]
    assert reuse["requests_with_reuse"] == 2
    assert reuse["request_rate"] == 0.5
    assert reuse["token_rate"] == round(6 / 32, 4)
    assert snap["interarrival_s"]["mean"] == 1.0


def test_class_mix_cardinality_is_gated():
    rec = TrafficRecorder(capacity=4)
    for i in range(200):
        rec.admit(_record(), f"cls{i}", now=10.0 + i)
    mix = rec.snapshot()["class_mix"]
    assert len(mix) <= 65                       # _MAX_KEYS + "_other"
    assert mix["_other"] == 200 - (len(mix) - 1)


# -- trace export / import ---------------------------------------------------
def _finished_trace(n=6, prompt_len=9, cls="standard"):
    rec = TrafficRecorder(capacity=64)
    for i, record in enumerate(_admit_n(rec, n, prompt_len=prompt_len,
                                        cls=cls, step=0.005)):
        record.tokens = 3 + (i % 2)
        record.status = "done"
        rec.finish(record)
    return rec.export_trace()


def test_trace_round_trips_through_json():
    data = _finished_trace()
    trace = load_trace(json.dumps(data))       # string path
    again = load_trace(data)                   # dict path
    assert trace.version == 1
    assert len(trace.events) == 6
    for a, b in zip(trace.events, again.events):
        for field in ("dt_s", "cls", "model", "prompt_len", "budget",
                      "output_len", "deadline_ms", "cached_prefix_len",
                      "finish"):
            assert getattr(a, field) == getattr(b, field)
    event = trace.events[1]
    assert event.dt_s == 0.005
    assert event.model == "generate"
    assert event.cls == "standard"
    assert event.prompt_len == 9
    assert event.output_len == 4
    assert event.finish == "done"
    assert event.deadline_ms is None


def test_trace_version_and_kind_rejected_on_skew():
    data = _finished_trace()
    stale = dict(data, version=99)
    with pytest.raises(TraceVersionError):
        load_trace(stale)
    alien = dict(data, kind="some-other-payload")
    with pytest.raises(TraceVersionError):
        load_trace(alien)
    with pytest.raises(TraceVersionError):
        load_trace([1, 2, 3])
    # TraceVersionError is a ValueError — callers catching broadly still work
    assert issubclass(TraceVersionError, ValueError)


def test_synth_prompt_deterministic_and_in_vocab():
    a = _synth_prompt(3, 17, 256, seed=42)
    b = _synth_prompt(3, 17, 256, seed=42)
    assert a == b
    assert len(a) == 17
    assert all(1 <= t < 256 for t in a)        # never the pad id 0
    assert _synth_prompt(4, 17, 256, seed=42) != a
    assert _request_seed(5, 7) == _request_seed(5, 7)
    assert _request_seed(5, 7) != _request_seed(6, 7)


# -- config factory ----------------------------------------------------------
class _Config:
    def __init__(self, values=None):
        self.values = values or {}

    def get(self, key, default=None):
        return self.values.get(key, default)

    def get_int(self, key, default=0):
        return int(self.values.get(key, default))


def test_new_traffic_recorder_knobs():
    assert new_traffic_recorder(_Config()).capacity == 2048
    assert new_traffic_recorder(
        _Config({"TRAFFIC_REC_CAPACITY": "64"})).capacity == 64
    assert new_traffic_recorder(
        _Config({"TRAFFIC_REC_ENABLED": "off"})) is None
    assert new_traffic_recorder(
        _Config({"TRAFFIC_REC_CAPACITY": "0"})) is None


# -- shared timing helper / executable ledger --------------------------------
def test_charge_device_time_totals_agree():
    """One elapsed charges both planes; their totals must be equal."""
    metrics = _Metrics()
    ledger = ExecutableLedger(metrics=metrics)
    device_seconds = {}
    charge_device_time(0.12, "llama", classes=["interactive", "standard"],
                       family="decode_paged[k=8,pw=16]",
                       device_seconds=device_seconds, metrics=metrics,
                       ledger=ledger)
    charge_device_time(0.03, "llama", classes=["standard"],
                       family="prefill[nb=1,b=16]",
                       device_seconds=device_seconds, metrics=metrics,
                       ledger=ledger)
    agg = sum(device_seconds.values())
    assert agg == pytest.approx(0.15)
    assert ledger.total_seconds("llama") == pytest.approx(agg)
    assert metrics.total("app_tpu_device_seconds_total") == \
        pytest.approx(metrics.total("app_tpu_executable_device_seconds_total"))
    # class split is even across participants
    assert device_seconds[("llama", "interactive")] == pytest.approx(0.06)
    assert device_seconds[("llama", "standard")] == pytest.approx(0.09)
    # executor path: family only, aggregate untouched
    charge_device_time(0.5, "classify", family="b32", ledger=ledger,
                       flops=1.0e9)
    assert ("classify", "b32") not in device_seconds
    assert ledger.total_seconds("classify") == pytest.approx(0.5)


def test_executable_ledger_roofline_and_bounds():
    ledger = ExecutableLedger(peak_flops=4.0e9, max_families=2)
    ledger.charge("m", "b8", 0.5, flops=1.0e9)
    ledger.charge("m", "b8", 0.5, flops=1.0e9)
    ledger.charge("m", "b16", 1.0)
    ledger.charge("m", "b32", 1.0)              # over the family cap
    snap = ledger.snapshot()
    assert snap["families"] == 2
    assert snap["dropped_families"] == 1
    top = snap["top"][0]
    assert top["family"] in ("b8", "b16")
    by_family = {row["family"]: row for row in snap["top"]}
    assert by_family["b8"]["dispatches"] == 2
    assert by_family["b8"]["achieved_flops_per_s"] == pytest.approx(2.0e9)
    assert by_family["b8"]["roofline_ratio"] == pytest.approx(0.5)
    assert by_family["b16"]["roofline_ratio"] is None
    assert sum(row["share"] for row in snap["top"]) == pytest.approx(1.0)


# -- engine integration: replay determinism + ladder re-weight ---------------
@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _make_engine(cfg, params, **kwargs):
    container = new_mock_container()
    kwargs.setdefault("max_slots", 4)
    kwargs.setdefault("max_len", 64)
    kwargs.setdefault("prompt_buckets", (8, 16))
    return GenerationEngine(cfg, params, logger=container.logger,
                            metrics=container.metrics, **kwargs)


def test_replay_is_deterministic(setup):
    """Two replays of the same trace → identical admitted-token counts,
    per-class tallies, and digest (the ISSUE 17 acceptance bar)."""
    cfg, params = setup
    rec = TrafficRecorder(capacity=64)
    lens = [(3, "interactive"), (5, "standard"), (4, "standard"),
            (6, "batch")]
    for i, (plen, cls) in enumerate(lens):
        record = _record(prompt_len=plen, budget=4)
        rec.admit(record, cls, now=10.0 + i * 0.002)
        record.tokens = 3
        record.status = "done"
        rec.finish(record)
    trace = load_trace(json.dumps(rec.export_trace()))

    async def run_once():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            return await asyncio.wait_for(
                replay_trace(engine, trace, time_scale=0.0), 120.0)
        finally:
            await engine.stop()

    first = asyncio.run(run_once())
    second = asyncio.run(run_once())
    assert first["requests"] == len(lens)
    assert first["errors"] == 0
    assert first["admitted_tokens"] == 3 * len(lens)   # recorded lengths
    assert first["per_class"]["standard"]["requests"] == 2
    assert first["per_class"]["interactive"]["outcomes"] == {"ok": 1}
    assert first["digest"] == second["digest"]
    assert first == second


def test_xlaz_ladder_reweights_with_recorded_traffic(setup):
    """The suggested-ladder DP must follow the recorder's recent window
    when one is attached, and fall back to lifetime shape stats when
    not — the ladder_source tag says which happened."""
    cfg, params = setup
    engine = _make_engine(cfg, params, prompt_buckets=(8, 64))
    # lifetime history says short prompts...
    for _ in range(50):
        engine.shapes.record("prompt", 4, 8)
    base = engine.xlaz(max_rungs=2)["models"]["prompt"]
    assert base["ladder_source"] == "observed_lengths"
    assert max(base["suggested_ladder"]) <= 8
    # ...but recent recorded traffic is long: suggestion must move
    rec = TrafficRecorder(capacity=64)
    for i in range(50):
        rec.admit(_record(model=engine.model_name, prompt_len=60),
                  "standard", now=10.0 + i * 0.01)
    engine.attach_workload(rec)
    shifted = engine.xlaz(max_rungs=2)["models"]["prompt"]
    assert shifted["ladder_source"] == "workload_trace"
    assert max(shifted["suggested_ladder"]) >= 60
    assert shifted["suggested_ladder"] != base["suggested_ladder"]


def test_engine_attributes_device_time_to_families(setup):
    """After real traffic, the per-family executable ledger total must
    agree with the per-class aggregate (shared charge site) and xlaz
    must rank families."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        rec = TrafficRecorder(capacity=64)
        engine.attach_workload(rec)
        await engine.start()
        try:
            await asyncio.wait_for(asyncio.gather(*[
                engine.generate([i + 1, i + 2], max_new_tokens=4)
                for i in range(3)]), 120.0)
        finally:
            await engine.stop()
        agg = sum(engine._device_seconds.values())
        fam = engine.exec_ledger.total_seconds(engine.model_name)
        assert agg > 0
        assert fam == pytest.approx(agg, rel=1e-6)   # same charge site
        snap = engine.xlaz()["executables"]
        families = {row["family"] for row in snap["top"]}
        assert any(f.startswith("prefill[") for f in families)
        assert any(f.startswith("decode") for f in families)
        # workload plane saw the traffic end to end
        wsnap = rec.snapshot()
        assert wsnap["admitted_total"] == 3
        assert wsnap["finished_total"] == 3
        assert wsnap["finish_mix"] == {"done": 3}
    asyncio.run(main())
