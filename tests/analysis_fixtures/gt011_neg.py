"""GT011 negative fixture: every recording buffer carries a bound."""

from collections import deque

MAX_EVENTS = 64


class BoundedRecorder:
    def __init__(self):
        self.samples = deque(maxlen=256)   # ring: bounded by construction
        self.events = []                   # bounded by the len() gate
        self.recent = []                   # bounded by the del-slice trim
        self.by_name = {}                  # bounded by the pop below

    def record(self, value):
        self.samples.append(value)
        if len(self.events) < MAX_EVENTS:
            self.events.append(value)
        self.recent.append(value)
        del self.recent[:-32]

    def observe(self, name, value):
        self.by_name[name] = value
        while len(self.by_name) > MAX_EVENTS:
            self.by_name.pop(next(iter(self.by_name)))
