"""GT010 unbounded retry: broad except inside a forever loop, no escape.

The chaos plane (ISSUE 14) makes retrying failures a first-class move —
and the classic way that move goes wrong is the blind retry loop::

    while True:
        try:
            await fetch()
        except Exception:
            continue          # spins hot forever against a dead peer

A persistent failure (peer gone, auth revoked, payload poisoned) turns
that loop into a busy-wait that hammers the dependency, pins a core,
and hides the outage from every caller. The repo's sanctioned shape is
``tpu/retry.py``'s :class:`RetryPolicy` — a bounded ``for`` over an
attempt budget with jittered backoff — which this rule cannot flag by
construction (no ``while True``).

Detection — for each ``while`` loop whose test is constantly true
(``while True:`` / ``while 1:``), every ``try`` in the loop's own body
with a *broad* handler (bare ``except``, ``except Exception``, or
``except BaseException``, alone or in a tuple) is a finding unless the
handler's own body (nested defs excluded) contains at least one of:

- an escape — ``raise``, ``return``, or ``break`` (the failure can
  leave the loop), *or a call to a helper whose body unconditionally
  raises* (an ``_abort(...)``-style escalator, resolved through the
  project call graph — even when it lives in another module), or
- pacing — a ``*.sleep(...)`` / ``*.wait(...)`` call (the retry is
  throttled, so a persistent failure degrades to a slow poll instead of
  a hot spin), *or a call to a helper that itself sleeps/waits* —
  followed through project call edges up to three hops, so a shared
  ``backoff()`` utility in its own module clears the loop. Pacing
  anywhere in the *loop's* own body clears the whole loop: a poll loop
  that sleeps between iterations cannot spin hot no matter which
  handler swallows (a ``continue`` can skip a trailing sleep, but that
  shape is rare enough to accept).

Loops whose test can go false (``while not self._draining``) terminate
by state and are skipped, as are ``try`` statements *wrapping* the loop
(a caught failure there exits the loop, it does not retry) and ``try``
statements nested *inside* another handler (error-path cleanup — the
swallow guards recovery code, not the retried operation). Narrow
handlers (``except KVWireError``) are deliberate routing, not blind
swallowing, and pass.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

_BROAD = {"Exception", "BaseException"}
_PACED_CALLS = {"sleep", "wait"}


def _constant_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _own_walk(node: ast.AST) -> Iterable[ast.AST]:
    """Walk ``node`` skipping nested function/lambda bodies — their
    control flow belongs to the nested callable, not this loop."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _own_walk(child)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    for node in types:
        name = node.id if isinstance(node, ast.Name) else (
            node.attr if isinstance(node, ast.Attribute) else None)
        if name in _BROAD:
            return True
    return False


def _escapes(handler: ast.ExceptHandler) -> bool:
    for node in _own_walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
    return False


def _paced(scope: ast.AST) -> bool:
    """True when ``scope``'s own walk contains a sleep/wait call."""
    for node in _own_walk(scope):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name in _PACED_CALLS:
            return True
    return False


def _always_raises(fn_node: ast.AST) -> bool:
    """A function whose body cannot fall through: every statement is a
    docstring/logging ``Expr`` except the final ``Raise``. Calling one
    from a handler is as good as raising inline."""
    body = getattr(fn_node, "body", [])
    if not body or not isinstance(body[-1], ast.Raise):
        return False
    return all(isinstance(stmt, ast.Expr) for stmt in body[:-1])


class _CallResolver:
    """Follows project call edges from a scope's call sites: is any
    callee (transitively, ≤3 hops) paced? does any callee always
    raise? Works with local-only edges when cross_module is off."""

    def __init__(self, project, ref):
        self.project = project
        self._by_site = {}
        if ref is not None:
            for callee, site in project.calls(ref):
                self._by_site.setdefault(id(site), callee)

    def callees_in(self, scope: ast.AST):
        for node in _own_walk(scope):
            if isinstance(node, ast.Call):
                callee = self._by_site.get(id(node))
                if callee is not None:
                    yield callee

    def paced_through(self, scope: ast.AST) -> bool:
        seen = set()
        stack = list(self.callees_in(scope))
        depth = {ref: 1 for ref in stack}
        while stack:
            ref = stack.pop()
            if ref in seen:
                continue
            seen.add(ref)
            fn = self.project.functions.get(ref)
            if fn is None:
                continue
            for node in self.project.body_nodes(ref):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else None)
                if name in _PACED_CALLS:
                    return True
            if depth.get(ref, 1) < 3:
                for callee, _site in self.project.calls(ref):
                    if callee not in seen:
                        depth[callee] = depth.get(ref, 1) + 1
                        stack.append(callee)
        return False

    def escapes_through(self, handler: ast.ExceptHandler) -> bool:
        for ref in self.callees_in(handler):
            fn = self.project.functions.get(ref)
            if fn is not None and _always_raises(fn.node):
                return True
        return False


def _in_handler(module: ModuleInfo, node: ast.AST,
                loop: ast.While) -> bool:
    """True when ``node`` sits inside an except handler between itself
    and ``loop`` — error-path cleanup, not the retried operation."""
    cursor = module.parents.get(node)
    while cursor is not None and cursor is not loop:
        if isinstance(cursor, ast.ExceptHandler):
            return True
        cursor = module.parents.get(cursor)
    return False


def _loop_owner(module: ModuleInfo, loop: ast.While) -> str:
    node = loop
    while node in module.parents:
        node = module.parents[node]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node.name
    return "<module>"


def _owner_node(module: ModuleInfo, loop: ast.While):
    node = loop
    while node in module.parents:
        node = module.parents[node]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
    return None


class UnboundedRetryRule(Rule):
    rule_id = "GT010"
    title = "unbounded-retry"
    severity = "error"

    def check_project(self, project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for rel in sorted(project.modules):
            findings.extend(
                self._check_module(project.modules[rel], project))
        return findings

    def _check_module(self, module: ModuleInfo,
                      project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for loop in ast.walk(module.tree):
            if not isinstance(loop, ast.While) or \
                    not _constant_true(loop.test):
                continue
            owner_node = _owner_node(module, loop)
            ref = (project.ref_of_node(owner_node)
                   if owner_node is not None else None)
            resolver = _CallResolver(project, ref)
            if _paced(loop) or resolver.paced_through(loop):
                continue
            for node in _own_walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                if _in_handler(module, node, loop):
                    continue
                for handler in node.handlers:
                    if not _is_broad(handler):
                        continue
                    if _escapes(handler) or \
                            resolver.escapes_through(handler):
                        continue
                    owner = _loop_owner(module, loop)
                    findings.append(Finding(
                        rule=self.rule_id,
                        path=module.relpath,
                        line=handler.lineno,
                        message=(
                            f"broad except inside '{owner}'s "
                            f"while-True loop swallows every failure "
                            f"and retries immediately — a persistent "
                            f"failure spins hot forever; bound the "
                            f"attempts (tpu/retry.py RetryPolicy), "
                            f"back off before retrying, or re-raise"),
                        severity=self.severity,
                        key=f"unbounded retry in {owner}",
                    ))
        return findings
