"""Sampling tests: ops/sampling math + per-request sampling and token
streaming through the continuous-batching engine (VERDICT r3 next #1)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import llama
from gofr_tpu.ops.sampling import sample_batch, sample_logits
from gofr_tpu.tpu.generate import GenerationEngine, Sampling
from tests.test_generate_engine import _make_engine


@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


# -- ops-level ------------------------------------------------------------

def test_zero_temperature_is_argmax():
    logits = jnp.asarray([0.1, 3.0, -1.0, 2.9], jnp.float32)
    key = jax.random.PRNGKey(7)
    for _ in range(3):
        token = sample_logits(logits, jnp.float32(0.0), jnp.int32(0),
                              jnp.float32(1.0), key)
        assert int(token) == 1


def test_top_k_one_is_argmax_even_with_temperature():
    logits = jnp.asarray([0.1, 3.0, -1.0, 2.9], jnp.float32)
    for seed in range(5):
        token = sample_logits(logits, jnp.float32(5.0), jnp.int32(1),
                              jnp.float32(1.0), jax.random.PRNGKey(seed))
        assert int(token) == 1


def test_tiny_top_p_is_argmax():
    logits = jnp.asarray([0.0, 1.0, 5.0, 2.0], jnp.float32)
    for seed in range(5):
        token = sample_logits(logits, jnp.float32(2.0), jnp.int32(0),
                              jnp.float32(1e-6), jax.random.PRNGKey(seed))
        assert int(token) == 2


def test_top_k_restricts_support():
    logits = jnp.asarray([5.0, 4.9, 4.8, -10.0, -10.0], jnp.float32)
    seen = set()
    for seed in range(32):
        token = sample_logits(logits, jnp.float32(1.0), jnp.int32(3),
                              jnp.float32(1.0), jax.random.PRNGKey(seed))
        seen.add(int(token))
    assert seen <= {0, 1, 2}
    assert len(seen) > 1   # temperature 1 over near-ties must actually mix


def test_sample_batch_mixes_greedy_and_sampled_rows():
    logits = jnp.tile(jnp.asarray([[0.0, 2.0, 1.9, -5.0]], jnp.float32),
                      (3, 1))
    temps = jnp.asarray([0.0, 1.0, 0.0], jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(3, dtype=jnp.uint32))
    tokens, new_keys = sample_batch(
        logits, temps, jnp.zeros((3,), jnp.int32), jnp.ones((3,)), keys)
    assert int(tokens[0]) == 1 and int(tokens[2]) == 1   # greedy rows
    assert new_keys.shape == (3, 2)
    assert not np.array_equal(np.asarray(new_keys[1]), np.asarray(keys[1]))


def test_sample_batch_deterministic_per_key():
    logits = jax.random.normal(jax.random.PRNGKey(3), (4, 64))
    temps = jnp.full((4,), 0.9, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4, dtype=jnp.uint32))
    args = (logits, temps, jnp.zeros((4,), jnp.int32),
            jnp.full((4,), 0.9, jnp.float32), keys)
    t1, k1 = sample_batch(*args)
    t2, k2 = sample_batch(*args)
    assert np.array_equal(np.asarray(t1), np.asarray(t2))
    assert np.array_equal(np.asarray(k1), np.asarray(k2))


# -- engine-level ---------------------------------------------------------

def test_stream_matches_generate_greedy(setup):
    """Streamed tokens must equal the gather-all result token for token."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            prompt = [1, 2, 3, 4]
            full = await asyncio.wait_for(
                engine.generate(prompt, max_new_tokens=6), 60.0)
            streamed = []
            stream = await engine.generate_stream(prompt, max_new_tokens=6)
            async for token in stream:
                streamed.append(token)
            assert streamed == full
        finally:
            await engine.stop()
    asyncio.run(main())


def test_sampled_generate_deterministic_with_seed(setup):
    """Same seed → same completion, across separate requests (the per-slot
    PRNG must not leak state between requests or depend on tick batching)."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params, steps_per_tick=4)
        await engine.start()
        try:
            sampling = Sampling(temperature=0.8, top_k=20, seed=42)
            out1 = await asyncio.wait_for(engine.generate(
                [5, 6, 7], max_new_tokens=8, sampling=sampling), 60.0)
            out2 = await asyncio.wait_for(engine.generate(
                [5, 6, 7], max_new_tokens=8, sampling=sampling), 60.0)
            assert out1 == out2
            other = await asyncio.wait_for(engine.generate(
                [5, 6, 7], max_new_tokens=8,
                sampling=Sampling(temperature=0.8, top_k=20, seed=43)), 60.0)
            assert len(other) == 8
        finally:
            await engine.stop()
    asyncio.run(main())


def test_mixed_batch_keeps_greedy_rows_greedy(setup):
    """A sampled request sharing ticks with a greedy one must not disturb
    the greedy request's tokens (they ride the sampled executable, where
    temp=0 rows resolve to argmax in-program)."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            prompt = [1, 2, 3, 4, 5]
            ref = llama.generate(params, cfg,
                                 np.asarray([prompt], np.int32), 6)
            ref = [int(t) for t in np.asarray(ref)[0]]
            greedy_task = asyncio.ensure_future(engine.generate(
                prompt, max_new_tokens=6))
            sampled_task = asyncio.ensure_future(engine.generate(
                [9, 8], max_new_tokens=6,
                sampling=Sampling(temperature=1.2, seed=7)))
            greedy, sampled = await asyncio.wait_for(
                asyncio.gather(greedy_task, sampled_task), 120.0)
            assert greedy == ref
            assert len(sampled) == 6
        finally:
            await engine.stop()
    asyncio.run(main())


def test_stream_sampled_deterministic(setup):
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            sampling = Sampling(temperature=0.7, top_p=0.9, seed=11)
            runs = []
            for _ in range(2):
                tokens = []
                stream = await engine.generate_stream(
                    [2, 4, 6], max_new_tokens=5, sampling=sampling)
                async for token in stream:
                    tokens.append(token)
                runs.append(tokens)
            assert runs[0] == runs[1]
            assert len(runs[0]) == 5
        finally:
            await engine.stop()
    asyncio.run(main())


def test_stream_eos_stops_early(setup):
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            prompt = [1, 2, 3]
            free = await asyncio.wait_for(
                engine.generate(prompt, max_new_tokens=8), 60.0)
            eos = free[2]
            streamed = []
            stream = await engine.generate_stream(
                prompt, max_new_tokens=8, eos_id=eos)
            async for token in stream:
                streamed.append(token)
            assert streamed == free[:3]   # eos token included, then stop
        finally:
            await engine.stop()
    asyncio.run(main())


def test_stream_engine_failure_raises(setup):
    """A loop failure mid-request must surface as an exception on the
    stream, not a hang (pairs with _fail_outstanding queue push)."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        real = engine._prefill_fn

        def exploding(nb, lb):
            raise RuntimeError("injected stream failure")

        engine._prefill_fn = exploding
        await engine.start()
        try:
            with pytest.raises(RuntimeError, match="injected"):
                stream = await engine.generate_stream([1, 2],
                                                      max_new_tokens=3)
                async for _ in stream:
                    pass
        finally:
            engine._prefill_fn = real
            await engine.stop()
    asyncio.run(main())

def test_stream_validation_is_eager(setup):
    """A bad request must raise at generate_stream() call time — before
    any response bytes could have been written (code-review r4 finding)."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            with pytest.raises(ValueError, match="exceeds largest bucket"):
                await engine.generate_stream(list(range(50)),
                                             max_new_tokens=4)
        finally:
            await engine.stop()
    asyncio.run(main())


def test_stream_cancel_frees_slot(setup):
    """Closing the stream iterator early (client disconnect) must free the
    slot instead of decoding the remaining budget into an unread queue."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            stream = await engine.generate_stream([1, 2, 3],
                                                  max_new_tokens=40)
            got = []
            async for token in stream:
                got.append(token)
                if len(got) == 2:
                    break
            await stream.aclose()
            assert len(got) == 2
            for _ in range(100):
                if engine.active_slots == 0:
                    break
                await asyncio.sleep(0.05)
            assert engine.active_slots == 0
            assert engine.stats()["free_slots"] == engine.max_slots
            # the engine must still serve fresh requests afterwards
            out = await asyncio.wait_for(
                engine.generate([4, 5], max_new_tokens=3), 60.0)
            assert len(out) == 3
        finally:
            await engine.stop()
    asyncio.run(main())

def test_stream_cancel_before_first_iteration(setup):
    """TokenStream.cancel must release the request even if iteration never
    started (unstarted async-generator aclose can't run a finally)."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        await engine.start()
        try:
            stream = await engine.generate_stream([1, 2], max_new_tokens=30)
            stream.cancel()   # before any __anext__
            await asyncio.sleep(0.3)
            for _ in range(100):
                if engine.active_slots == 0:
                    break
                await asyncio.sleep(0.05)
            assert engine.active_slots == 0
            out = await asyncio.wait_for(
                engine.generate([3], max_new_tokens=2), 60.0)
            assert len(out) == 2
        finally:
            await engine.stop()
    asyncio.run(main())


def test_multibucket_admission_failure_fails_all(setup):
    """If one bucket's prefill dispatch raises, requests admitted in the
    same batch for OTHER buckets must be failed too, not stranded
    (code-review r4 finding: slots are claimed for all buckets before any
    dispatch)."""
    cfg, params = setup

    async def main():
        engine = _make_engine(cfg, params)
        boom = {"armed": True}
        real = engine._prefill_fn

        def exploding(nb, lb):
            if boom["armed"]:
                raise RuntimeError("injected admission failure")
            return real(nb, lb)

        engine._prefill_fn = exploding
        await engine.start()
        try:
            # bucket 8 and bucket 16 in one admission batch
            t_small = asyncio.ensure_future(
                engine.generate([1, 2], max_new_tokens=2))
            t_large = asyncio.ensure_future(
                engine.generate(list(range(12)), max_new_tokens=2))
            results = await asyncio.wait_for(
                asyncio.gather(t_small, t_large, return_exceptions=True),
                60.0)
            assert all(isinstance(r, RuntimeError) for r in results), results
            boom["armed"] = False
            out = await asyncio.wait_for(
                engine.generate([1, 2], max_new_tokens=2), 60.0)
            assert len(out) == 2
        finally:
            await engine.stop()
    asyncio.run(main())
