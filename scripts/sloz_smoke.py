#!/usr/bin/env python
"""Tier-1 sloz smoke: a seeded fault burst must *burn* and be *named*.

A tiny engine (forced host devices) serves real traffic while an
``ErrorBudgetPlane`` differences the labelled ``app_tpu_slo_total``
series through a ``TimeSeriesStore`` on a synthetic 1 Hz clock, and a
``WorstOffenders`` ring diagnoses every finished request at finish
time. After a healthy baseline, a seeded ``nan_logits`` fault plan
poisons every request — each one quarantines into a labelled ``error``
outcome — and the smoke asserts the full judgment path ISSUE 18 exists
for:

1. the fast window pair (5m / 1h) trips within ONE ``evaluate`` call
   after the burst — no warm-up, no second counting path,
2. the watchdog reason names the burning (class, window) and flips the
   replica DEGRADED, and the brownout ladder's escalation gate sees the
   fast burn and allows the climb,
3. the worst offender in the ring is a burst casualty whose top whyz
   verdict cites the fault-injection site by name, and
4. ``/debug/whyz/{trace_id}`` serves that finish-time verdict from the
   ring (``source="offender_ring"``).

Prints ``sloz smoke: OK`` and exits 0, or raises with the failing
property. Budget: a few seconds on 8 host CPU devices.
"""

import asyncio
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    import jax

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.metrics.timeseries import TimeSeriesStore
    from gofr_tpu.models import llama
    from gofr_tpu.slo import (BrownoutLadder, SLOTracker, STATE_DEGRADED,
                              Watchdog)
    from gofr_tpu.slo_budget import ErrorBudgetPlane
    from gofr_tpu.tpu import faults
    from gofr_tpu.tpu.diagnose import WorstOffenders, build_window_context
    from gofr_tpu.tpu.generate import GenerationEngine

    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    container = new_mock_container()
    slo = SLOTracker(metrics=container.metrics)
    engine = GenerationEngine(cfg, params, max_slots=2, max_len=32,
                              prompt_buckets=(8,), kv_page=4,
                              paged_kv=True, prefix_cache=False,
                              model_name="llama-tiny",
                              logger=container.logger,
                              metrics=container.metrics,
                              tracer=container.tracer, slo=slo)

    # detector kept quiet (huge baseline requirement): this smoke is
    # about the budget plane's own judgment, not the anomaly detector's
    store = TimeSeriesStore(metrics=container.metrics,
                            detector_min_baseline=100_000)
    plane = ErrorBudgetPlane(store, container.metrics,
                             logger=container.logger)
    ring = WorstOffenders(
        k=16, window_s=300.0, keep_windows=2,
        context_fn=lambda: build_window_context(engine=engine, store=store))
    engine.recorder.offenders = ring

    clock = {"t": 0.0}
    ladder = BrownoutLadder(escalate_after=1)
    ladder.escalation_gate = plane.fast_burning
    dog = Watchdog(slo, min_attainment=0.0, hysteresis=1, brownout=ladder,
                   budget_fn=lambda: list(
                       plane.evaluate(now=clock["t"])["reasons"]))

    prompt, budget = [9, 8, 7], 4

    async def run() -> None:
        await engine.start()
        try:
            # healthy baseline: one ok request per synthetic second.
            # The first request creates the labelled series; evaluate()
            # then discovers the (model, cls) pair and registers its
            # providers before the priming sample.
            tokens = await asyncio.wait_for(engine.generate(
                prompt, max_new_tokens=budget), 60.0)
            assert tokens, "baseline request produced no tokens"
            plane.evaluate(now=clock["t"])
            store.sample(now=clock["t"])     # counter priming sample
            for _ in range(10):
                await asyncio.wait_for(engine.generate(
                    prompt, max_new_tokens=budget), 60.0)
                clock["t"] += 1.0
                store.sample(now=clock["t"])
            healthy = plane.evaluate(now=clock["t"])
            assert healthy["reasons"] == [], \
                f"healthy baseline burned budget: {healthy['reasons']}"
            assert healthy["budgets"], "no (model, cls) pair discovered"

            # the burst: every request hits seeded NaN logits and
            # quarantines into a labelled error outcome at the same
            # cadence — the plane's ONLY input is the existing counter
            plan = faults.FaultPlan("nan_logits", seed=11)
            faults.install(plan)
            for _ in range(8):
                try:
                    await asyncio.wait_for(engine.generate(
                        prompt, max_new_tokens=budget), 60.0)
                except Exception:
                    pass                     # the poison path
                clock["t"] += 1.0
                store.sample(now=clock["t"])
            assert plan.fired("nan_logits") >= 1, \
                "the armed fault never fired — the smoke proved nothing"

            # (1) one evaluation after the burst: the fast pair burns
            state = plane.evaluate(now=clock["t"])
            (entry,) = state["budgets"]
            assert any(b["window"] == "fast" for b in entry["burning"]), \
                f"fast pair did not trip in one evaluation: {entry}"
            assert entry["budget_remaining"] < 1.0, entry

            # (2) the watchdog reason names the (class, window) and the
            # gate lets the ladder climb on the fast burn
            assert dog.evaluate() == STATE_DEGRADED, dog.statusz()
            reason = " ".join(dog._last_reasons)
            assert "error budget burn" in reason, reason
            assert "cls=batch" in reason, reason
            assert "window=fast" in reason, reason
            assert ladder.level == 1, ladder.statusz()

            # (3) the burst casualties sit in the offender ring with a
            # finish-time top verdict citing the fault site by name
            snap = ring.snapshot()
            casualties = [e for w in snap["windows"] for e in w["entries"]
                          if e["status"] == "error"]
            assert casualties, f"no burst casualty in the ring: {snap}"
            victim = max(casualties, key=lambda e: e["e2e_s"])
            assert victim["trace_id"], victim
            entry = ring.find(victim["trace_id"])
            top = entry["verdicts"][0]
            assert top["rule"] == "fault_injection", entry["verdicts"]
            assert "nan_logits" in top["cause"], top

            # (4) whyz serves the finish-time verdict from the ring
            from types import SimpleNamespace

            from gofr_tpu.whyz import build_whyz
            app = SimpleNamespace(container=SimpleNamespace(
                app_name="smoke", app_version="0", offenders=ring,
                tpu=engine, telemetry=store))
            page = build_whyz(app, victim["trace_id"])
            assert page["source"] == "offender_ring", page
            assert page["verdicts"][0]["rule"] == "fault_injection", page
        finally:
            faults.reset()
            await engine.stop()

    asyncio.run(run())
    print("sloz smoke: OK")


if __name__ == "__main__":
    main()
