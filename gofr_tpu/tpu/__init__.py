"""TPU executor datasource: compiled-model serving with bucketed AOT
compilation, dynamic batching, continuous-batching generation, per-chip
health (north star, BASELINE.json). The identical executor runs on the
CPU backend in tests — the "miniredis of XLA" strategy (SURVEY.md §4)."""

from gofr_tpu.tpu import kv_wire
from gofr_tpu.tpu.batch_lane import BatchLane, JobError, new_batch_lane
from gofr_tpu.tpu.batcher import DynamicBatcher
from gofr_tpu.tpu.cluster import (ClusterRegistry, DisaggRouter,
                                  HTTPTransport, InProcTransport,
                                  NoReplicaAvailable, parse_peers)
from gofr_tpu.tpu.compile_ledger import (CAUSE_SERVING, CAUSE_WARMUP,
                                         CompileLedger, ShapeStats,
                                         fingerprint_lowered, suggest_ladder)
from gofr_tpu.tpu.executor import DEFAULT_BUCKETS, Executor, new_executor
from gofr_tpu.tpu.flightrecorder import FlightRecorder, RequestRecord
from gofr_tpu.tpu.generate import GenerationEngine
from gofr_tpu.tpu.page_pool import HBMBudget, PagePool
from gofr_tpu.tpu.registry import ModelRegistry, ModelUnavailable

__all__ = ["DynamicBatcher", "Executor", "FlightRecorder",
           "GenerationEngine", "RequestRecord", "new_executor",
           "DEFAULT_BUCKETS", "CompileLedger", "ShapeStats",
           "CAUSE_WARMUP", "CAUSE_SERVING", "fingerprint_lowered",
           "suggest_ladder", "ModelRegistry", "ModelUnavailable",
           "PagePool", "HBMBudget", "kv_wire", "ClusterRegistry",
           "DisaggRouter", "InProcTransport", "HTTPTransport",
           "NoReplicaAvailable", "parse_peers", "BatchLane", "JobError",
           "new_batch_lane"]
