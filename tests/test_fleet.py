"""Fleet control plane (ISSUE 12): prefix-affinity routing, live
decode→decode migration, and the autoscaler's decision kernel.

The load-bearing contracts, in order:

1. AFFINITY ROUTES TO RESIDENCY — a replica's clusterz digest is enough
   for the router to steer a shared-prefix request back to the replica
   whose radix cache already holds the prefix; a cold prompt falls back
   to the least-inflight pick.
2. MIGRATION IS INVISIBLE — a mid-stream session migrated between
   replicas emits exactly the monolithic engine's token stream, with
   zero prefill dispatches on the target (``prefill_bucket_tokens`` 0:
   shipped pages become page-table entries, never a prefill), and the
   source's pages return to its free list.
3. DRAIN IS MIGRATE-OUT — draining a replica with live sessions moves
   them to a peer and completes immediately instead of waiting out the
   decode budget; the drained replica takes no new routes.
4. THE AUTOSCALER IS BORING — hysteresis streaks, cooldown, the
   compile-ledger guard, and single-flight overlap protection all hold
   before a scale callback ever fires.
"""

import asyncio

import jax
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.tpu import kv_wire
from gofr_tpu.tpu.cluster import (ROLE_BOTH, ROLE_DECODE, ClusterRegistry,
                                  InProcTransport)
from gofr_tpu.tpu.fleet import (Autoscaler, FleetPrefixIndex, FleetRouter,
                                FleetSession)
from gofr_tpu.tpu.generate import GenerationEngine
from gofr_tpu.tpu.prefix_cache import chain_hashes


@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _make_engine(cfg, params, **kwargs):
    container = new_mock_container()
    kwargs.setdefault("max_slots", 4)
    kwargs.setdefault("max_len", 64)
    kwargs.setdefault("prompt_buckets", (8, 16))
    engine = GenerationEngine(cfg, params, logger=container.logger,
                              metrics=container.metrics, **kwargs)
    return engine, container


async def _reference(cfg, params, prompt, budget):
    engine, _ = _make_engine(cfg, params, paged_kv=True, kv_page=4)
    await engine.start()
    try:
        return await asyncio.wait_for(
            engine.generate(prompt, max_new_tokens=budget), 60.0)
    finally:
        await engine.stop()


async def _fleet(cfg, params, names=("d0", "d1"), **engine_kwargs):
    """N in-proc ``both`` replicas behind a FleetRouter — every replica
    owns a paged pool and its own admission path (so its radix cache
    builds residency), the topology affinity routing exists for."""
    engine_kwargs.setdefault("paged_kv", True)
    engine_kwargs.setdefault("kv_page", 4)
    engines = {}
    cluster = ClusterRegistry()
    for name in names:
        engine, _ = _make_engine(cfg, params, **engine_kwargs)
        engines[name] = engine
        cluster.register(name, ROLE_BOTH, InProcTransport(engine))
    router = FleetRouter(cluster)
    for engine in engines.values():
        await engine.start()
    return engines, cluster, router


async def _stop(engines):
    for engine in engines.values():
        await engine.stop()


# -- kv_wire chunk-size knob (satellite) --------------------------------------

def test_chunk_bytes_env_knob(monkeypatch):
    monkeypatch.delenv("KV_WIRE_CHUNK_BYTES", raising=False)
    assert kv_wire.resolve_chunk_bytes() == kv_wire.DEFAULT_CHUNK_BYTES

    monkeypatch.setenv("KV_WIRE_CHUNK_BYTES", str(64 << 10))
    assert kv_wire.resolve_chunk_bytes() == 64 << 10

    for bad in ("12",                       # under the 4 KiB floor
                str(kv_wire.MAX_CHUNK_BYTES),   # at the 4 MiB cap
                "not-a-number"):
        monkeypatch.setenv("KV_WIRE_CHUNK_BYTES", bad)
        with pytest.raises(ValueError, match="KV_WIRE_CHUNK_BYTES"):
            kv_wire.resolve_chunk_bytes()

    # explicit values bypass the knob window (tests use tiny frames)
    monkeypatch.setenv("KV_WIRE_CHUNK_BYTES", "12")
    assert kv_wire.resolve_chunk_bytes(7) == 7
    chunks = list(kv_wire.iter_chunks(b"x" * 100, chunk_bytes=7))
    assert sum(len(c) for c in chunks) == 100
    assert all(len(c) <= 7 for c in chunks)


# -- registry: least-inflight pick (satellite) --------------------------------

class _FakeTransport:
    kind = "fake"

    def available(self):
        return True

    def health_check(self):
        return {"status": "UP"}

    def describe(self):
        return {"kind": self.kind}


def test_pick_prefers_least_inflight():
    cluster = ClusterRegistry()
    cluster.register("d0", "decode", _FakeTransport())
    cluster.register("d1", "decode", _FakeTransport())
    busy = cluster._require("d0")
    cluster.note_start(busy)
    # d0 carries a stream: every pick goes to the idle replica, not RR
    assert all(cluster.pick(ROLE_DECODE).name == "d1" for _ in range(4))
    cluster.note_end(busy)
    picked = {cluster.pick(ROLE_DECODE).name for _ in range(4)}
    assert picked == {"d0", "d1"}        # tied again: RR spreads


# -- prefix index -------------------------------------------------------------

def test_prefix_index_depth_ties_and_page_guard():
    idx = FleetPrefixIndex()
    hashes = chain_hashes(list(range(1, 13)), 4)       # 3 full pages
    assert len(hashes) == 3
    idx.update("a", {"page": 4, "entries": hashes[:2], "occupancy": 0.5})
    idx.update("b", {"page": 4, "entries": hashes[:1], "occupancy": 0.1})
    assert idx.page == 4
    assert idx.match_depth("a", hashes) == 2
    assert idx.match_depth("b", hashes) == 1
    assert idx.best(hashes, ["a", "b"]) == ("a", 2)
    assert idx.best(hashes, ["b"]) == ("b", 1)

    # equal depth: the tie goes to the lower-occupancy replica
    idx.update("a", {"page": 4, "entries": hashes[:1], "occupancy": 0.5})
    assert idx.best(hashes, ["a", "b"]) == ("b", 1)

    # a digest at a different page size cannot match chained hashes —
    # the replica drops out of the index instead of poisoning it
    idx.update("a", {"page": 8, "entries": hashes[:1], "occupancy": 0.0})
    assert idx.match_depth("a", hashes) == 0
    assert idx.stats()["replicas"] == ["b"]

    cold = chain_hashes([99, 98, 97, 96], 4)
    assert idx.best(cold, ["b"]) == (None, 0)
    idx.drop("b")
    assert idx.stats()["replicas"] == []


# -- tentpole: affinity routing ----------------------------------------------

def test_affinity_routes_repeat_prefix_to_the_holder(setup):
    cfg, params = setup
    prompt = list(range(1, 13))                        # 3 full pages

    async def run():
        engines, cluster, router = await _fleet(
            cfg, params, prefix_cache=True)
        try:
            # cold prompt: fallback pick serves it locally and, in doing
            # so, builds residency in that replica's radix cache
            session = await router.generate_stream(prompt, 6)
            assert isinstance(session, FleetSession)
            first = [token async for token in session]
            assert len(first) == 6
            holder = session.replica_name
            assert router.fleet_stats()["routing"] == {
                "affinity": 0, "fallback": 1}

            # the clusterz probe carries the digest into the index
            await router.refresh()
            stats = router.index.stats()
            assert stats["page"] == 4
            assert stats["entries"].get(holder, 0) > 0

            # same 2-page prefix, different tail: affinity finds the
            # holder even though the registry's RR would rotate away
            repeat = prompt[:8] + [77, 78]
            replica, depth = router._route(repeat)
            assert replica.name == holder and depth == 2
            out = await asyncio.wait_for(router.generate(repeat, 6), 60.0)
            assert len(out) == 6
            assert router.fleet_stats()["routing"]["affinity"] == 2

            # a cold prompt still falls back
            replica, depth = router._route([51, 52, 53, 54, 55])
            assert depth == 0 and replica is not None
        finally:
            await _stop(engines)

    asyncio.run(run())


# -- tentpole: live migration -------------------------------------------------

def test_migration_mid_stream_is_token_identical(setup):
    cfg, params = setup
    prompt, budget = [1, 2, 3, 4, 5], 24
    ref = asyncio.run(_reference(cfg, params, prompt, budget))

    async def run():
        engines, cluster, router = await _fleet(cfg, params)
        try:
            baseline = {name: engine._pool.free_pages
                        for name, engine in engines.items()}
            session = await router.generate_stream(prompt, budget)
            tokens = [await asyncio.wait_for(session.__anext__(), 60.0)
                      for _ in range(2)]
            source = session.replica_name

            target = await router.migrate_session(session)
            assert target != source
            assert session.replica_name == target
            assert session.migrations == 1

            async for token in session:
                tokens.append(token)
            assert tokens == ref                      # token identity

            src_eng, tgt_eng = engines[source], engines[target]
            # zero re-prefill: the shipped pages were adopted, the
            # target never ran a prefill dispatch for this session
            assert tgt_eng.stats()["prefill_bucket_tokens"] == 0
            assert tgt_eng.stats()["session_adoptions"] == 1
            assert src_eng.stats()["session_exports"] == 1

            # the source's pages ride the normal teardown back to free
            for _ in range(200):
                if src_eng._pool.free_pages == baseline[source]:
                    break
                await asyncio.sleep(0.02)
            assert src_eng._pool.free_pages == baseline[source]
            assert router.fleet_stats()["migrations"] == {
                "ok": 1, "failed": 0}
        finally:
            await _stop(engines)

    asyncio.run(run())


def test_migration_rejects_double_inflight_and_bad_target(setup):
    cfg, params = setup

    async def run():
        engines, cluster, router = await _fleet(cfg, params)
        try:
            session = await router.generate_stream([1, 2, 3], 16)
            await asyncio.wait_for(session.__anext__(), 60.0)
            source = session.replica_name
            with pytest.raises(ValueError, match="target equals"):
                await router.migrate_session(session, target_name=source)
            # the failed attempt must not leave a splice armed
            target = await router.migrate_session(session)
            assert target != source
            async for _ in session:
                pass
        finally:
            await _stop(engines)

    asyncio.run(run())


# -- drain = migrate-out ------------------------------------------------------

def test_drain_migrates_live_sessions_out(setup):
    cfg, params = setup
    prompt, budget = [2, 4, 6, 8], 24
    ref = asyncio.run(_reference(cfg, params, prompt, budget))

    async def run():
        engines, cluster, router = await _fleet(cfg, params)
        try:
            session = await router.generate_stream(prompt, budget)
            tokens = [await asyncio.wait_for(session.__anext__(), 60.0)]
            source = session.replica_name
            other = next(n for n in engines if n != source)

            drained = await asyncio.wait_for(router.drain(source), 10.0)
            assert drained is True
            assert cluster._replicas[source].state == "DRAINING"
            assert session.replica_name == other
            assert engines[source].stats()["session_exports"] == 1

            async for token in session:
                tokens.append(token)
            assert tokens == ref                      # lossless hand-off

            # the drained replica takes no new routes
            before = cluster._replicas[other].requests
            out = await asyncio.wait_for(router.generate(prompt, 4), 60.0)
            assert len(out) == 4
            assert cluster._replicas[other].requests == before + 1
        finally:
            await _stop(engines)

    asyncio.run(run())


# -- autoscaler ---------------------------------------------------------------

class _Ledger:
    def __init__(self, n):
        self.n = n

    def serving_compiles(self, window_s):
        return self.n


def _scaler(registry=None, **kwargs):
    calls = []
    kwargs.setdefault("min_decode", 1)
    kwargs.setdefault("max_decode", 3)
    kwargs.setdefault("queue_high", 4)
    kwargs.setdefault("queue_low", 1)
    kwargs.setdefault("up_after", 2)
    kwargs.setdefault("down_after", 2)
    kwargs.setdefault("cooldown_s", 0.0)
    scaler = Autoscaler(registry or ClusterRegistry(),
                        scale_up=lambda: calls.append("up"),
                        scale_down=lambda name: calls.append(
                            ("down", name)),
                        **kwargs)
    return scaler, calls


def test_autoscaler_hysteresis_and_bounds():
    async def run():
        pressure = {"queue_depth": 9, "decode_replicas": 1}
        scaler, calls = _scaler(signals_fn=lambda: dict(pressure))
        assert (await scaler())["result"] == "hold"     # streak 1 of 2
        assert (await scaler())["result"] == "up"
        assert calls == ["up"]
        assert (await scaler())["result"] == "hold"     # streak reset

        # at the ceiling pressure never scales
        pressure["decode_replicas"] = 3
        scaler, calls = _scaler(signals_fn=lambda: dict(pressure),
                                up_after=1)
        assert (await scaler())["result"] == "hold"
        assert calls == []

    asyncio.run(run())


def test_autoscaler_cooldown_and_compile_guard():
    async def run():
        pressure = {"queue_depth": 9, "decode_replicas": 1}
        scaler, calls = _scaler(signals_fn=lambda: dict(pressure),
                                up_after=1, cooldown_s=1000.0)
        assert (await scaler())["result"] == "up"
        assert (await scaler())["result"] == "cooldown"
        assert calls == ["up"]

        scaler, calls = _scaler(signals_fn=lambda: dict(pressure),
                                up_after=1, compile_ledger=_Ledger(1))
        assert (await scaler())["result"] == "compile_guard"
        assert calls == []

        # a quiet ledger lets the same step through
        scaler, calls = _scaler(signals_fn=lambda: dict(pressure),
                                up_after=1, compile_ledger=_Ledger(0))
        assert (await scaler())["result"] == "up"

    asyncio.run(run())


def test_autoscaler_scales_down_idle_fleet_to_the_floor():
    async def run():
        cluster = ClusterRegistry()
        cluster.register("d0", "decode", _FakeTransport())
        cluster.register("d1", "decode", _FakeTransport())
        cluster.note_start(cluster._require("d0"))      # d1 is idler
        idle = {"queue_depth": 0, "decode_replicas": 2}
        scaler, calls = _scaler(cluster, signals_fn=lambda: dict(idle))
        assert (await scaler())["result"] == "hold"     # streak 1 of 2
        event = await scaler()
        assert event["result"] == "down"
        assert calls == [("down", "d1")]                # least-inflight

        # at the floor the victim pick refuses
        idle["decode_replicas"] = 1
        scaler, calls = _scaler(cluster, signals_fn=lambda: dict(idle),
                                down_after=1, min_decode=2)
        assert (await scaler())["result"] == "hold"
        assert calls == []

    asyncio.run(run())


def test_autoscaler_overlapping_firings_are_dropped():
    async def run():
        gate = asyncio.Event()

        async def slow_signals():
            await gate.wait()
            return {"queue_depth": 0, "decode_replicas": 1}

        scaler, calls = _scaler(signals_fn=slow_signals)
        first = asyncio.create_task(scaler())
        await asyncio.sleep(0)                          # enter _gather
        second = await scaler()
        assert second["result"] == "overlap"            # dropped, not queued
        gate.set()
        assert (await first)["result"] == "hold"
        status = scaler.status()
        assert status["busy"] is False
        assert [e["result"] for e in status["recent"]] == \
            ["overlap", "hold"]

    asyncio.run(run())


def test_autoscaler_rejects_bad_bounds():
    with pytest.raises(ValueError, match="min_decode"):
        Autoscaler(ClusterRegistry(), lambda: None, lambda n: None,
                   min_decode=0)
    with pytest.raises(ValueError, match="max_decode"):
        Autoscaler(ClusterRegistry(), lambda: None, lambda n: None,
                   min_decode=2, max_decode=1)
