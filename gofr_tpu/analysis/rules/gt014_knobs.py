"""GT014: serving-knob mutation outside a guarded apply path.

The online auto-tuner (ISSUE 19) made the engine's serving knobs —
prompt-bucket ladders, fused steps per tick, spec-γ cap, page-reserve
watermark, WFQ class weights, batcher coalescing — *mutable at
runtime*, which is only safe because every mutation funnels through one
guarded, validate-then-swap apply path
(``GenerationEngine.apply_operating_point`` /
``DynamicBatcher.apply_operating_point``): shape-changing moves are
refused until pre-warmed, brownouts refuse any move, and the swap is
atomic with respect to the engine loop. A direct write —
``engine.steps_per_tick = 8`` from a cron handler, a debug endpoint, a
"quick fix" in an example — bypasses all of it: it can push a compile
onto the serving path, tear the knob set mid-tick, and leave the
operating-point provenance lying about what is live. This rule is the
static guard on that funnel.

What it flags: an ``ast.Assign`` / ``ast.AugAssign`` whose target is
``<receiver>.<knob>`` (or a subscript of one, e.g.
``engine.class_weights["batch"] = 9``) where

- ``<knob>`` is one of the serving-knob attribute names below,
- the receiver is NOT ``self`` (a class managing its own state inside
  its own methods is the implementation, not a bypass), and
- the enclosing function is not itself a sanctioned apply path
  (``apply_operating_point``, ``set_weights``) or a constructor
  (``__init__`` wires the seed point).

Knob set: ``steps_per_tick``, ``prompt_buckets``, ``spec_gamma``,
``max_slots``, ``slots_cap``, ``class_weights``, ``staging_depth``,
``max_batch``, ``max_delay``, ``max_delay_ms``, ``kv_page_reserve``,
``_gamma_cap``, ``_kv_reserve``, ``_k_ladder``.

What clears it: route the change through the owning object's
``apply_operating_point`` (engine or batcher), or
``ClassQueues.set_weights`` for admission weights. Tests that
deliberately poke internals suppress per line with
``# graftcheck: ignore[GT014]``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

# Runtime-tunable serving knobs: the attribute names the guarded apply
# paths own. Includes the engine's private derived state (_gamma_cap /
# _kv_reserve / _k_ladder) — writing those from outside is the same
# bypass with one more underscore.
KNOB_ATTRS = frozenset({
    "steps_per_tick", "prompt_buckets", "spec_gamma", "max_slots",
    "slots_cap", "class_weights", "staging_depth",
    "max_batch", "max_delay", "max_delay_ms", "kv_page_reserve",
    "_gamma_cap", "_kv_reserve", "_k_ladder",
})

# Functions allowed to write knobs directly: the guarded apply paths
# themselves, and constructors (the seed operating point is wired
# there).
SANCTIONED_FUNCTIONS = frozenset({
    "apply_operating_point", "set_weights", "__init__",
})


def _assign_targets(node: ast.AST) -> List[ast.expr]:
    if isinstance(node, ast.Assign):
        out: List[ast.expr] = []
        for target in node.targets:
            if isinstance(target, ast.Tuple):
                out.extend(target.elts)
            else:
                out.append(target)
        return out
    if isinstance(node, ast.AugAssign):
        return [node.target]
    return []


class ServingKnobMutationRule(Rule):
    rule_id = "GT014"
    title = "serving-knob-mutation"
    severity = "error"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            for target in _assign_targets(node):
                # peel a subscript: engine.class_weights["batch"] = ...
                # mutates the knob exactly like a whole-value write
                attr = (target.value
                        if isinstance(target, ast.Subscript) else target)
                if not isinstance(attr, ast.Attribute):
                    continue
                if attr.attr not in KNOB_ATTRS:
                    continue
                receiver = attr.value
                if isinstance(receiver, ast.Name) and \
                        receiver.id == "self":
                    continue
                fn = module.enclosing_function(node)
                if fn is not None and fn.name in SANCTIONED_FUNCTIONS:
                    continue
                recv = module.dotted(receiver) or "<expr>"
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=node.lineno,
                    message=(
                        f"direct write to serving knob "
                        f"'{recv}.{attr.attr}' bypasses the guarded "
                        f"apply path — route it through "
                        f"apply_operating_point() (pre-warm, brownout "
                        f"refusal, atomic swap) so a knob move can "
                        f"never compile on the serving path or tear "
                        f"mid-tick"),
                    severity=self.severity,
                    key=f"knob write {recv}.{attr.attr}",
                ))
        findings.sort(key=lambda f: f.line)
        return findings
