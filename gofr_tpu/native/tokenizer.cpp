// Native BPE tokenizer encoder/decoder for the text serving hot path.
//
// The Go reference has no native code (SURVEY.md §2.7); this is the
// framework's own runtime-native component: HTTP text -> token ids sits on
// the /generate critical path in front of every Llama call, and a Python
// inner loop there costs more than the decode step itself at high QPS.
//
// Model: byte-level BPE. Token ids 0..255 are raw bytes; merge i produces
// id 256+i from (left, right). Encoding repeatedly applies the
// lowest-rank adjacent merge (classic BPE, priority by rank); decode
// concatenates recursively-expanded byte strings.
//
// C ABI (ctypes-friendly, no C++ types across the boundary):
//   gofr_tok_new(pairs, n)            -> handle   (pairs: 2*n int32)
//   gofr_tok_encode(h, text, len, out, cap) -> n_tokens (or -1)
//   gofr_tok_decode(h, ids, n, out, cap)    -> n_bytes  (or -1)
//   gofr_tok_free(h)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
    // merge (left<<32|right) -> rank
    std::unordered_map<uint64_t, int32_t> ranks;
    // token id -> produced pair (for decode); bytes have no entry
    std::vector<std::pair<int32_t, int32_t>> pairs;
    // token id -> expanded byte string, built lazily at decode
    std::vector<std::string> bytes_cache;

    const std::string& expand(int32_t id) {
        std::string& slot = bytes_cache[id];
        if (slot.empty() && id >= 256) {
            const auto& pr = pairs[id - 256];
            slot = expand(pr.first) + expand(pr.second);
        }
        return slot;
    }
};

inline uint64_t pack(int32_t a, int32_t b) {
    return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32)
         | static_cast<uint32_t>(b);
}

}  // namespace

extern "C" {

void* gofr_tok_new(const int32_t* merge_pairs, int32_t n_merges) {
    auto* tok = new Tokenizer();
    tok->pairs.reserve(n_merges);
    tok->ranks.reserve(n_merges * 2);
    for (int32_t i = 0; i < n_merges; ++i) {
        int32_t left = merge_pairs[2 * i];
        int32_t right = merge_pairs[2 * i + 1];
        tok->pairs.emplace_back(left, right);
        tok->ranks.emplace(pack(left, right), i);
    }
    tok->bytes_cache.resize(256 + n_merges);
    for (int32_t b = 0; b < 256; ++b)
        tok->bytes_cache[b] = std::string(1, static_cast<char>(b));
    return tok;
}

int32_t gofr_tok_encode(void* handle, const uint8_t* text, int32_t len,
                        int32_t* out, int32_t cap) {
    auto* tok = static_cast<Tokenizer*>(handle);
    std::vector<int32_t> ids(text, text + len);
    // classic BPE: merge the lowest-rank adjacent pair until none applies.
    // O(n * n_merges_applied) with early exit; fine for request-sized text.
    while (ids.size() >= 2) {
        int32_t best_rank = INT32_MAX;
        size_t best_pos = 0;
        for (size_t i = 0; i + 1 < ids.size(); ++i) {
            auto it = tok->ranks.find(pack(ids[i], ids[i + 1]));
            if (it != tok->ranks.end() && it->second < best_rank) {
                best_rank = it->second;
                best_pos = i;
            }
        }
        if (best_rank == INT32_MAX) break;
        ids[best_pos] = 256 + best_rank;
        ids.erase(ids.begin() + best_pos + 1);
    }
    if (static_cast<int32_t>(ids.size()) > cap) return -1;
    std::memcpy(out, ids.data(), ids.size() * sizeof(int32_t));
    return static_cast<int32_t>(ids.size());
}

int32_t gofr_tok_decode(void* handle, const int32_t* ids, int32_t n,
                        uint8_t* out, int32_t cap) {
    auto* tok = static_cast<Tokenizer*>(handle);
    std::string result;
    for (int32_t i = 0; i < n; ++i) {
        int32_t id = ids[i];
        if (id < 0 || id >= static_cast<int32_t>(tok->bytes_cache.size()))
            return -1;
        result += tok->expand(id);
    }
    if (static_cast<int32_t>(result.size()) > cap) return -1;
    std::memcpy(out, result.data(), result.size());
    return static_cast<int32_t>(result.size());
}

void gofr_tok_free(void* handle) {
    delete static_cast<Tokenizer*>(handle);
}

}  // extern "C"
