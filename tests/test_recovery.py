"""Failure-detection/recovery: SQL maintenance loop (reconnect + stats
push, reference sql.go:108-132/189-202 analog) and Redis wire-client
transport retry — kill the backend, watch the datasource come back
without an app restart."""

import socket
import threading
import time

import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.datasource.redisx.client import RedisClient, RedisError
from gofr_tpu.datasource.sql.db import SQLError, new_sql


def _wait_for(predicate, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_sql_reconnects_after_backend_death(tmp_path):
    container = new_mock_container({
        "DB_NAME": str(tmp_path / "app.db"),
        "DB_RETRY_FREQUENCY": "0.05",
    })
    db = new_sql(container.config, container.logger, container.metrics)
    try:
        db.execute("CREATE TABLE t (id INTEGER)")
        db.execute("INSERT INTO t VALUES (1)")
        # kill the backend: close the live connection out from under the DB
        db._conn.close()
        with pytest.raises(SQLError):
            db.select("SELECT * FROM t")
        # the failing query woke the maintenance loop; recovery is in
        # flight — the same DB object serves again without a restart
        assert _wait_for(lambda: db._ping()), "reconnect never happened"
        rows = db.select("SELECT * FROM t")
        assert rows == [{"id": 1}]
    finally:
        db.close()


def test_sql_stats_gauges_pushed(tmp_path):
    container = new_mock_container({
        "DB_NAME": str(tmp_path / "stats.db"),
        "DB_RETRY_FREQUENCY": "0.05",
    })
    db = new_sql(container.config, container.logger, container.metrics)
    try:
        def gauge_up():
            snapshot = container.metrics.snapshot()
            return snapshot.get("app_sql_open_connections")
        assert _wait_for(lambda: gauge_up() is not None)
        assert "app_sql_inuse_connections" in container.metrics.snapshot()
    finally:
        db.close()


def test_sql_close_stops_maintenance_thread(tmp_path):
    container = new_mock_container({
        "DB_NAME": ":memory:", "DB_RETRY_FREQUENCY": "0.05"})
    db = new_sql(container.config, container.logger, container.metrics)
    db.close()
    assert _wait_for(lambda: not db._maintenance.is_alive())


class _FakeRedisServer:
    """Single-connection RESP2 responder for transport-failure tests."""

    def __init__(self, port=0):
        self.listener = socket.socket()
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind(("127.0.0.1", port))
        self.listener.listen(4)
        self.port = self.listener.getsockname()[1]
        self.commands = []
        self.error_replies = 0     # next N commands answered with -ERR
        self.drop_next = 0         # next N connections closed pre-reply
        self._stop = False
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _read_command(self, conn, buffer):
        # parse one RESP array of bulk strings
        def read_line():
            while b"\r\n" not in buffer[0]:
                chunk = conn.recv(4096)
                if not chunk:
                    raise ConnectionError
                buffer[0] += chunk
            line, buffer[0] = buffer[0].split(b"\r\n", 1)
            return line

        head = read_line()
        n = int(head[1:])
        parts = []
        for _ in range(n):
            size = int(read_line()[1:])
            while len(buffer[0]) < size + 2:
                chunk = conn.recv(4096)
                if not chunk:
                    raise ConnectionError
                buffer[0] += chunk
            parts.append(buffer[0][:size].decode())
            buffer[0] = buffer[0][size + 2:]
        return parts

    def _handle(self, conn):
        buffer = [b""]
        try:
            while True:
                parts = self._read_command(conn, buffer)
                self.commands.append(parts)
                if self.drop_next > 0:
                    self.drop_next -= 1
                    conn.close()
                    return
                if self.error_replies > 0:
                    self.error_replies -= 1
                    conn.sendall(b"-WRONGTYPE wrong kind of value\r\n")
                    continue
                cmd = parts[0].upper()
                if cmd == "PING":
                    conn.sendall(b"+PONG\r\n")
                elif cmd == "INCR":
                    conn.sendall(b":1\r\n")
                else:
                    conn.sendall(b"+OK\r\n")
        except (ConnectionError, OSError):
            pass

    def close(self):
        self._stop = True
        try:
            self.listener.close()
        except OSError:
            pass


@pytest.fixture()
def fake_redis():
    server = _FakeRedisServer()
    yield server
    server.close()


def _wire_client(server):
    container = new_mock_container({"REDIS_HOST": "127.0.0.1",
                                    "REDIS_PORT": str(server.port)})
    return RedisClient(container.config, container.logger,
                       container.metrics)


def test_redis_wire_reconnects_on_dead_socket(fake_redis):
    client = _wire_client(fake_redis)
    assert client.ping()
    # server drops the next connection mid-command: the client must
    # reconnect and reissue transparently
    fake_redis.drop_next = 1
    assert client.ping()
    client.close()


def test_redis_server_error_is_not_retried(fake_redis):
    """-ERR replies must surface as RedisError WITHOUT a reconnect+reissue:
    reissuing a non-idempotent INCR would double-apply it."""
    client = _wire_client(fake_redis)
    assert client.ping()
    before = len(fake_redis.commands)
    fake_redis.error_replies = 1
    with pytest.raises(RedisError):
        client.incr("counter")
    # exactly ONE INCR hit the server — no retry happened
    incrs = [c for c in fake_redis.commands[before:] if c[0] == "INCR"]
    assert len(incrs) == 1
    client.close()


def test_redis_wire_pipeline_single_roundtrip(fake_redis):
    """pipeline(): all commands in one write, per-slot results; an error
    reply fills its slot without aborting the batch."""
    client = _wire_client(fake_redis)
    results = client.pipeline([("SET", "k", "v"), ("PING",),
                               ("INCR", "counter")])
    assert results == ["OK", "PONG", 1]
    # error reply lands in its slot as RedisError, batch continues
    fake_redis.error_replies = 1
    first, second = client.pipeline([("INCR", "k"), ("PING",)])
    assert isinstance(first, RedisError)
    assert second == "PONG"
    client.close()


def test_engine_fetch_failure_between_dispatch_and_publish():
    """Kill the device→host token fetch AFTER the decode tick dispatched
    (VERDICT r3 #5: fault injection mid-tick). The fetch task raising must
    fail the bound callers, drain cleanly (no 'exception was never
    retrieved'), rebuild device state, and keep serving correct tokens."""
    import asyncio

    import jax
    import numpy as np

    import gofr_tpu.tpu.generate as generate_module
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.generate import GenerationEngine

    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    container = new_mock_container()
    engine = GenerationEngine(cfg, params, max_slots=2, max_len=64,
                              prompt_buckets=(8,),
                              logger=container.logger,
                              metrics=container.metrics)

    real_asarray = np.asarray
    state = {"failures_left": 1}

    class _ExplodingNumpy:
        def __getattr__(self, name):
            return getattr(np, name)

        @staticmethod
        def asarray(value, *args, **kwargs):
            # only detonate on tick fetches (device arrays), not the
            # prefill fetch or host-side array building
            if state["failures_left"] and hasattr(value, "addressable_shards"):
                if getattr(value, "ndim", 0) == 2:   # (K, B) tick tokens
                    state["failures_left"] -= 1
                    raise RuntimeError("injected fetch failure")
            return real_asarray(value, *args, **kwargs)

    async def main():
        await engine.start()
        generate_module.np = _ExplodingNumpy()
        try:
            with pytest.raises(RuntimeError, match="injected fetch"):
                await asyncio.wait_for(
                    engine.generate([1, 2, 3], max_new_tokens=4), 60.0)
            # engine recovered: correct greedy tokens on a fresh request
            out = await asyncio.wait_for(
                engine.generate([1, 2, 3], max_new_tokens=4), 60.0)
            ref = llama.generate(params, cfg,
                                 np.asarray([[1, 2, 3]], np.int32), 4)
            assert out == [int(t) for t in np.asarray(ref)[0]]
            assert engine.stats()["free_slots"] == engine.max_slots
        finally:
            generate_module.np = np
            await engine.stop()
    asyncio.run(main())
