"""Servable model zoo (north star, BASELINE.json): ResNet-50 classify,
BERT-base embeddings, Llama generate. The Go reference ships no models
(SURVEY.md §2.7) — these are original TPU-first designs; see each module's
docstring for the design rules (bf16/MXU, stacked-scan layers, static
shapes, sharding-annotation-only parallelism)."""

from gofr_tpu.models import bert, llama, resnet

__all__ = ["bert", "llama", "resnet"]
