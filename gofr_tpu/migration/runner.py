"""Versioned, transactional, journaled migrations.

Capability parity with ``pkg/gofr/migration`` (migration.go:14-26
``Migrate{UP}`` keyed by int64 version; Run 28-91: validate + sort, skip ≤
last, per-migration txn begin/commit/rollback; sql.go:12-25 journal table
DDL + insert; redis.go:29-96 journal hash; interface.go:27-42 datasource
decorators incl. pub-sub topic create/delete inside migrations). The
journal doubles as the framework's checkpoint/resume analog (SURVEY.md §5):
resume point = max(SQL table, Redis hash).
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, Optional, Union

MIGRATION_TABLE_DDL = """
CREATE TABLE IF NOT EXISTS gofr_migrations (
    version INTEGER PRIMARY KEY,
    method TEXT NOT NULL,
    start_time TEXT NOT NULL,
    duration_ms REAL
)
"""

REDIS_JOURNAL_KEY = "gofr_migrations"


class MigrationError(Exception):
    pass


class Migration:
    """A single UP step: ``Migration(up=fn)`` where fn(datasources)."""

    def __init__(self, up: Callable[["Datasources"], None]):
        if not callable(up):
            raise MigrationError("migration UP must be callable")
        self.up = up


class Datasources:
    """What a migration sees: the SQL handle is the transaction, Redis is
    live, pub/sub exposes topic create/delete (interface.go:27-30)."""

    def __init__(self, sql=None, redis=None, pubsub=None, logger=None):
        self.sql = sql
        self.redis = redis
        self.pubsub = pubsub
        self.logger = logger

    def create_topic(self, name: str) -> None:
        if self.pubsub is not None:
            self.pubsub.create_topic(name)

    def delete_topic(self, name: str) -> None:
        if self.pubsub is not None:
            self.pubsub.delete_topic(name)


def _last_sql_version(sql) -> int:
    sql.execute(MIGRATION_TABLE_DDL)
    row = sql.query_row("SELECT MAX(version) AS v FROM gofr_migrations")
    return int(row["v"] or 0) if row else 0


def _last_redis_version(redis) -> int:
    journal = redis.hgetall(REDIS_JOURNAL_KEY)
    return max((int(v) for v in journal.keys()), default=0)


def run_migrations(container,
                   migrations: Dict[int, Union[Migration, Callable]]) -> int:
    """Run pending migrations in version order; returns how many ran."""
    logger = container.logger
    if not migrations:
        return 0
    for version in migrations:
        if not isinstance(version, int) or version <= 0:
            raise MigrationError(f"invalid migration version {version!r}")

    sql = container.sql
    redis = container.redis
    last = 0
    if sql is not None:
        last = max(last, _last_sql_version(sql))
    if redis is not None:
        last = max(last, _last_redis_version(redis))

    ran = 0
    for version in sorted(migrations):
        if version <= last:
            continue
        migration = migrations[version]
        up = migration.up if isinstance(migration, Migration) else migration
        start = time.time()
        t0 = time.perf_counter()
        tx = sql.begin() if sql is not None else None
        try:
            up(Datasources(sql=tx if tx is not None else None, redis=redis,
                           pubsub=container.pubsub, logger=logger))
            duration_ms = (time.perf_counter() - t0) * 1e3
            if tx is not None:
                tx.execute(
                    "INSERT INTO gofr_migrations "
                    "(version, method, start_time, duration_ms) "
                    "VALUES (?, ?, ?, ?)",
                    version, "UP",
                    time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(start)),
                    duration_ms)
                tx.commit()
            if redis is not None:
                redis.hsetnx(REDIS_JOURNAL_KEY, str(version), json.dumps({
                    "method": "UP", "start_time": start,
                    "duration_ms": duration_ms}))
            logger.info("migration %d UP ok in %.1fms", version, duration_ms)
            ran += 1
        except Exception as exc:
            if tx is not None:
                tx.rollback()
            logger.error("migration %d failed, rolled back: %r", version, exc)
            raise MigrationError(f"migration {version} failed: {exc}") \
                from exc
    return ran


def last_migration(container) -> int:
    """Highest applied version across journals (the resume point)."""
    last = 0
    if container.sql is not None:
        last = max(last, _last_sql_version(container.sql))
    if container.redis is not None:
        last = max(last, _last_redis_version(container.redis))
    return last
