"""Multi-host distributed runtime: DCN × ICI hybrid meshes.

TPU-native replacement for the reference's inter-node story (SURVEY.md
§2.8: the Go reference scales out via Kafka consumer groups + k8s; tensor
traffic here is delegated to XLA exactly as GoFr delegates broker IO to
kafka-go). Within a slice, collectives ride ICI; across slices/hosts they
ride DCN — so mesh axes must be laid out DCN-outermost, which is exactly
what ``hybrid_mesh`` builds (mesh_utils.create_hybrid_device_mesh).

Initialization follows the JAX multi-process model: every host runs the
same program, ``initialize_distributed`` wires them via the coordinator
address, and ``jax.devices()`` becomes the global slice view.
Env contract (k8s-friendly, matching the framework's env-first config):
  JAX_COORDINATOR=host:port  JAX_NUM_PROCESSES=N  JAX_PROCESS_ID=i
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
from jax.sharding import Mesh


def initialize_distributed(config=None) -> bool:
    """Initialize jax.distributed from env/config; returns True if a
    multi-process runtime was actually started (single-host no-op)."""
    def get(key: str, default: str = "") -> str:
        if config is not None:
            return config.get_or_default(key, default)
        import os
        return os.environ.get(key, default)

    coordinator = get("JAX_COORDINATOR")
    if not coordinator:
        return False
    num_processes = int(get("JAX_NUM_PROCESSES", "1"))
    process_id = int(get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def hybrid_mesh(ici_axes: Dict[str, int],
                dcn_axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Mesh with DCN axes outermost and ICI axes innermost.

    ``hybrid_mesh({"dp": 4, "tp": 2}, {"dp_outer": 2})`` on 2 hosts × 8
    chips: data parallelism splits over DCN first (gradient all-reduce
    crosses hosts once), while tp all-reduces stay on ICI. On a single
    host every dcn axis must be 1 (validated).
    """
    from jax.experimental import mesh_utils

    dcn_axes = dcn_axes or {}
    num_slices = max(1, getattr(jax, "process_count", lambda: 1)())
    dcn_total = 1
    for size in dcn_axes.values():
        dcn_total *= size
    if dcn_total > num_slices:
        raise ValueError(
            f"dcn axes {dcn_axes} need {dcn_total} processes, have "
            f"{num_slices}")

    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    ici_shape = tuple(ici_axes.values())
    if dcn_axes and dcn_total > 1:
        # Backends without real slice topology (multi-process CPU — the
        # test rig, dcn_check) report one slice for every device; when
        # slices can't satisfy the dcn axes but processes can, fall back
        # to mesh_utils' own process-granule layout (one process == one
        # slice). Real shape errors still propagate.
        slice_count = len({getattr(d, "slice_index", None)
                           for d in jax.devices()})
        # mesh_utils wants SAME-RANK inner/outer shapes (axis i of the
        # result = dcn[i] * ici[i]); our distinct named axes become
        # dcn-dims padded with trailing 1s × ici-dims padded with
        # leading 1s, giving the dcn-outermost layout
        inner = (1,) * len(dcn_axes) + ici_shape
        outer = tuple(dcn_axes.values()) + (1,) * len(ici_axes)
        devices = mesh_utils.create_hybrid_device_mesh(
            inner, outer,
            process_is_granule=slice_count < dcn_total)
    else:
        # single host: dcn axes degenerate to 1, plain ICI mesh
        devices = mesh_utils.create_device_mesh(ici_shape)
        devices = devices.reshape((1,) * len(dcn_axes) + ici_shape)
    return Mesh(devices, names,
                axis_types=(jax.sharding.AxisType.Auto,) * len(names))


def process_info() -> Dict[str, int]:
    """This host's view of the job (for logs/health endpoints)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
