"""Kafka wire-client error matrix (VERDICT r4 missing #3: deepen the
thinnest seams — coordinator error codes, fetch error codes, partition
growth, and the per-partition fetcher's failure modes)."""

import asyncio
import struct
import threading
import time

import pytest

from gofr_tpu.config import MapConfig
from gofr_tpu.container import new_mock_container
from gofr_tpu.datasource.pubsub.kafka import (
    ERR_ILLEGAL_GENERATION,
    ERR_REBALANCE_IN_PROGRESS,
    ERR_UNKNOWN_MEMBER,
    KafkaClient,
    KafkaError,
    KafkaRebalance,
    _Reader,
)

from tests.test_pubsub_wire import FakeKafkaBroker


def _make_client(broker, extra=None):
    config = {"PUBSUB_BROKER": f"127.0.0.1:{broker.port}",
              "CONSUMER_ID": "workers",
              "KAFKA_FETCH_MAX_WAIT_MS": "20",
              "KAFKA_HEARTBEAT_INTERVAL_MS": "100",
              "KAFKA_SESSION_TIMEOUT_MS": "1000"}
    config.update(extra or {})
    container = new_mock_container()
    return KafkaClient(MapConfig(config), container.logger,
                       container.metrics)


@pytest.mark.parametrize("code,expect,reset", [
    (ERR_UNKNOWN_MEMBER, KafkaRebalance, True),
    (ERR_ILLEGAL_GENERATION, KafkaRebalance, False),
    (ERR_REBALANCE_IN_PROGRESS, KafkaRebalance, False),
    (7, KafkaError, None),          # request timed out: plain error
])
def test_heartbeat_error_code_matrix(code, expect, reset):
    """Heartbeat 22/25/27 must raise KafkaRebalance (25 additionally
    resetting the member id); any other nonzero code is a KafkaError."""
    broker = FakeKafkaBroker()
    client = _make_client(broker)

    class Coordinator:
        def call(self, api_key, api_version, body):
            return _Reader(struct.pack(">h", code))

    try:
        with pytest.raises(expect) as err:
            client._heartbeat(Coordinator(), 3, "m-1")
        if expect is KafkaRebalance:
            assert err.value.reset_member is reset
    finally:
        client.close()
        broker.stop()


def test_join_group_unknown_member_resets_id():
    broker = FakeKafkaBroker()
    client = _make_client(broker)

    class Coordinator:
        def call(self, api_key, api_version, body):
            return _Reader(struct.pack(">h", ERR_UNKNOWN_MEMBER))

    try:
        with pytest.raises(KafkaRebalance) as err:
            client._join_group(Coordinator(), "t", "stale-member")
        assert err.value.reset_member
    finally:
        client.close()
        broker.stop()


def test_fetch_error_code_surfaces_as_kafka_error():
    """A non-offset fetch error (e.g. 6 NOT_LEADER) raises KafkaError —
    the fetcher records it and the poller restarts the pass."""
    broker = FakeKafkaBroker()
    client = _make_client(broker)

    class Conn:
        def call(self, api_key, api_version, body):
            # throttle, 1 topic, name "t", 1 partition: id 0, error 6,
            # hwm 0, empty message set
            return _Reader(struct.pack(">i", 0) + struct.pack(">i", 1)
                           + struct.pack(">h", 1) + b"t"
                           + struct.pack(">i", 1)
                           + struct.pack(">ihq", 0, 6, 0)
                           + struct.pack(">i", 0))

    try:
        with pytest.raises(KafkaError, match="fetch error code 6"):
            client._fetch("t", 0, 0, broker=Conn())
    finally:
        client.close()
        broker.stop()


def test_static_partition_growth_spawns_new_fetcher():
    """Partition growth after subscribe must be consumed without a
    restart: the static poller's metadata refresh spawns a fetcher for
    the new partition (reference: kafka-go reader re-config)."""
    broker = FakeKafkaBroker()
    broker.partitions["logs"] = 1
    broker.logs[("logs", 0)] = [(b"", b"p0-old")]
    client = _make_client(broker, {"KAFKA_GROUP_MODE": "static",
                                   "KAFKA_METADATA_REFRESH_S": "0.2"})
    try:
        async def scenario():
            first = await asyncio.wait_for(client.subscribe("logs"), 10.0)
            assert first.value == b"p0-old"
            # topic grows; the new partition has a message
            broker.partitions["logs"] = 2
            broker.logs[("logs", 1)] = [(b"", b"p1-new")]
            second = await asyncio.wait_for(client.subscribe("logs"), 10.0)
            assert second.value == b"p1-new"
            assert second.metadata["partition"] == 1

        asyncio.run(scenario())
    finally:
        client.close()
        broker.stop()


def test_fetcher_heals_in_place_when_leader_connection_refused():
    """A partition leader going down must NOT kill the sibling
    partitions' consumption: the fetcher retries its own connection
    while the others keep flowing (the pre-r5 sequential loop and a
    naive fetcher both tear everything down)."""
    broker = FakeKafkaBroker()
    broker.partitions["events"] = 2
    broker.logs[("events", 0)] = []
    broker.logs[("events", 1)] = [(b"", b"ok-%d" % i) for i in range(3)]
    client = _make_client(broker, {"KAFKA_GROUP_MODE": "static"})

    # poison partition 0's leader address AFTER metadata is cached so its
    # fetcher dials a dead port forever; partition 1 stays healthy
    client._refresh_metadata("events")
    dead = FakeKafkaBroker()
    dead_port = dead.port
    dead.stop()
    client._leaders[("events", 0)] = ("127.0.0.1", dead_port)

    # keep metadata poisoned: _refresh_metadata would heal it, which is
    # fine in production but defeats the isolation assertion here
    orig_refresh = client._refresh_metadata

    def poisoned_refresh(topic):
        parts = orig_refresh(topic)
        client._leaders[("events", 0)] = ("127.0.0.1", dead_port)
        return parts

    client._refresh_metadata = poisoned_refresh
    try:
        async def scenario():
            got = []
            for _ in range(3):
                message = await asyncio.wait_for(
                    client.subscribe("events"), 10.0)
                got.append(message.value)
            assert got == [b"ok-0", b"ok-1", b"ok-2"]

        asyncio.run(scenario())
    finally:
        client.close()
        broker.stop()


def test_committer_carries_generation_fencing_fields():
    """The committer built inside the group loop must commit with the
    member's generation so stale-generation commits are fenced broker-
    side (kafka.py commit fencing; broker state asserted end-to-end in
    test_kafka_groups.py — this pins the wire fields)."""
    broker = FakeKafkaBroker()
    client = _make_client(broker)
    try:
        captured = {}
        orig = client._commit_offset

        def spy(topic, partition, offset, generation=-1, member_id="",
                broker_conn=None):
            captured.update(generation=generation, member_id=member_id)
            return orig(topic, partition, offset, generation, member_id)

        client._commit_offset = spy
        committer = client._make_committer("t", 0, 5, 7, "member-x")
        # the fake coordinator does NOT know member-x/generation 7, so a
        # correctly-fenced commit is REJECTED broker-side (error 25):
        # both the field plumbing and the fencing raise are asserted
        with pytest.raises(KafkaRebalance, match="fenced"):
            committer()
        assert captured == {"generation": 7, "member_id": "member-x"}
    finally:
        client.close()
        broker.stop()
