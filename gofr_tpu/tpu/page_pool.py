"""Shared device-resident KV page pool (ISSUE 6).

One pool backs every KV byte of the paged serving path: prefill output,
the radix prefix cache, and decode appends all address the same
``(L, num_pages, page, Hkv, Dh)`` arrays (int8 caches add the scale
planes ``(L, num_pages, page, Hkv)``). The dense engine kept one
``(max_slots, max_len, ...)`` cache whose HBM cost was the *worst-case*
sequence length times the slot count; here HBM is ``num_pages × page``
tokens regardless of ``max_len``, and slot count scales with the actual
token footprint of live traffic (the ragged-paged-attention layout from
PAPERS.md: "Ragged Paged Attention", arxiv 2604.15464; sizing by real
footprint instead of static worst case follows the batch-size/latency
study, arxiv 1812.11731).

Host-side state is a free list plus a per-page refcount:

- ``alloc`` hands out pages at refcount 1 (the allocating owner — an
  engine slot or a prefix-trie node).
- ``retain``/``release`` move shared ownership: a slot's page that the
  prefix trie adopts is retained once by the trie, so the page outlives
  the slot; release drops a ref and returns the page to the free list at
  zero.
- ``alloc`` takes an optional ``reclaim`` callback (the prefix store's
  LRU leaf eviction): it is invoked while the free list is short and may
  release pages; allocation is all-or-nothing and never blocks.

``num_pages`` doubles as the out-of-bounds sentinel id: scatters use
``mode="drop"`` so a sentinel entry writes nothing, and gathers clamp —
the clamped garbage is always masked by ``cache_len`` downstream.

Device arrays live in ``leaves``; owners that run donating executables
(the engine's decode tick / paged insert) write the returned arrays
back. All dispatches are serialized on the engine loop, so handle churn
is single-writer; JAX dataflow orders in-flight readers before the
donated buffer is reused.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["PagePool", "HBMBudget"]


class PagePool:
    """Refcounted device page pool shared by prefill, prefix cache, and
    decode. ``num_pages`` may be given directly or derived from
    ``budget_bytes`` (HBM cap across every leaf)."""

    def __init__(self, cfg, page: int = 32,
                 num_pages: Optional[int] = None,
                 budget_bytes: Optional[int] = None,
                 mesh=None, metrics=None):
        import threading

        import jax
        import numpy as np

        self._jax = jax
        self._np = np
        # Serializes donating executions against the pool leaves AND the
        # host-side ownership tables (free list, refcounts). One engine's
        # dispatches are already serialized on its loop, but co-resident
        # engines (multi-model tenancy) each run cold dispatches in
        # executor threads: engine A's donation deletes the handle
        # engine B captured unless call + leaves write-back form one
        # critical section. alloc/retain/release/reset take this lock
        # internally (reentrant), so callers on the engine loop stay
        # lock-free while staying race-free.
        self.lock = threading.RLock()
        self.cfg = cfg
        self.mesh = mesh
        self.metrics = metrics
        self.page = int(page)
        self.page_bytes = self._page_bytes(cfg, self.page)
        if num_pages is not None:
            self.num_pages = int(num_pages)
        elif budget_bytes is not None:
            self.num_pages = max(1, int(budget_bytes) // self.page_bytes)
        else:
            raise ValueError("PagePool needs num_pages or budget_bytes")
        # cumulative counters (survive reset: pool history, not contents)
        self.writes = 0        # page-rows scattered into the pool
        self.stalls = 0        # failed allocations (free list exhausted)
        self.allocs = 0
        self.leaves: Dict[str, Any] = {}
        self._free: List[int] = []
        self._refs = np.zeros((self.num_pages,), np.int32)
        self._reset_subscribers: List[Callable[[], None]] = []
        self.reset()

    @property
    def sentinel(self) -> int:
        """Out-of-bounds page id: dropped by scatters, clamped (and then
        length-masked) by gathers."""
        return self.num_pages

    @staticmethod
    def _page_bytes(cfg, page: int) -> int:
        """HBM bytes one page occupies across every cache leaf."""
        import jax.numpy as jnp

        kv = cfg.n_layers * page * cfg.n_kv_heads * cfg.head_dim
        if cfg.kv_int8:
            scales = cfg.n_layers * page * cfg.n_kv_heads * 4
            return 2 * (kv + scales)          # int8 k+v, f32 ks+vs
        return 2 * kv * jnp.dtype(cfg.dtype).itemsize

    def _init_leaves(self) -> None:
        import jax.numpy as jnp

        cfg = self.cfg
        shape = (cfg.n_layers, self.num_pages, self.page, cfg.n_kv_heads,
                 cfg.head_dim)
        if cfg.kv_int8:
            leaves = {"k": jnp.zeros(shape, jnp.int8),
                      "v": jnp.zeros(shape, jnp.int8),
                      "ks": jnp.ones(shape[:-1], jnp.float32),
                      "vs": jnp.ones(shape[:-1], jnp.float32)}
        else:
            leaves = {"k": jnp.zeros(shape, cfg.dtype),
                      "v": jnp.zeros(shape, cfg.dtype)}
        if self.mesh is not None:
            # any slot gathers any page, so rows cannot shard over dp;
            # kv-heads shard over tp exactly like the dense cache
            from gofr_tpu.parallel.sharding import (
                llama_prefix_pool_specs, prune_specs, shard_pytree)
            leaves = shard_pytree(
                leaves, self.mesh,
                prune_specs(llama_prefix_pool_specs(kv_int8=cfg.kv_int8),
                            self.mesh))
        else:
            leaves = self._jax.device_put(leaves)
        self.leaves = leaves

    def reset(self) -> None:
        """Fresh device buffers, empty ownership. Called at engine
        device-state reset: a failed donating executable may have
        poisoned any in-flight handle. Honors a caller-resized
        ``num_pages`` (tests shrink pools to force eviction). When the
        pool is shared by several engines (multi-model tenancy), every
        subscriber is notified so co-resident owners can drop their now
        dangling page ids and device handles."""
        with self.lock:
            self._free = list(range(self.num_pages))
            self._refs = self._np.zeros((self.num_pages,), self._np.int32)
            self._init_leaves()
            self._set_gauges()
            callbacks = list(self._reset_subscribers)
        for callback in callbacks:
            callback()

    def subscribe(self, callback: Callable[[], None]) -> None:
        """Register a reset observer. A co-resident engine uses this to
        learn that another owner tore the pool down (its own page tables
        now point at freed pages and must be re-sentineled)."""
        if callback not in self._reset_subscribers:
            self._reset_subscribers.append(callback)

    # -- ownership ----------------------------------------------------------
    def alloc(self, n: int = 1,
              reclaim: Optional[Callable[[], bool]] = None
              ) -> Optional[List[int]]:
        """Allocate ``n`` pages at refcount 1, all-or-nothing. While the
        free list is short, ``reclaim()`` (if given) is called to release
        evictable pages; it returns False when it has nothing left. On
        failure returns None and counts a stall — never blocks.

        Self-serializing: the free list and refcounts mutate under the
        pool's own (reentrant) lock, so loop-thread allocation cannot
        race another owner's release — co-resident engines share one
        pool but not one thread. ``reclaim`` runs under the lock too;
        eviction callbacks re-enter ``release`` harmlessly (RLock)."""
        with self.lock:
            while len(self._free) < n and reclaim is not None \
                    and reclaim():
                pass
            if len(self._free) < n:
                self.stalls += 1
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_tpu_kv_pages_stalled_total")
                return None
            ids = [self._free.pop() for _ in range(n)]
            for pid in ids:
                self._refs[pid] = 1
            self.allocs += n
            self._set_gauges()
            return ids

    def retain(self, page_ids: Sequence[int]) -> None:
        with self.lock:
            for pid in page_ids:
                self._refs[pid] += 1

    def release(self, page_ids: Sequence[int]) -> None:
        """Drop one ref per page; refcount 0 returns the page to the free
        list. Releasing an already-free page is a no-op (reset guards)."""
        with self.lock:
            for pid in page_ids:
                if self._refs[pid] > 0:
                    self._refs[pid] -= 1
                    if self._refs[pid] == 0:
                        self._free.append(pid)
            self._set_gauges()

    @staticmethod
    def pad_table(table, block: int, sentinel: int):
        """Pad a host page table's width to a multiple of ``block`` with
        sentinel entries (ragged-paged-attention export: the Pallas
        kernel walks the table in page blocks, so its width must tile;
        the sentinel tail is skipped by the kernel's length guard exactly
        like any other dead entry). Returns the input unchanged when the
        width already tiles. table: (B, P) int32 ndarray."""
        import numpy as np

        width = table.shape[1]
        block = max(int(block), 1)
        pad = (-width) % block
        if pad == 0:
            return table
        return np.concatenate(
            [table, np.full((table.shape[0], pad), sentinel,
                            table.dtype)], axis=1)

    def note_writes(self, pages: int) -> None:
        """Count page-rows an owner's scatter actually wrote (sentinel
        entries excluded) — the zero-copy-admission proof reads this."""
        if pages <= 0:
            return
        self.writes += pages
        if self.metrics is not None:
            self.metrics.delta_updown_counter(
                "app_tpu_kv_pages_written_total", float(pages))

    # -- introspection ------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def pool_bytes(self) -> int:
        return self.num_pages * self.page_bytes

    def refs(self, pid: int) -> int:
        return int(self._refs[pid])

    def _set_gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("app_tpu_kv_pages_used",
                                   float(self.used_pages))
            self.metrics.set_gauge("app_tpu_kv_pages_capacity",
                                   float(self.num_pages))

    def stats(self) -> Dict[str, Any]:
        return {
            "page_tokens": self.page,
            "num_pages": self.num_pages,
            "used_pages": self.used_pages,
            "free_pages": self.free_pages,
            "page_bytes": self.page_bytes,
            "pool_bytes": self.pool_bytes,
            "occupancy": (round(self.used_pages / self.num_pages, 6)
                          if self.num_pages else 0.0),
            "allocs": self.allocs,
            "writes": self.writes,
            "stalls": self.stalls,
        }


class HBMBudget:
    """Byte-granular HBM arbiter for multi-model tenancy.

    Engines with the *same* KV geometry share one :class:`PagePool`
    instance directly (page ids are interchangeable). Heterogeneous
    co-residents (different head counts, dtypes, page sizes) cannot share
    pages, so the registry carves the chip's KV budget in bytes instead:
    each model's carve becomes its own pool's ``budget_bytes``. The
    arbiter only does conservative bookkeeping — it never talks to the
    device — but it turns "two models silently OOM-ing each other" into
    an explicit, observable admission failure at load time.
    """

    def __init__(self, total_bytes: int):
        if total_bytes <= 0:
            raise ValueError("HBMBudget needs a positive byte budget")
        self.total_bytes = int(total_bytes)
        self._carves: Dict[str, int] = {}

    @property
    def carved_bytes(self) -> int:
        return sum(self._carves.values())

    @property
    def free_bytes(self) -> int:
        return self.total_bytes - self.carved_bytes

    def carve(self, name: str, nbytes: int) -> int:
        """Reserve ``nbytes`` for ``name``; raises when the remaining
        budget cannot cover it (fail at load, not mid-traffic)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            raise ValueError(f"carve({name!r}) needs a positive size")
        if name in self._carves:
            raise ValueError(f"model {name!r} already holds a carve")
        if nbytes > self.free_bytes:
            raise ValueError(
                f"HBM budget exhausted: {name!r} wants {nbytes} bytes, "
                f"{self.free_bytes} of {self.total_bytes} remain")
        self._carves[name] = nbytes
        return nbytes

    def release(self, name: str) -> None:
        self._carves.pop(name, None)

    def stats(self) -> Dict[str, Any]:
        return {
            "total_bytes": self.total_bytes,
            "carved_bytes": self.carved_bytes,
            "free_bytes": self.free_bytes,
            "carves": dict(self._carves),
        }
