"""Disaggregated serving (ISSUE 8): kv_wire codec, cluster routing, and
the prefill→decode handoff.

The load-bearing contracts, in order:

1. WIRE FIDELITY — pack → iter_chunks → assemble → unpack is byte-exact
   for both codecs (bf16 raw, int8 + scale planes), and any structural
   defect (truncation, bad magic, version skew, geometry lies, trailing
   garbage) raises ``KVWireError`` before a single leaf is admitted.
2. TOKEN IDENTITY, ZERO RE-PREFILL — greedy decode through the disagg
   router (prefill replica exports, decode replica adopts) emits exactly
   the monolithic engine's stream, while the decode replica's
   ``prefill_bucket_tokens`` stays at zero: migrated KV becomes
   page-table entries, never a prefill dispatch.
3. DRAIN IS LOSSLESS — a DRAINING decode replica takes no new routes,
   finishes its in-flight streams, and its page-pool free list returns
   to the pre-test level (migrated pages ride the normal slot teardown).
"""

import asyncio
import dataclasses
import struct
import time

import jax
import ml_dtypes
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.tpu import kv_wire
from gofr_tpu.tpu.cluster import (ROLE_DECODE, ROLE_PREFILL, ClusterRegistry,
                                  DisaggRouter, HandoffTable,
                                  InProcTransport, NoReplicaAvailable,
                                  parse_peers)
from gofr_tpu.tpu.generate import GenerationEngine, Sampling
from gofr_tpu.tpu.kv_wire import (CODEC_INT8, CODEC_RAW, KVPayload,
                                  KVWireError)
from gofr_tpu.trace.tracer import ListExporter, Tracer


@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _make_engine(cfg, params, **kwargs):
    container = new_mock_container()
    kwargs.setdefault("max_slots", 4)
    kwargs.setdefault("max_len", 64)
    kwargs.setdefault("prompt_buckets", (8, 16))
    engine = GenerationEngine(cfg, params, logger=container.logger,
                              metrics=container.metrics, **kwargs)
    return engine, container


# -- kv_wire: synthetic payloads ---------------------------------------------

def _payload(codec, tokens=6, page=4, n_layers=2, n_kv_heads=2, head_dim=4,
             dtype="bfloat16"):
    n_pages = -(-tokens // page)
    payload = KVPayload(codec=codec, dtype=dtype, page=page, tokens=tokens,
                        n_layers=n_layers, n_kv_heads=n_kv_heads,
                        head_dim=head_dim, n_pages=n_pages, first_token=17,
                        sample_key=(0xDEAD, 0xBEEF), model="tiny",
                        leaves={})
    rng = np.random.default_rng(7)
    for name in kv_wire.leaf_names(codec):
        shape = kv_wire.leaf_shape(payload, name)
        if codec == CODEC_INT8 and name in ("k", "v"):
            payload.leaves[name] = rng.integers(
                -128, 128, size=shape, dtype=np.int8)
        elif codec == CODEC_INT8:      # ks/vs scale planes
            payload.leaves[name] = rng.random(shape, dtype=np.float32)
        else:
            payload.leaves[name] = rng.standard_normal(shape).astype(
                ml_dtypes.bfloat16)
    return payload


@pytest.mark.parametrize("codec", [CODEC_RAW, CODEC_INT8])
def test_wire_roundtrip_is_byte_exact(codec):
    src = _payload(codec)
    blob = kv_wire.pack(src)
    chunks = list(kv_wire.iter_chunks(blob, chunk_bytes=64))
    assert all(len(c) <= 64 for c in chunks)
    assert sum(len(c) for c in chunks) == len(blob)
    out = kv_wire.unpack(kv_wire.assemble(chunks))
    assert (out.codec, out.dtype, out.page, out.tokens) == \
        (src.codec, src.dtype, src.page, src.tokens)
    assert (out.n_layers, out.n_kv_heads, out.head_dim, out.n_pages) == \
        (src.n_layers, src.n_kv_heads, src.head_dim, src.n_pages)
    assert out.first_token == 17 and out.sample_key == (0xDEAD, 0xBEEF)
    assert out.model == "tiny"
    assert sorted(out.leaves) == sorted(src.leaves)
    for name, arr in src.leaves.items():
        assert out.leaves[name].tobytes() == arr.tobytes()
        assert out.leaves[name].shape == arr.shape


def test_wire_rejects_corruption():
    blob = kv_wire.pack(_payload(CODEC_RAW))

    with pytest.raises(KVWireError, match="truncated"):
        kv_wire.unpack(blob[:10])                    # short header
    with pytest.raises(KVWireError, match="magic"):
        kv_wire.unpack(b"XKVW" + blob[4:])           # bad magic
    with pytest.raises(KVWireError, match="version"):
        kv_wire.unpack(blob[:4] + struct.pack("<H", 99) + blob[6:])
    with pytest.raises(KVWireError, match="trailing"):
        kv_wire.unpack(blob + b"\x00")               # trailing garbage
    with pytest.raises(KVWireError, match="truncated"):
        kv_wire.unpack(blob[:-5])                    # short last leaf
    # lie about n_pages in the header: tokens=6/page=4 needs 2 pages
    head = list(kv_wire._HEAD.unpack_from(blob))
    head[9] += 1
    with pytest.raises(KVWireError, match="geometry"):
        kv_wire.unpack(kv_wire._HEAD.pack(*head) + blob[kv_wire._HEAD.size:])


def test_wire_pack_validates_leaves():
    src = _payload(CODEC_INT8)
    del src.leaves["vs"]
    with pytest.raises(KVWireError, match="lacks leaves"):
        kv_wire.pack(src)
    bad = _payload(CODEC_RAW)
    bad.leaves["v"] = bad.leaves["v"][:, :1]         # wrong page count
    with pytest.raises(KVWireError, match="shape"):
        kv_wire.pack(bad)


def test_resolve_codec_refuses_transcoding(setup):
    cfg, _ = setup
    cfg8 = dataclasses.replace(cfg, kv_int8=True)
    assert kv_wire.resolve_codec("auto", cfg) == CODEC_RAW
    assert kv_wire.resolve_codec("auto", cfg8) == CODEC_INT8
    assert kv_wire.resolve_codec("bf16", cfg) == CODEC_RAW
    assert kv_wire.resolve_codec("int8", cfg8) == CODEC_INT8
    with pytest.raises(ValueError, match="storage format"):
        kv_wire.resolve_codec("int8", cfg)           # pool is bf16
    with pytest.raises(ValueError, match="storage format"):
        kv_wire.resolve_codec("bf16", cfg8)          # pool is int8


# -- cluster plumbing: peers, handoffs, registry ------------------------------

def test_parse_peers():
    peers = parse_peers(
        "p0=prefill@http://10.0.0.1:8000#10.0.0.1:9000, "
        "d0=decode@http://10.0.0.2:8000")
    assert peers == [
        ("p0", "prefill", "http://10.0.0.1:8000", "10.0.0.1:9000"),
        ("d0", "decode", "http://10.0.0.2:8000", None),
    ]
    assert parse_peers(None) == [] and parse_peers("") == []
    with pytest.raises(ValueError, match="name=role@url"):
        parse_peers("p0@http://x")                   # missing role
    with pytest.raises(ValueError, match="role"):
        parse_peers("p0=router@http://x")            # unknown role


def test_handoff_table_capacity_and_ttl():
    table = HandoffTable(capacity=2, ttl_s=60.0)
    first = table.put(b"one")
    second = table.put(b"two")
    third = table.put(b"three")                      # evicts the oldest
    assert len(table) == 2
    assert table.get(third) == b"three" and table.get(second) == b"two"
    with pytest.raises(KeyError):
        table.get(first)
    table.pop(third)
    assert len(table) == 1

    brief = HandoffTable(capacity=4, ttl_s=0.02)
    handoff = brief.put(b"blob")
    time.sleep(0.05)
    with pytest.raises(KeyError, match="expired"):
        brief.get(handoff)


class _FakeTransport:
    kind = "fake"

    def __init__(self, up=True, circuit_open=False):
        self.up = up
        self.circuit_open = circuit_open

    def available(self):
        return not self.circuit_open

    def health_check(self):
        return {"status": "UP" if self.up else "DOWN"}

    def describe(self):
        return {"kind": self.kind}


def test_registry_routes_by_role_round_robin():
    cluster = ClusterRegistry()
    cluster.register("p0", "prefill", _FakeTransport())
    cluster.register("d0", "decode", _FakeTransport())
    cluster.register("d1", "decode", _FakeTransport())
    with pytest.raises(ValueError, match="role"):
        cluster.register("x", "router", _FakeTransport())
    with pytest.raises(ValueError, match="already registered"):
        cluster.register("p0", "prefill", _FakeTransport())

    assert cluster.pick(ROLE_PREFILL).name == "p0"
    picked = [cluster.pick(ROLE_DECODE).name for _ in range(4)]
    assert sorted(set(picked)) == ["d0", "d1"]       # round-robin over both
    assert cluster.roles() == {"prefill": ["p0"], "decode": ["d0", "d1"]}

    # a ``both`` replica serves either phase
    solo = ClusterRegistry()
    solo.register("m0", "both", _FakeTransport())
    assert solo.pick(ROLE_PREFILL).name == "m0"
    assert solo.pick(ROLE_DECODE).name == "m0"


def test_registry_skips_open_circuits_and_draining():
    cluster = ClusterRegistry()
    cluster.register("d0", "decode", _FakeTransport(circuit_open=True))
    with pytest.raises(NoReplicaAvailable) as err:
        cluster.pick(ROLE_DECODE)
    assert err.value.status_code == 503

    cluster.register("d1", "decode", _FakeTransport())
    assert cluster.pick(ROLE_DECODE).name == "d1"
    assert asyncio.run(cluster.drain("d1")) is True  # idle: drains at once
    with pytest.raises(NoReplicaAvailable):
        cluster.pick(ROLE_DECODE)
    cluster.resume("d1")
    assert cluster.pick(ROLE_DECODE).name == "d1"


def test_cluster_health_is_role_aware():
    cluster = ClusterRegistry()
    cluster.register("p0", "prefill", _FakeTransport())
    assert cluster.health_check()["status"] == "DOWN"   # no decode capacity
    cluster.register("d0", "decode", _FakeTransport())
    health = cluster.health_check()
    assert health["status"] == "UP"
    assert health["details"]["roles"] == {"prefill": ["p0"],
                                          "decode": ["d0"]}
    asyncio.run(cluster.drain("d0"))
    assert cluster.health_check()["status"] == "DOWN"   # decode tier gone


# -- tentpole: disagg token identity ------------------------------------------

async def _monolithic(cfg, params, requests, prefix_cache=False):
    engine, _ = _make_engine(cfg, params, paged_kv=True, kv_page=4,
                             prefix_cache=prefix_cache)
    await engine.start()
    try:
        outs = []
        for prompt, budget, sampling in requests:
            outs.append(await asyncio.wait_for(engine.generate(
                prompt, max_new_tokens=budget, sampling=sampling), 60.0))
        return outs
    finally:
        await engine.stop()


async def _disagg(cfg, params, requests, tracer=None, prefix_cache=False):
    """1 prefill + 1 decode replica behind the router; the prefill
    replica runs DENSE (export reads the small cache, never a pool) —
    the decode replica is the only paged engine in the topology."""
    prefill_eng, _ = _make_engine(cfg, params, kv_page=4, tracer=tracer)
    decode_eng, _ = _make_engine(cfg, params, paged_kv=True, kv_page=4,
                                 tracer=tracer, prefix_cache=prefix_cache)
    cluster = ClusterRegistry()
    cluster.register("p0", ROLE_PREFILL, InProcTransport(prefill_eng))
    cluster.register("d0", ROLE_DECODE, InProcTransport(decode_eng))
    router = DisaggRouter(cluster, tracer=tracer)
    await decode_eng.start()                 # prefill needs no engine loop
    try:
        outs = []
        for prompt, budget, sampling in requests:
            outs.append(await asyncio.wait_for(router.generate(
                prompt, max_new_tokens=budget, sampling=sampling), 60.0))
        return outs, prefill_eng, decode_eng, router
    finally:
        await decode_eng.stop()


@pytest.mark.parametrize("kv_int8,prefix_cache", [
    (False, False),     # bf16 wire, prefix cache off
    (True, False),      # int8 + scale planes on the wire
    (False, True),      # monolithic ref serves its repeats via the
                        # prefix cache; disagg must still match it
])
def test_disagg_greedy_token_identity(setup, kv_int8, prefix_cache):
    """The acceptance criterion: identical greedy streams through the
    split topology, with ZERO prefill dispatches on the decode replica —
    for both wire codecs (bf16 raw, int8 + scales), with the prefix
    cache on and off."""
    cfg, params = setup
    if kv_int8:
        cfg = dataclasses.replace(cfg, kv_int8=True)
    requests = [([1, 2, 3, 4, 5], 8, None),
                (list(range(1, 11)), 8, None),       # 16-bucket, 3 pages
                ([9, 8, 7], 6, None),
                ([1, 2, 3, 4, 5], 8, None)]          # slot churn / cache hit

    ref = asyncio.run(_monolithic(cfg, params, requests,
                                  prefix_cache=prefix_cache))
    outs, prefill_eng, decode_eng, router = asyncio.run(
        _disagg(cfg, params, requests, prefix_cache=prefix_cache))
    assert outs == ref
    assert all(len(out) == budget for out, (_, budget, _)
               in zip(outs, requests))

    decode_stats = decode_eng.stats()
    assert decode_stats["prefill_bucket_tokens"] == 0   # zero re-prefill
    assert decode_stats["kv_adoptions"] == len(requests)
    prefill_stats = prefill_eng.stats()
    assert prefill_stats["kv_exports"] == len(requests)
    assert prefill_stats["prefill_bucket_tokens"] > 0
    assert router.stats()["requests"] == len(requests)
    assert router.stats()["bytes_shipped"] > 0

    # the wire cost lands on the decode replica's flight records
    recent = decode_eng.recorder.snapshot()["recent"]
    assert len(recent) == len(requests)
    assert all(row["kv_transfer_bytes"] > 0 for row in recent)


def test_disagg_sampled_identity_with_explicit_seed(setup):
    """The exported payload carries the advanced PRNG key, so *sampled*
    decode continues bitwise-identically too. Seeds must be explicit:
    ``Sampling(seed=None)`` draws fresh entropy per construction."""
    cfg, params = setup
    sampled = lambda: Sampling(temperature=0.9, top_k=7, seed=1234)
    requests = [([1, 2, 3, 4, 5], 8, sampled()),
                (list(range(1, 9)), 8, sampled())]

    ref = asyncio.run(_monolithic(cfg, params, requests))
    outs, _, decode_eng, _ = asyncio.run(_disagg(cfg, params, requests))
    assert outs == ref
    assert decode_eng.stats()["prefill_bucket_tokens"] == 0


def test_disagg_trace_stitches_across_the_hop(setup):
    """One trace spans the split: the router's ``kv_transfer`` span
    (bytes + both replica names) parents the prefill replica's
    ``prefill.export`` and the decode replica's ``kv_adopt`` via the
    forwarded traceparent."""
    cfg, params = setup
    exporter = ListExporter()
    tracer = Tracer(exporter=exporter)
    outs, _, _, _ = asyncio.run(_disagg(
        cfg, params, [([1, 2, 3], 4, None)], tracer=tracer))
    tracer.shutdown()
    assert len(outs[0]) == 4

    (transfer,) = exporter.find("kv_transfer")
    assert int(transfer.attributes["bytes"]) > 0
    assert transfer.attributes["prefill_replica"] == "p0"
    assert transfer.attributes["decode_replica"] == "d0"
    assert transfer.attributes["transport"] == "inproc"
    (adopt,) = exporter.find("kv_adopt")
    assert adopt.trace_id == transfer.trace_id       # joined via traceparent
    assert int(adopt.attributes["transfer_bytes"]) > 0


def test_adopt_rejects_geometry_and_codec_mismatch(setup):
    cfg, params = setup
    engine, _ = _make_engine(cfg, params, paged_kv=True, kv_page=8)

    async def run():
        source, _ = _make_engine(cfg, params, kv_page=4)
        payload = await source.prefill_export([1, 2, 3, 4, 5])
        with pytest.raises(KVWireError, match="page size"):
            await engine.adopt_kv(payload, 4)        # page 4 into kv_page 8
        wrong = _payload(CODEC_INT8, page=8, n_layers=cfg.n_layers,
                         n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim)
        with pytest.raises(KVWireError, match="codec"):
            await engine.adopt_kv(wrong, 4)          # int8 into a bf16 pool
        alien = _payload(CODEC_RAW, page=8)          # 2-layer toy geometry
        with pytest.raises(KVWireError, match="geometry"):
            await engine.adopt_kv(alien, 4)

    asyncio.run(run())


# -- acceptance: drain is lossless --------------------------------------------

def test_decode_drain_finishes_streams_and_releases_pages(setup):
    """DRAINING stops routing immediately, in-flight streams run to
    completion, and the decode pool's free list returns to its pre-test
    level — migrated pages release through normal slot teardown."""
    cfg, params = setup

    async def run():
        prefill_eng, _ = _make_engine(cfg, params, kv_page=4)
        decode_eng, _ = _make_engine(cfg, params, paged_kv=True, kv_page=4)
        cluster = ClusterRegistry()
        cluster.register("p0", ROLE_PREFILL, InProcTransport(prefill_eng))
        cluster.register("d0", ROLE_DECODE, InProcTransport(decode_eng))
        router = DisaggRouter(cluster)
        await decode_eng.start()
        try:
            baseline = decode_eng._pool.free_pages
            stream = await router.generate_stream([1, 2, 3, 4, 5],
                                                  max_new_tokens=6)
            tokens = [await asyncio.wait_for(stream.__anext__(), 60.0)]
            drain_task = asyncio.create_task(
                cluster.drain("d0", timeout_s=30.0))
            await asyncio.sleep(0)                   # DRAINING is immediate
            with pytest.raises(NoReplicaAvailable):
                cluster.pick(ROLE_DECODE)
            async for token in stream:               # in-flight finishes
                tokens.append(token)
            assert len(tokens) == 6
            assert await asyncio.wait_for(drain_task, 30.0) is True
            for _ in range(200):                     # slot teardown lands
                if decode_eng._pool.free_pages == baseline:
                    break
                await asyncio.sleep(0.02)
            assert decode_eng._pool.free_pages == baseline
            cluster.resume("d0")
            assert cluster.pick(ROLE_DECODE).name == "d0"
        finally:
            await decode_eng.stop()

    asyncio.run(run())
