from gofr_tpu.trace.tracer import (
    ListExporter,
    Span,
    Tracer,
    current_span,
    extract_traceparent,
    format_traceparent,
    new_tracer,
)

__all__ = [
    "ListExporter",
    "Span",
    "Tracer",
    "current_span",
    "extract_traceparent",
    "format_traceparent",
    "new_tracer",
]
