"""Resilience depth tests: Kafka client across a broker restart, health
aggregation DEGRADED propagation, live-server Response/Redirect/
FileResponse rendering, and websocket close handshake — reference
datasource/pubsub/kafka and container/health test coverage."""

import asyncio
import json

import pytest

from gofr_tpu.config import MapConfig
from gofr_tpu.container import new_mock_container
from tests.test_pubsub_wire import FakeKafkaBroker
from tests.util import http_request, make_app, run, serving


# -- kafka across broker restart ----------------------------------------------

def test_kafka_publish_recovers_after_broker_restart():
    from gofr_tpu.datasource.pubsub.kafka import KafkaClient
    broker = FakeKafkaBroker()
    container = new_mock_container()
    client = KafkaClient(
        MapConfig({"PUBSUB_BROKER": f"127.0.0.1:{broker.port}",
                   "CONSUMER_ID": "workers",
                   "KAFKA_FETCH_MAX_WAIT_MS": "20"}),
        container.logger, container.metrics)
    try:
        client.create_topic("orders")
        client.publish("orders", b"before")
        assert broker.logs[("orders", 0)][-1][1] == b"before"
        # kill the broker: the client's socket dies mid-life
        port = broker.port
        broker.stop()
        with pytest.raises(Exception):
            client.publish("orders", b"into the void")
        # new broker on the SAME port (restart); client must reconnect
        broker = FakeKafkaBroker(port=port)
        deadline = 50
        for _ in range(deadline):
            try:
                client.publish("orders", b"after")
                break
            except Exception:
                import time
                time.sleep(0.1)
        else:
            pytest.fail("client never recovered after broker restart")
        assert broker.logs[("orders", 0)][-1][1] == b"after"
    finally:
        client.close()
        broker.stop()


def test_kafka_subscriber_survives_broker_restart():
    """The per-topic poller must back off and retry through an outage —
    not die on the first failed fetch (code-review r3 finding)."""
    from gofr_tpu.datasource.pubsub.kafka import KafkaClient
    broker = FakeKafkaBroker()
    container = new_mock_container()
    client = KafkaClient(
        MapConfig({"PUBSUB_BROKER": f"127.0.0.1:{broker.port}",
                   "CONSUMER_ID": "workers",
                   "KAFKA_FETCH_MAX_WAIT_MS": "20"}),
        container.logger, container.metrics)
    try:
        client.create_topic("events")
        client.publish("events", b"first")

        async def scenario():
            nonlocal broker
            first = await asyncio.wait_for(client.subscribe("events"), 5.0)
            assert first.value == b"first"
            # outage: broker gone for a moment, poller keeps retrying
            port = broker.port
            broker.stop()
            await asyncio.sleep(0.5)
            broker = FakeKafkaBroker(port=port)
            # the restarted fake broker lost its log; republish
            for _ in range(50):
                try:
                    client.publish("events", b"second")
                    break
                except Exception:
                    await asyncio.sleep(0.1)
            second = await asyncio.wait_for(client.subscribe("events"),
                                            15.0)
            assert second is not None and second.value == b"second"

        run(scenario())
    finally:
        client.close()
        broker.stop()


# -- container health aggregation ---------------------------------------------

def test_health_degrades_on_single_datasource_failure():
    container = new_mock_container()

    class _DeadRedis:
        def health_check(self):
            return {"status": "DOWN", "details": {"error": "gone"}}

        def close(self):
            pass

    container.redis = _DeadRedis()
    doc = container.health()
    assert doc["status"] == "DEGRADED"
    assert doc["redis"]["status"] == "DOWN"
    assert doc["pubsub"]["status"] == "UP"    # others unaffected


def test_health_survives_throwing_health_check():
    container = new_mock_container()

    class _Exploding:
        def health_check(self):
            raise RuntimeError("health probe crashed")

    container.mongo = _Exploding()
    doc = container.health()
    assert doc["status"] == "DEGRADED"
    assert "error" in doc["mongo"]["details"]


def test_health_over_http_reports_degraded():
    async def main():
        app = make_app()

        class _DeadSql:
            def health_check(self):
                return {"status": "DOWN", "details": {}}

            def close(self):
                pass

        app.container.sql = _DeadSql()
        async with serving(app) as port:
            health = await http_request(port, "GET", "/.well-known/health")
            body = health.json()
            assert body["status"] == "DEGRADED"
            assert body["sql"]["status"] == "DOWN"
    run(main())


# -- live-server response types -----------------------------------------------

def test_response_types_over_live_server():
    from gofr_tpu.http.response import FileResponse, Raw, Redirect, Response

    async def main():
        app = make_app()
        app.get("/created", lambda ctx: Response(
            {"id": 9}, status_code=202, headers={"X-Job": "j-9"}))
        app.get("/raw", lambda ctx: Raw({"no": "envelope"}))
        app.get("/file", lambda ctx: FileResponse(
            content=b"%PDF-1.4 fake", content_type="application/pdf"))
        app.get("/old", lambda ctx: Redirect("/new"))
        app.get("/bytes", lambda ctx: Response(
            b"\x00\x01binary", content_type="application/octet-stream"))
        async with serving(app) as port:
            created = await http_request(port, "GET", "/created")
            assert created.status == 202
            assert created.headers["x-job"] == "j-9"
            assert created.json()["id"] == 9      # Response: no envelope

            raw = await http_request(port, "GET", "/raw")
            assert raw.json() == {"no": "envelope"}

            pdf = await http_request(port, "GET", "/file")
            assert pdf.headers["content-type"] == "application/pdf"
            assert pdf.body.startswith(b"%PDF")

            moved = await http_request(port, "GET", "/old")
            assert moved.status in (301, 302, 307, 308)
            assert moved.headers["location"] == "/new"

            blob = await http_request(port, "GET", "/bytes")
            assert blob.body == b"\x00\x01binary"
            assert blob.headers["content-type"] == \
                "application/octet-stream"
    run(main())


# -- websocket close handshake ------------------------------------------------

def test_websocket_close_handshake():
    """Client CLOSE gets the server's CLOSE reply and the connection ends
    cleanly (RFC 6455 §5.5.1)."""
    import base64
    import os as _os

    from gofr_tpu.websocket.frames import (OP_CLOSE, decode_frame,
                                           encode_frame)

    async def main():
        app = make_app()

        async def echo(ctx):
            while True:
                message = await ctx.read_message()
                if message is None:
                    return
                await ctx.write_message(message)

        app.websocket("/ws", echo)
        async with serving(app) as port:
            key = base64.b64encode(_os.urandom(16)).decode()
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            writer.write((
                f"GET /ws HTTP/1.1\r\nHost: x\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n").encode())
            await writer.drain()
            await reader.readuntil(b"\r\n\r\n")
            writer.write(encode_frame(OP_CLOSE, b"\x03\xe8", mask=True))
            await writer.drain()
            buffer = await asyncio.wait_for(reader.read(64), 10.0)
            frame = decode_frame(buffer)
            assert frame is not None
            opcode = frame[0]
            assert opcode == OP_CLOSE
            # server closes the TCP side after the handshake
            rest = await asyncio.wait_for(reader.read(64), 10.0)
            assert rest == b""
            writer.close()
    run(main())
