"""Request logging middleware with correlation IDs.

Capability parity with ``pkg/gofr/http/middleware/logger.go``
(StatusResponseWriter 16-24, RequestLog with trace id + microsecond latency
27-42, X-Correlation-ID response header 74-77, outermost panic recovery →
500 JSON 127-150).
"""

from __future__ import annotations

import json
import time

from gofr_tpu.http.router import Middleware, WireHandler
from gofr_tpu.logging import Logger


class RequestLog:
    """Structured request log entry; pretty-printable (logger.go:44-61)."""

    def __init__(self, trace_id: str, method: str, uri: str,
                 status: int, duration_us: int, remote: str):
        self.trace_id = trace_id
        self.method = method
        self.uri = uri
        self.status = status
        self.duration_us = duration_us
        self.remote = remote

    def to_log(self):
        return vars(self)

    def pretty_print(self, writer) -> None:
        color = "\033[32m" if self.status < 400 else (
            "\033[33m" if self.status < 500 else "\033[31m")
        writer.write(
            f"  {color}{self.status}\033[0m {self.method:<7} "
            f"{self.uri} {self.duration_us}µs\n"
        )


def logging_middleware(logger: Logger) -> Middleware:
    def middleware(next_handler: WireHandler) -> WireHandler:
        async def handle(request):
            start = time.perf_counter()
            span = request.context_values.get("span")
            trace_id = span.trace_id if span is not None else ""
            try:
                status, headers, body = await next_handler(request)
            except Exception as exc:  # last-resort panic recovery
                logger.error("panic recovered in handler: %r", exc,
                             method=request.method, uri=request.path)
                status = 500
                headers = {"Content-Type": "application/json"}
                body = json.dumps(
                    {"error": {"message": "some unexpected error has occurred"}}
                ).encode()
            if trace_id:
                headers.setdefault("X-Correlation-ID", trace_id)

            def emit(status: int) -> None:
                duration_us = int((time.perf_counter() - start) * 1e6)
                entry = RequestLog(trace_id, request.method, request.path,
                                   status, duration_us, request.remote_addr)
                if status >= 500:
                    logger.error("request failed", payload=entry)
                else:
                    logger.info("request", payload=entry)

            from gofr_tpu.http.response import StreamBody
            if isinstance(body, StreamBody):
                # log when the stream finishes: true duration, and a 500
                # if the producer died mid-stream
                body.on_complete(
                    lambda ok, messages, status=status:
                        emit(status if ok else 500))
            else:
                emit(status)
            return status, headers, body
        return handle
    return middleware
