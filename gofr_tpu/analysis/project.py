"""Whole-program symbol table + cross-module call graph (graftcheck v2).

The module-local graph (``callgraph.py``) stops at the file boundary, so
every rule that needs reachability — "is this blocking call reachable
from an ``async def``?" — went blind the moment a helper moved to its
own module. :class:`ProjectGraph` stitches the per-module graphs into
one program-wide graph:

- **symbol table** — every scanned module under a dotted name derived
  from its repo-relative path, every module-level function, every class
  (with bases and methods) indexed project-wide;
- **import edges** — ``from x import y`` / ``import x`` call sites
  resolve through each module's alias table, then through the symbol
  table by *dotted-suffix match* (fixture packages and the real tree
  rarely share an import root with the scan root);
- **typed attribute edges** — ``self.engine.submit(...)`` resolves when
  the receiver's class is assignable from what the tree actually
  constructs: ``self.engine = GenerationEngine(...)`` in a constructor,
  an annotated parameter (``pool: PagePool``), an ``AnnAssign``, or a
  parameter whose annotation names a project class. Attribute chains
  resolve transitively (``self.container.engine.tick`` walks two class
  attribute tables);
- **duck-typed edges** — a method name defined by *exactly one* project
  class (and not a ubiquitous container/IO verb) resolves to that class:
  the container/engine plumbing passes duck-typed collaborators around
  without annotations, and a unique name is as good as a type;
- **loop-callback edges** — ``call_soon``/``call_later``/
  ``add_done_callback`` targets run on the loop, exactly as in the
  module-local graph.

Thread hops stay invisible by construction: a callable *passed* to
``run_in_executor`` / ``asyncio.to_thread`` is an argument, not a call,
so offloaded work falls out of every reachability query for free.

Known blind spots (documented in docs/references/static-analysis.md):
calls through dynamic dispatch tables, ``getattr`` strings, decorators
that rebind, re-exports through ``__init__`` shims, and duck-typed
names shared by several classes (ambiguity drops the edge — the graph
is deliberately conservative toward *fewer* edges, never wrong ones).

``cross_module=False`` disables every cross-module mechanism and
reproduces the v1 module-local behavior exactly — tier1 regression
tests pin a cross-module event-loop block that project mode catches and
local mode provably misses.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from gofr_tpu.analysis.callgraph import CallGraph, FunctionNode
from gofr_tpu.analysis.engine import ModuleInfo

# (module relpath, function qualname) — the project-wide function id
FuncRef = Tuple[str, str]
# (module relpath, class qualname)
ClassRef = Tuple[str, str]

# method names too generic to duck-type: one project class defining
# ``get`` must not capture every ``obj.get(...)`` in the tree
_COMMON_METHODS = {
    "get", "set", "put", "pop", "push", "add", "remove", "append",
    "extend", "insert", "clear", "copy", "update", "keys", "values",
    "items", "close", "open", "read", "write", "send", "recv", "flush",
    "run", "start", "stop", "reset", "submit", "result", "done",
    "cancel", "wait", "notify", "join", "acquire", "release", "item",
    "count", "index", "sort", "split", "strip", "format", "encode",
    "decode", "register", "stats", "setdefault", "render", "match",
    "group", "search", "exists", "mkdir", "touch", "next", "emit",
}

_LOOP_CALLBACK_ARG = {
    "call_soon": 0,
    "call_soon_threadsafe": 0,
    "call_later": 1,
    "call_at": 1,
    "add_done_callback": 0,
}


def module_dotted_name(relpath: str) -> str:
    """``gofr_tpu/tpu/generate.py`` → ``gofr_tpu.tpu.generate``;
    package ``__init__.py`` files name the package itself."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") \
        else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ClassInfo:
    """One class definition: bases (unresolved dotted names), method
    table, and the inferred types of its instance attributes."""

    __slots__ = ("ref", "name", "qualname", "node", "bases",
                 "methods", "attr_types")

    def __init__(self, ref: ClassRef, node: ast.ClassDef, qualname: str):
        self.ref = ref
        self.name = node.name
        self.qualname = qualname
        self.node = node
        self.bases: List[str] = []      # dotted names, resolved lazily
        self.methods: Dict[str, str] = {}   # method name -> fn qualname
        self.attr_types: Dict[str, ClassRef] = {}


class ProjectGraph:
    """Project-wide function table + call edges over ``modules``.

    ``cross_module=False`` keeps only module-local edges (the v1
    behavior); rules use it to regression-test what interprocedural
    analysis buys.
    """

    def __init__(self, modules: Sequence[ModuleInfo],
                 cross_module: bool = True):
        self.cross_module = cross_module
        self.modules: Dict[str, ModuleInfo] = {
            m.relpath: m for m in modules}
        self.graphs: Dict[str, CallGraph] = {
            rel: CallGraph(m) for rel, m in self.modules.items()}
        self.functions: Dict[FuncRef, FunctionNode] = {}
        self._fn_module: Dict[int, FuncRef] = {}   # id(fn node) -> ref
        for rel, graph in self.graphs.items():
            for qual, fn in graph.functions.items():
                self.functions[(rel, qual)] = fn
                self._fn_module[id(fn.node)] = (rel, qual)

        # dotted module names, exact + suffix index
        self._dotted: Dict[str, str] = {}
        for rel in self.modules:
            self._dotted.setdefault(module_dotted_name(rel), rel)

        # class index
        self.classes: Dict[ClassRef, ClassInfo] = {}
        self._class_by_name: Dict[str, List[ClassRef]] = {}
        self._method_index: Dict[str, List[ClassRef]] = {}
        for rel, module in self.modules.items():
            self._collect_classes(rel, module)
        if cross_module:
            for info in self.classes.values():
                self._infer_attr_types(info)

        # call edges, lifted project-wide
        self._edges: Dict[FuncRef, List[Tuple[FuncRef, ast.Call]]] = {}
        self._callers: Dict[FuncRef, List[Tuple[FuncRef, ast.Call]]] = {}
        self._local_env_cache: Dict[int, Dict[str, ClassRef]] = {}
        for ref in self.functions:
            self._edges[ref] = list(self._build_edges(ref))
        for caller, edges in self._edges.items():
            for callee, site in edges:
                self._callers.setdefault(callee, []).append((caller, site))

    # -- basic accessors ----------------------------------------------------
    def module_of(self, ref: FuncRef) -> ModuleInfo:
        return self.modules[ref[0]]

    def body_nodes(self, ref: FuncRef) -> Iterable[ast.AST]:
        """A function's own executed nodes (lambdas/comprehensions in,
        nested ``def``s out) — same semantics as the module graph."""
        return self.graphs[ref[0]].body_nodes(self.functions[ref])

    def calls(self, ref: FuncRef) -> List[Tuple[FuncRef, ast.Call]]:
        return self._edges.get(ref, [])

    def callers(self, ref: FuncRef) -> List[Tuple[FuncRef, ast.Call]]:
        return self._callers.get(ref, [])

    def ref_of_node(self, fn_node: ast.AST) -> Optional[FuncRef]:
        return self._fn_module.get(id(fn_node))

    def display(self, ref: FuncRef, relative_to: str) -> str:
        """Render a function for chain messages: bare qualname within
        the same module, ``stem.qualname`` across modules."""
        rel, qual = ref
        if rel == relative_to:
            return qual
        stem = rel.rsplit("/", 1)[-1]
        stem = stem[:-3] if stem.endswith(".py") else stem
        return f"{stem}.{qual}"

    # -- reachability -------------------------------------------------------
    def reachable(self, roots: Iterable[FuncRef]
                  ) -> Dict[FuncRef, List[FuncRef]]:
        """Map of function → call chain from the nearest root, for every
        function reachable from ``roots`` along call edges. Chains never
        cross a thread hop (executor-passed callables have no edge)."""
        chains: Dict[FuncRef, List[FuncRef]] = {}
        stack: List[Tuple[FuncRef, List[FuncRef]]] = [
            (ref, [ref]) for ref in sorted(roots)]
        stack.reverse()
        while stack:
            ref, chain = stack.pop()
            if ref in chains:
                continue
            chains[ref] = chain
            for callee, _site in self.calls(ref):
                if callee not in chains:
                    stack.append((callee, chain + [callee]))
        return chains

    def async_roots(self) -> List[FuncRef]:
        return [ref for ref, fn in self.functions.items() if fn.is_async]

    # -- class collection ---------------------------------------------------
    def _collect_classes(self, rel: str, module: ModuleInfo) -> None:
        def walk(tree: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(tree):
                if isinstance(child, ast.ClassDef):
                    qual = f"{prefix}{child.name}"
                    ref = (rel, qual)
                    info = ClassInfo(ref, child, qual)
                    for base in child.bases:
                        dotted = module.dotted(base)
                        if dotted:
                            info.bases.append(dotted)
                    graph = self.graphs[rel]
                    for item in child.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            mqual = f"{qual}.{item.name}"
                            if mqual in graph.functions:
                                info.methods[item.name] = mqual
                    self.classes[ref] = info
                    self._class_by_name.setdefault(
                        child.name, []).append(ref)
                    for name in info.methods:
                        self._method_index.setdefault(
                            name, []).append(ref)
                    walk(child, prefix=f"{qual}.")
                elif not isinstance(child, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                    walk(child, prefix=prefix)
        walk(module.tree, prefix="")

    # -- symbol resolution --------------------------------------------------
    def _resolve_module(self, dotted: str) -> Optional[str]:
        """Exact dotted-name match, else unique suffix match — fixture
        packages import as ``from pkg.mod import f`` while the scan
        names them ``tests.analysis_fixtures...pkg.mod``."""
        rel = self._dotted.get(dotted)
        if rel is not None:
            return rel
        suffix = "." + dotted
        hits = [r for d, r in self._dotted.items() if d.endswith(suffix)]
        return hits[0] if len(hits) == 1 else None

    def resolve_symbol(self, dotted: str
                       ) -> Optional[Tuple[str, object]]:
        """Resolve ``pkg.mod.sym`` to ``("func", FuncRef)`` or
        ``("class", ClassRef)``. Returns None when the module part does
        not uniquely match a scanned module or the symbol is absent."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            rel = self._resolve_module(".".join(parts[:i]))
            if rel is None:
                continue
            rest = parts[i:]
            if len(rest) != 1:
                # pkg.mod.Class.method — not resolved (blind spot)
                return None
            sym = rest[0]
            if sym in self.graphs[rel].functions:
                return ("func", (rel, sym))
            if (rel, sym) in self.classes:
                return ("class", (rel, sym))
            return None
        return None

    def _resolve_class_name(self, module: ModuleInfo,
                            node: ast.AST) -> Optional[ClassRef]:
        """A constructor/annotation expression → project class."""
        dotted = module.dotted(node)
        if dotted is None:
            return None
        # same-module class first (bare name, no import alias)
        if "." not in dotted and (module.relpath, dotted) in self.classes:
            return (module.relpath, dotted)
        if "." in dotted:
            hit = self.resolve_symbol(dotted)
            if hit is not None and hit[0] == "class":
                return hit[1]  # type: ignore[return-value]
            # ``from x import Cls`` leaves dotted = "x.Cls"; suffix on
            # the class name alone as last resort
            dotted = dotted.rsplit(".", 1)[-1]
        refs = self._class_by_name.get(dotted, [])
        return refs[0] if len(refs) == 1 else None

    # -- type inference -----------------------------------------------------
    def _infer_attr_types(self, info: ClassInfo) -> None:
        rel = info.ref[0]
        module = self.modules[rel]
        graph = self.graphs[rel]
        for mname, mqual in info.methods.items():
            fn = graph.functions[mqual]
            ann_params = self._param_annotations(module, fn.node)
            for node in graph.body_nodes(fn):
                target = None
                value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target = node.target
                    ann = self._resolve_class_name(module, node.annotation)
                    if ann is not None and _is_self_attr(target):
                        info.attr_types.setdefault(target.attr, ann)
                    continue
                if not _is_self_attr(target):
                    continue
                inferred = None
                if isinstance(value, ast.Call):
                    inferred = self._resolve_class_name(module, value.func)
                elif isinstance(value, ast.Name):
                    inferred = ann_params.get(value.id)
                if inferred is not None:
                    info.attr_types.setdefault(target.attr, inferred)

    def _param_annotations(self, module: ModuleInfo,
                           fn_node: ast.AST) -> Dict[str, ClassRef]:
        out: Dict[str, ClassRef] = {}
        args = fn_node.args
        for arg in (list(args.posonlyargs) + list(args.args)
                    + list(args.kwonlyargs)):
            if arg.annotation is not None:
                ref = self._resolve_class_name(module, arg.annotation)
                if ref is not None:
                    out[arg.arg] = ref
        return out

    def _local_env(self, ref: FuncRef) -> Dict[str, ClassRef]:
        """name → class for a function's locals: annotated params,
        ``x = Cls(...)`` constructor assigns, ``x: Cls`` AnnAssigns."""
        fn = self.functions[ref]
        cached = self._local_env_cache.get(id(fn.node))
        if cached is not None:
            return cached
        module = self.modules[ref[0]]
        graph = self.graphs[ref[0]]
        env = dict(self._param_annotations(module, fn.node))
        for node in graph.body_nodes(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                inferred = self._resolve_class_name(module, node.value.func)
                if inferred is not None:
                    env.setdefault(node.targets[0].id, inferred)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                inferred = self._resolve_class_name(module, node.annotation)
                if inferred is not None:
                    env.setdefault(node.target.id, inferred)
        self._local_env_cache[id(fn.node)] = env
        return env

    def class_of_function(self, ref: FuncRef) -> Optional[ClassInfo]:
        fn = self.functions[ref]
        if fn.class_name is None:
            return None
        qual = ref[1]
        if "." not in qual:
            return None
        return self.classes.get((ref[0], qual.rsplit(".", 1)[0]))

    def type_of(self, ref: FuncRef, expr: ast.AST) -> Optional[ClassRef]:
        """Infer the class of a receiver expression inside ``ref``:
        locals/params by assignment or annotation, ``self.attr`` through
        the class attribute table, chains transitively."""
        if not self.cross_module:
            return None
        if isinstance(expr, ast.Name):
            if expr.id in ("self", "cls"):
                info = self.class_of_function(ref)
                return info.ref if info is not None else None
            return self._local_env(ref).get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.type_of(ref, expr.value)
            if base is None:
                return None
            info = self.classes.get(base)
            while info is not None:
                if expr.attr in info.attr_types:
                    return info.attr_types[expr.attr]
                info = self._first_base(info)
            return None
        return None

    def _first_base(self, info: ClassInfo) -> Optional[ClassInfo]:
        for dotted in info.bases:
            ref = self._resolve_class_name(
                self.modules[info.ref[0]],
                ast.parse(dotted, mode="eval").body)
            if ref is not None and ref != info.ref:
                return self.classes.get(ref)
        return None

    def _lookup_method(self, cref: ClassRef,
                       name: str) -> Optional[FuncRef]:
        seen: Set[ClassRef] = set()
        info = self.classes.get(cref)
        while info is not None and info.ref not in seen:
            seen.add(info.ref)
            mqual = info.methods.get(name)
            if mqual is not None:
                return (info.ref[0], mqual)
            info = self._first_base(info)
        return None

    # -- edge construction --------------------------------------------------
    def _build_edges(self, ref: FuncRef
                     ) -> Iterable[Tuple[FuncRef, ast.Call]]:
        rel, _qual = ref
        graph = self.graphs[rel]
        fn = self.functions[ref]
        locally_resolved: Set[int] = set()
        for callee_qual, site in fn.calls:
            locally_resolved.add(id(site))
            yield ((rel, callee_qual), site)
        if not self.cross_module:
            return
        module = self.modules[rel]
        for node in graph.body_nodes(fn):
            if not isinstance(node, ast.Call) or id(node) in locally_resolved:
                continue
            callee = self._resolve_cross(module, ref, node)
            if callee is not None:
                yield (callee, node)
            target = self._cross_callback_target(module, ref, node)
            if target is not None:
                yield (target, node)

    def _resolve_cross(self, module: ModuleInfo, ref: FuncRef,
                       call: ast.Call) -> Optional[FuncRef]:
        func = call.func
        if isinstance(func, ast.Name):
            dotted = module.import_aliases.get(func.id)
            if dotted and "." in dotted:
                hit = self.resolve_symbol(dotted)
                if hit is not None and hit[0] == "func":
                    return hit[1]  # type: ignore[return-value]
            return None
        if not isinstance(func, ast.Attribute):
            return None
        # typed receiver: self.engine.submit(...), pool.alloc(...)
        rtype = self.type_of(ref, func.value)
        if rtype is not None:
            hit = self._lookup_method(rtype, func.attr)
            if hit is not None:
                return hit
        # module attribute: helpers.prep(...) with ``import helpers``
        dotted = module.dotted(func)
        if dotted is not None and "." in dotted:
            hit = self.resolve_symbol(dotted)
            if hit is not None and hit[0] == "func":
                return hit[1]  # type: ignore[return-value]
        # duck-typed: a method name only one project class defines
        if func.attr not in _COMMON_METHODS \
                and not func.attr.startswith("__"):
            owners = self._method_index.get(func.attr, [])
            if len(owners) == 1:
                return self._lookup_method(owners[0], func.attr)
        return None

    def _cross_callback_target(self, module: ModuleInfo, ref: FuncRef,
                               call: ast.Call) -> Optional[FuncRef]:
        """Loop-scheduled callbacks whose target is an imported
        function: ``loop.call_soon(imported_fn)`` runs on the loop."""
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        index = _LOOP_CALLBACK_ARG.get(func.attr)
        if index is None or len(call.args) <= index:
            return None
        target = call.args[index]
        if isinstance(target, ast.Name):
            dotted = module.import_aliases.get(target.id)
            if dotted and "." in dotted:
                hit = self.resolve_symbol(dotted)
                if hit is not None and hit[0] == "func":
                    return hit[1]  # type: ignore[return-value]
        return None


def _is_self_attr(node: Optional[ast.AST]) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")
