"""Live serving-state snapshot over HTTP: ``/debug/statusz``.

The flight recorder's admin surface (ISSUE 1). Where ``/metrics`` exposes
aggregates and ``/debug/profiler`` captures device traces, ``statusz``
answers the on-call question "what is the server doing *right now*": the
batcher's pending queue depths, the generation engine's admission queue and
per-slot states, KV-cache occupancy, per-device health gauges, and the
last-N request timelines (queue wait, TTFT, tokens/s, batch sizes ridden).

Registered like the profiler — ``app.enable_statusz()`` — never on by
default. Everything rendered is host-side bookkeeping: no device syncs, so
hitting the endpoint cannot perturb serving latency.
"""

from __future__ import annotations

from typing import Any, Dict


def build_status(app, recent: int = 32) -> Dict[str, Any]:
    """Assemble the statusz snapshot from whatever serving pieces the app
    actually wired: Executor and GenerationEngine both duck-type via
    ``health_check``/``statusz``; absent pieces are simply omitted."""
    container = app.container
    status: Dict[str, Any] = {
        "app": {
            "name": container.app_name,
            "version": container.app_version,
        },
    }
    # debug-surface index (ISSUE 18): every enabled /debug/* page with a
    # one-line description lives behind this link
    if getattr(app, "_debug_surfaces", None):
        status["app"]["debug_index"] = "/debug/"

    # SLO & watchdog view (ISSUE 2): windowed goodput and the degradation
    # state machine next to the queues they explain
    slo = getattr(container, "slo", None)
    if slo is not None:
        status["slo"] = slo.snapshot()
    watchdog = getattr(container, "watchdog", None)
    if watchdog is not None:
        status["watchdog"] = watchdog.statusz()

    # error-budget burn plane (ISSUE 18): per-(model, class) burn rates
    # and budget remaining — the full view (plus worst offenders) lives
    # on /debug/sloz
    plane = getattr(container, "slo_budget", None)
    if plane is not None:
        try:
            status["slo_budget"] = plane.statusz()
        except Exception as exc:   # a budget bug must not 500 statusz
            status["slo_budget"] = {"error": repr(exc)}
    offenders = getattr(container, "offenders", None)
    if offenders is not None:
        try:
            status["worst_offenders"] = offenders.snapshot(limit=8)
        except Exception as exc:
            status["worst_offenders"] = {"error": repr(exc)}

    # online auto-tuner (ISSUE 19): live operating point + guard state
    # and the last few decisions — the full candidate ledger lives on
    # /debug/tunez
    autotune = getattr(container, "autotune", None)
    if autotune is not None:
        try:
            status["autotune"] = autotune.status()
        except Exception as exc:   # a tuner bug must not 500 statusz
            status["autotune"] = {"error": repr(exc)}

    # continuous telemetry plane (ISSUE 16): compact sparkline view of
    # the time-series store plus any active anomalies — the offending
    # signal shows up both here and in the watchdog's last_reasons; the
    # full aligned series live on /debug/timez
    telemetry = getattr(container, "telemetry", None)
    if telemetry is not None:
        try:
            status["telemetry"] = telemetry.statusz()
        except Exception as exc:   # a telemetry bug must not 500 statusz
            status["telemetry"] = {"error": repr(exc)}

    # on-demand profiler (ISSUE 10): is a capture running, and where did
    # the last one land — surfaced here so trace artifacts are findable
    # without grepping logs
    profiler_state = getattr(app, "_profiler_state", None)
    if profiler_state is not None:
        from gofr_tpu.profiler import profiler_status
        status["profiler"] = profiler_status(profiler_state)

    # disaggregated cluster membership (the full fleet rollup lives on
    # /debug/clusterz; this is the local replica's registry view)
    cluster = getattr(container, "cluster", None)
    if cluster is not None:
        status["cluster"] = cluster.stats()
        router = getattr(container, "cluster_router", None)
        if router is not None:
            status["cluster"]["router"] = {
                "requests": router._requests,
                "bytes_shipped": router._bytes_shipped,
                "kv_transfer_quantiles": router.transfer_quantiles(),
            }

    batcher = getattr(container, "tpu_batcher", None)
    if batcher is not None:
        status["batcher"] = {
            "max_batch": batcher.max_batch,
            "max_delay_ms": batcher.max_delay * 1000.0,
            "queue_depths": batcher.queue_depths(),
        }

    tpu = container.tpu
    if tpu is not None:
        statusz_fn = getattr(tpu, "statusz", None)
        if statusz_fn is not None:      # GenerationEngine
            status["engine"] = statusz_fn(recent=recent)
        health_fn = getattr(tpu, "health_check", None)
        if health_fn is not None:       # device liveness + HBM gauges
            status["devices"] = health_fn()
        recorder = getattr(tpu, "recorder", None)
        if recorder is not None and "engine" not in status:
            status["requests"] = recorder.snapshot(limit=recent)
        saturation_fn = getattr(tpu, "saturation", None)
        if saturation_fn is not None:   # Executor duty-cycle/MFU/HBM view
            try:
                status["saturation"] = saturation_fn()
            except Exception as exc:
                status["saturation"] = {"error": repr(exc)}
        # zero-copy data plane (ISSUE 9): staging-slab occupancy, H2D
        # totals per path, and transfer-coalescer amortization — the
        # live twin of app_tpu_h2d_bytes_total/_seconds
        data_plane_fn = getattr(tpu, "data_plane", None)
        if data_plane_fn is not None:
            try:
                status["data_plane"] = data_plane_fn()
            except Exception as exc:
                status["data_plane"] = {"error": repr(exc)}
        # per-executable roofline attribution (ISSUE 17): ranked
        # top-offenders by device-seconds — which compiled executable
        # family is burning the device, and how far from roofline; the
        # full table lives on /debug/xlaz and /debug/workloadz
        exec_ledger = getattr(tpu, "exec_ledger", None)
        if exec_ledger is not None:
            try:
                status["executables"] = exec_ledger.snapshot(limit=8)
            except Exception as exc:
                status["executables"] = {"error": repr(exc)}
        # compile-plane summary (ISSUE 3): totals + the serve-time-compile
        # window the watchdog acts on; the full table lives on /debug/xlaz
        ledger = getattr(tpu, "ledger", None)
        if ledger is not None:
            compiles = ledger.snapshot(limit=8)
            status["compiles"] = {
                "total": compiles["total"],
                "by_cause": compiles["by_cause"],
                "serving_compiles_60s": compiles["serving_compiles_60s"],
                "recent": compiles["recent"],
            }

    return status


def enable_statusz(app, prefix: str = "/debug/statusz") -> None:
    def statusz(ctx):
        try:
            recent = int(ctx.param("recent") or 32)
        except (TypeError, ValueError):
            recent = 32
        return build_status(app, recent=max(1, min(recent, 256)))

    app.get(prefix, statusz)
