"""GT013 negative fixture: every citation names a registered signal, a
prefix-registered f-string family, or a documented metric.

Parsed by graftcheck in tests, never imported.
"""

STATIC_NAMES = ["queue_depth", "brownout_level"]


def wire(store, engine):
    store.register("serving_compiles", lambda: 0.0)
    names = list(STATIC_NAMES)
    names.extend(f"queue_{cls}" for cls in engine.classes)
    store.register_provider(names, engine.stats)
    bad_name = f"slo_bad_{engine.model}"
    store.register_provider((bad_name,), engine.budget)


def cites_registered():
    return {"signal": "serving_compiles", "count_60s": 2}


def cites_provider_list():
    return [{"signal": "queue_depth"}, {"signal": "brownout_level"}]


def cites_fstring_family(entry):
    # prefix allowance from the f-string registrations above
    return [dict(entry, signal="queue_batch"),
            {"signal": "slo_bad_llama_default"}]


def cites_documented_metric():
    # documented in the fixture catalog (gt005_docs.md)
    return {"signal": "app_fixture_requests_total"}


def record_local_fact():
    # "field" keys are record-local facts, never checked
    return {"field": "anything_goes_here", "seconds": 1.5}


def dynamic_citation(name, entry):
    # non-literal signal references are skipped by design
    return dict(entry, signal=name)
