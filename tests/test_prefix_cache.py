"""Prefix KV reuse (ISSUE 4): radix prefix store + suffix-only prefill.

The determinism contract is the load-bearing property: with a bf16 KV
cache, greedy decode through the engine must be TOKEN-IDENTICAL with the
prefix cache on or off — the pooled pages hold exactly the K/V a full
prefill would recompute. Everything else (eviction, pinning, dedup,
reset) protects that contract under churn.
"""

import asyncio

import jax
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.tpu.generate import GenerationEngine
from gofr_tpu.tpu.prefix_cache import PrefixStore


@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _make_engine(cfg, params, **kwargs):
    container = new_mock_container()
    kwargs.setdefault("max_slots", 4)
    kwargs.setdefault("max_len", 64)
    kwargs.setdefault("prompt_buckets", (8, 16))
    engine = GenerationEngine(cfg, params, logger=container.logger,
                              metrics=container.metrics, **kwargs)
    return engine, container


# -- PrefixStore unit tests (host index only; tiny pool) ---------------------

def test_store_lookup_insert_roundtrip(setup):
    cfg, _ = setup
    store = PrefixStore(cfg, page=4, max_pages=4, num_pages=8)
    prompt = list(range(1, 18))                   # 17 tokens -> 4 full pages
    assert store.lookup(prompt) == []
    assert store.max_lookup_pages(len(prompt)) == 4
    # a prompt of exactly N pages may only reuse N-1 (suffix keeps >=1)
    assert store.max_lookup_pages(16) == 3
    pages = store.insert(prompt, 4)
    assert [is_new for _, is_new in pages] == [True] * 4
    chain = store.lookup(prompt)
    assert [n.page_id for n in chain] == [p for p, _ in pages]
    # re-insert dedups: same ids, nothing new
    again = store.insert(prompt, 4)
    assert again == [(p, False) for p, _ in pages]
    # a prompt diverging at page 2 shares page 1 only
    other = prompt[:4] + [99] * 13
    assert len(store.lookup(other)) == 1


def test_store_eviction_lru_and_refcount_pinning(setup):
    cfg, _ = setup
    store = PrefixStore(cfg, page=4, max_pages=2, num_pages=2)
    a, b = [1] * 9, [2] * 9
    store.insert(a, 2)                            # fills both pages
    assert store.used_pages == 2
    chain_a = store.lookup(a)
    assert len(chain_a) == 2

    # everything pinned: insert must NOT evict, it degrades gracefully
    store.acquire(chain_a)
    assert store.insert(b, 2) == []
    assert store.evictions == 0
    assert len(store.lookup(a)) == 2              # a's chain survived

    # unpinned: leaf-only LRU eviction — the interior page (with a child)
    # is protected, so inserting one page of b evicts a's LEAF
    store.release(chain_a)
    store.lookup(b)                               # bump b's (empty) path
    pages_b = store.insert(b, 1)
    assert len(pages_b) == 1 and pages_b[0][1] is True
    assert store.evictions == 1
    assert len(store.lookup(a)) == 1              # interior page survived
    assert len(store.lookup(b)) == 1


def test_store_budget_sizes_pool(setup):
    cfg, _ = setup
    tight = PrefixStore(cfg, page=4, max_pages=2,
                        budget_bytes=3 * PrefixStore._page_bytes(cfg, 4))
    assert tight.num_pages == 3
    assert tight.stats()["pool_bytes"] == 3 * tight.page_bytes


def test_store_reset_clears_index_keeps_counters(setup):
    cfg, _ = setup
    store = PrefixStore(cfg, page=4, max_pages=2, num_pages=4)
    store.insert([1] * 9, 2)
    inserts = store.inserts
    store.reset()
    assert store.used_pages == 0
    assert store.lookup([1] * 9) == []
    assert store.inserts == inserts               # history survives


# -- engine integration: determinism contract --------------------------------

def test_greedy_token_identity_cache_on_off(setup):
    """Full hits, partial hits, and page-boundary prompts all decode the
    exact token stream a cache-off engine produces."""
    cfg, params = setup
    base = list(range(1, 11))          # 10 tokens: 2 full pages + tail
    partial = base[:8] + [31, 32, 33]  # shares both pages, new tail
    boundary = base[:8]                # exactly 2 pages -> reuse 1 page

    async def run(prefix_cache):
        engine, _ = _make_engine(cfg, params, prefix_cache=prefix_cache,
                                 prefix_page=4)
        await engine.start()
        try:
            outs = []
            for prompt in (base, base, partial, boundary):
                outs.append(await asyncio.wait_for(
                    engine.generate(prompt, max_new_tokens=6), 60.0))
            return outs, engine.stats()
        finally:
            await engine.stop()

    ref, _ = asyncio.run(run(False))
    out, stats = asyncio.run(run(True))
    assert out == ref
    lookups = stats["prefix_cache"]["lookups"]
    assert lookups["miss"] >= 1
    assert lookups["hit"] + lookups["partial"] >= 2
    assert stats["prefix_cache"]["tokens_saved"] > 0


def test_suffix_prefill_dispatches_fewer_prompt_flops(setup):
    """Acceptance criterion: with a shared prefix the suffix path must
    dispatch strictly fewer prompt tokens to prefill executables than
    full prefill would — prefill FLOPs scale with bucket tokens."""
    cfg, params = setup
    shared = list(range(1, 9))         # 2 pages of 4
    prompts = [shared + [50 + i, 60 + i] for i in range(4)]

    async def run(prefix_cache):
        engine, _ = _make_engine(cfg, params, prefix_cache=prefix_cache,
                                 prefix_page=4)
        await engine.start()
        try:
            outs = []
            for prompt in prompts:     # sequential: later ones hit
                outs.append(await asyncio.wait_for(
                    engine.generate(prompt, max_new_tokens=4), 60.0))
            return outs, engine.stats()
        finally:
            await engine.stop()

    ref, off = asyncio.run(run(False))
    out, on = asyncio.run(run(True))
    assert out == ref
    assert on["prefill_bucket_tokens"] < off["prefill_bucket_tokens"]
    # 3 of 4 prompts reused the 8-token prefix
    assert on["prefix_cache"]["tokens_saved"] == 24


def test_concurrent_admissions_share_one_prefix(setup):
    """Same-pass identical prefixes: all miss at lookup (no KV exists
    yet), the first row's publish wins, later GENERATIONS hit."""
    cfg, params = setup
    shared = list(range(1, 9))
    batch = [shared + [70 + i] for i in range(3)]

    async def run(prefix_cache):
        engine, _ = _make_engine(cfg, params, prefix_cache=prefix_cache,
                                 prefix_page=4)
        await engine.start()
        try:
            first = await asyncio.wait_for(asyncio.gather(*[
                engine.generate(p, max_new_tokens=4) for p in batch]),
                120.0)
            second = await asyncio.wait_for(asyncio.gather(*[
                engine.generate(p, max_new_tokens=4) for p in batch]),
                120.0)
            return first + second, engine.stats()
        finally:
            await engine.stop()

    ref, _ = asyncio.run(run(False))
    out, stats = asyncio.run(run(True))
    assert out == ref
    store = stats["prefix_cache"]
    # the shared 2-page prefix occupies exactly one chain, not one per row
    assert store["inserts"] == 2
    assert store["lookups"]["hit"] >= 3        # the second wave
    assert store["used_pages"] == 2


def test_eviction_under_tight_budget_keeps_outputs_exact(setup):
    """A pool too small for the working set must evict and recompute,
    never corrupt: outputs stay identical to cache-off."""
    cfg, params = setup
    prompts = [[10 * k + i for i in range(1, 11)] for k in range(4)]

    async def run(prefix_cache, **kw):
        engine, _ = _make_engine(cfg, params, prefix_cache=prefix_cache,
                                 prefix_page=4, **kw)
        if prefix_cache:
            # shrink the pool to 3 pages: each prompt wants 2, so serving
            # all four churns through eviction
            engine._prefix.num_pages = 3
            engine._prefix.reset()
        await engine.start()
        try:
            outs = []
            for prompt in prompts * 2:
                outs.append(await asyncio.wait_for(
                    engine.generate(prompt, max_new_tokens=4), 60.0))
            return outs, engine.stats()
        finally:
            await engine.stop()

    ref, _ = asyncio.run(run(False))
    out, stats = asyncio.run(run(True))
    assert out == ref
    store = stats["prefix_cache"]
    assert store["evictions"] > 0
    assert store["used_pages"] <= 3


def test_reset_device_state_invalidates_store(setup):
    cfg, params = setup
    prompt = list(range(1, 11))

    async def run():
        engine, _ = _make_engine(cfg, params, prefix_cache=True,
                                 prefix_page=4)
        await engine.start()
        try:
            ref = await asyncio.wait_for(
                engine.generate(prompt, max_new_tokens=4), 60.0)
            assert engine._prefix.used_pages == 2
            engine._reset_device_state()
            assert engine._prefix.used_pages == 0
            assert engine._prefix.lookup(prompt) == []
            # the store repopulates and still serves exact tokens
            out1 = await asyncio.wait_for(
                engine.generate(prompt, max_new_tokens=4), 60.0)
            out2 = await asyncio.wait_for(
                engine.generate(prompt, max_new_tokens=4), 60.0)
            assert out1 == ref and out2 == ref
            assert engine._prefix.used_pages == 2
        finally:
            await engine.stop()

    asyncio.run(run())


def test_flight_recorder_carries_cached_prefix_len(setup):
    cfg, params = setup
    prompt = list(range(1, 11))

    async def run():
        engine, _ = _make_engine(cfg, params, prefix_cache=True,
                                 prefix_page=4)
        await engine.start()
        try:
            await engine.generate(prompt, max_new_tokens=3)
            await engine.generate(prompt, max_new_tokens=3)
        finally:
            await engine.stop()
        recent = engine.recorder.snapshot(limit=2)["recent"]
        lens = sorted(r["cached_prefix_len"] for r in recent)
        assert lens == [0, 8]      # miss then 2-page hit
        assert "prefix_cache" in engine.statusz()["stats"]
        assert "prefix_cache" in engine.xlaz()

    asyncio.run(run())


def test_prefix_metrics_emitted(setup):
    cfg, params = setup
    prompt = list(range(1, 11))

    async def run():
        engine, container = _make_engine(cfg, params, prefix_cache=True,
                                         prefix_page=4)
        await engine.start()
        try:
            await engine.generate(prompt, max_new_tokens=3)
            await engine.generate(prompt, max_new_tokens=3)
        finally:
            await engine.stop()
        metrics = container.metrics
        assert metrics.value("app_tpu_prefix_lookup_total",
                             result="miss") == 1
        assert metrics.value("app_tpu_prefix_lookup_total",
                             result="hit") == 1
        assert metrics.value("app_tpu_prefix_tokens_saved_total") == 8
        assert metrics.value("app_tpu_prefix_cache_occupancy") > 0

    asyncio.run(run())


def test_prefix_cache_sharded_pool(setup):
    """The page pool takes the same kv-head tp spec as the main cache and
    suffix prefill stays exact on a dp x tp mesh."""
    from gofr_tpu.parallel import make_mesh
    cfg, params = setup
    mesh = make_mesh({"dp": 4, "tp": 2})   # tp=2 divides tiny's 2 kv heads
    prompt = list(range(1, 11))

    async def run(prefix_cache):
        engine, _ = _make_engine(cfg, params, mesh=mesh,
                                 prefix_cache=prefix_cache, prefix_page=4,
                                 max_slots=4)
        await engine.start()
        try:
            outs = []
            for _ in range(2):
                outs.append(await asyncio.wait_for(
                    engine.generate(prompt, max_new_tokens=4), 120.0))
            return outs
        finally:
            await engine.stop()

    ref = asyncio.run(run(False))
    out = asyncio.run(run(True))
    assert out == ref
