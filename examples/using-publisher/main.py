"""Publisher example — parity with reference examples/using-publisher:
POST /publish-order and POST /publish-product publish the bound JSON body
to their topics through the configured pub/sub backend
(``PUBSUB_BACKEND`` = KAFKA | MQTT | GOOGLE | INMEM).
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import new_app
from gofr_tpu.http.errors import InvalidParam


def _publish(ctx, topic, required_fields):
    data = ctx.bind()
    missing = [f for f in required_fields if f not in data]
    if missing:
        raise InvalidParam(missing)
    ctx.publish(topic, json.dumps(data).encode())
    return "Published"


async def order(ctx):
    """{"orderId": "...", "status": "..."} → topic order-logs."""
    return _publish(ctx, "order-logs", ("orderId", "status"))


async def product(ctx):
    """{"productId": "...", "price": "..."} → topic products."""
    return _publish(ctx, "products", ("productId", "price"))


def build_app():
    app = new_app()
    app.post("/publish-order", order)
    app.post("/publish-product", product)
    return app


if __name__ == "__main__":
    build_app().run()
