"""graftcheck rule engine: repo-aware AST analysis with pragmas + baseline.

The serving stack's latency story rests on invariants nothing at runtime
can enforce cheaply — the asyncio loop must never block on a device sync,
fire-and-forget tasks must not swallow exceptions, jitted call sites must
not smuggle in recompile hazards. graftcheck machine-checks them ahead of
deploy; PR 3's compile ledger can only *count* recompile storms after one
already stalled traffic.

Architecture:

- :class:`ModuleInfo` — one parsed source file: AST, source lines,
  ``# graftcheck: ignore[RULE]`` pragma map, import-alias table, and a
  child→parent node map (``ast`` does not keep parents).
- :class:`Rule` — per-rule ``check_module`` (file-local findings) and
  ``finalize`` (cross-file findings, e.g. GT005's registered-vs-observed
  metric join).
- :func:`run` — walk a tree, apply rules, subtract pragma suppressions,
  then subtract the committed baseline (grandfathered findings are
  *pinned by count per fingerprint*: fixing one and adding another at the
  same site still fails).

Fingerprints deliberately exclude line numbers so unrelated edits above a
grandfathered finding don't resurrect it; they include the enclosing
function so two distinct sites never share one baseline slot by accident.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

ROOT = pathlib.Path(__file__).resolve().parents[2]
PACKAGE = ROOT / "gofr_tpu"
DEFAULT_BASELINE = ROOT / "scripts" / "graftcheck_baseline.json"

_PRAGMA_RE = re.compile(
    r"#\s*graftcheck:\s*ignore\[([A-Za-z0-9_*,\s]+)\]")
_PRAGMA_FILE_RE = re.compile(
    r"#\s*graftcheck:\s*ignore-file\[([A-Za-z0-9_*,\s]+)\]")


@dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str            # "GT001"
    path: str            # repo-relative posix path
    line: int            # 1-based
    message: str         # human-readable, printed as path:line: RULE msg
    severity: str = "error"
    key: str = ""        # stable fingerprint token (defaults to message)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.key or self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class ModuleInfo:
    """A parsed module plus the derived tables every rule needs."""

    def __init__(self, path: pathlib.Path, source: str):
        self.path = path
        try:
            self.relpath = path.resolve().relative_to(ROOT).as_posix()
        except ValueError:
            self.relpath = path.as_posix()
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.ignores: Dict[int, Set[str]] = {}
        self.file_ignores: Set[str] = set()
        for lineno, text in enumerate(self.lines, start=1):
            match = _PRAGMA_RE.search(text)
            if match:
                tags = {token.strip()
                        for token in match.group(1).split(",")}
                self.ignores.setdefault(lineno, set()).update(tags)
                # a pragma on a comment-only line covers the statement it
                # precedes: skip past the rest of the comment block
                if text.lstrip().startswith("#"):
                    nxt = lineno
                    while nxt < len(self.lines) and (
                            not self.lines[nxt].strip()
                            or self.lines[nxt].lstrip().startswith("#")):
                        nxt += 1
                    if nxt < len(self.lines):
                        self.ignores.setdefault(nxt + 1, set()).update(tags)
            match = _PRAGMA_FILE_RE.search(text)
            if match:
                self.file_ignores.update(
                    token.strip() for token in match.group(1).split(","))
        # import alias tables: "np" -> "numpy", "sleep" -> "time.sleep"
        self.import_aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or
                                        alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.import_aliases[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_ignores or "*" in self.file_ignores:
            return True
        # check the finding's own line plus the line above: findings inside
        # a multi-line statement report their continuation line, one past
        # the statement start the pragma covers
        for lineno in (finding.line, finding.line - 1):
            tags = self.ignores.get(lineno, ())
            if finding.rule in tags or "*" in tags:
                return True
        return False

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Resolve ``np.asarray`` → ``numpy.asarray`` through the module's
        import aliases; plain names resolve through from-imports. Returns
        None for expressions rooted at something other than a Name."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.import_aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cursor = self.parents.get(node)
        while cursor is not None:
            if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cursor
            cursor = self.parents.get(cursor)
        return None


class Rule:
    """Base rule. Subclasses set ``rule_id``/``title`` and override
    ``check_module`` and/or ``finalize``."""

    rule_id = "GT000"
    title = ""
    severity = "error"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finalize(self, modules: Sequence[ModuleInfo]) -> Iterable[Finding]:
        return ()


@dataclass
class Report:
    """Outcome of one analysis run."""

    new_findings: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    stale_baseline: List[str] = field(default_factory=list)
    parse_errors: List[str] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if (self.new_findings or self.parse_errors) else 0


def load_baseline(path: pathlib.Path) -> Dict[str, int]:
    try:
        payload = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {}
    counts = payload.get("counts", {})
    return {str(k): int(v) for k, v in counts.items()}


def write_baseline(path: pathlib.Path, findings: Sequence[Finding]) -> None:
    counts: Dict[str, int] = {}
    for finding in findings:
        counts[finding.fingerprint] = counts.get(finding.fingerprint, 0) + 1
    payload = {
        "_comment": (
            "graftcheck grandfathered findings, pinned by count per "
            "fingerprint. Regenerate with: "
            "python -m gofr_tpu.analysis --write-baseline. Shrink it when "
            "you fix one; never grow it for new code."),
        "version": 1,
        "counts": {k: counts[k] for k in sorted(counts)},
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def iter_python_files(paths: Sequence[pathlib.Path]) -> List[pathlib.Path]:
    out: List[pathlib.Path] = []
    for path in paths:
        if path.is_dir():
            out.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            out.append(path)
    return out


def run(paths: Optional[Sequence[pathlib.Path]] = None,
        rules: Optional[Sequence[Rule]] = None,
        baseline: Optional[Dict[str, int]] = None) -> Report:
    """Run ``rules`` over every ``*.py`` under ``paths``.

    ``baseline`` maps fingerprints to grandfathered counts; within one
    fingerprint the first N findings are baselined and the rest are new.
    """
    if rules is None:
        from gofr_tpu.analysis.rules import default_rules
        rules = default_rules()
    if paths is None:
        paths = [PACKAGE]
    report = Report()
    modules: List[ModuleInfo] = []
    for path in iter_python_files(paths):
        try:
            source = path.read_text(encoding="utf-8")
            modules.append(ModuleInfo(path, source))
        except (OSError, SyntaxError) as exc:
            report.parse_errors.append(f"{path}: unparseable: {exc}")
    report.files_scanned = len(modules)

    module_by_rel = {m.relpath: m for m in modules}
    raw: List[Finding] = []
    for rule in rules:
        for module in modules:
            raw.extend(rule.check_module(module))
        raw.extend(rule.finalize(modules))

    kept: List[Finding] = []
    for finding in raw:
        module = module_by_rel.get(finding.path)
        if module is not None and module.suppressed(finding):
            report.suppressed += 1
        else:
            kept.append(finding)

    budget = dict(baseline or {})
    for finding in sorted(kept, key=lambda f: (f.path, f.line, f.rule)):
        if budget.get(finding.fingerprint, 0) > 0:
            budget[finding.fingerprint] -= 1
            report.baselined.append(finding)
        else:
            report.new_findings.append(finding)
    report.stale_baseline = sorted(
        fp for fp, remaining in budget.items() if remaining > 0)
    return report
