"""GT007 hot-path-host-alloc: per-dispatch host copies in dispatch/tick.

The zero-copy data plane (ISSUE 9) exists because ``np.asarray`` +
``np.pad`` per dispatch and per-slot ``float()`` / ``.item()`` syncs in
decode loops were the measured gap between the served path and the
hardware. The staging pool (``gofr_tpu/tpu/staging.py``) kills those
copies; this rule keeps them dead — a fresh host allocation on a
dispatch path is exactly the regression the bench's relay block would
take rounds to re-attribute.

Detection (v2, whole-program): take every function reachable from a
*dispatch root* — a function whose name is
``dispatch``/``_dispatch*``/``dispatch_*``, a tick (``_dispatch_tick``
/ ``_dispatch_spec`` / ``tick`` / ``_tick``), admission
(``_admit_pending``) or the batcher's ``_run`` — along the project call
graph (a staging helper in its own module is still per-dispatch work),
and flag:

- allocating/copying numpy module calls: ``np.asarray``, ``np.array``,
  ``np.pad``, ``np.stack``, ``np.concatenate``, ``np.copy``,
  ``np.ascontiguousarray`` (write into a staging slab instead;
  ``np.zeros``/``np.empty`` are how slabs are *made*, so they pass),
- ``.copy()`` method calls (a fresh host buffer per dispatch),
- per-slot device syncs inside ``for``/``while`` loops: ``.item()``
  and ``float(x[...])`` — ship one packed token array per tick instead
  of one D2H sync per slot.

``jnp.asarray`` resolves to ``jax.numpy`` and is never flagged: device
puts are the data plane's job. Functions *passed* to
``run_in_executor`` get no call edge, so offloaded cold paths are
naturally exempt. Suppress a justified copy with
``# graftcheck: ignore[GT007]`` plus a reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

# exact dispatch-root function names (matched on the last qualname
# component, so closures named ``dispatch`` inside admission count)
HOT_ROOT_NAMES = {
    "dispatch", "draft_dispatch", "_admit_pending",
    "_run", "tick", "_tick",
}

# numpy module calls that allocate or copy a host buffer per dispatch
ALLOC_CALLS = {
    "numpy.asarray": "allocates/copies a fresh host array per dispatch",
    "numpy.array": "allocates/copies a fresh host array per dispatch",
    "numpy.pad": "allocates a padded copy per dispatch — write into a "
                 "preallocated staging slab row instead",
    "numpy.stack": "stacks a fresh batch buffer per dispatch — write "
                   "rows into a staging slab instead",
    "numpy.concatenate": "concatenates a fresh buffer per dispatch",
    "numpy.copy": "copies a host buffer per dispatch",
    "numpy.ascontiguousarray": "may copy a host buffer per dispatch",
}


def _is_hot_root(qualname: str) -> bool:
    last = qualname.split(".")[-1]
    return (last in HOT_ROOT_NAMES
            or last.startswith("_dispatch")
            or last.startswith("dispatch_"))


class HostAllocRule(Rule):
    rule_id = "GT007"
    title = "hot-path-host-alloc"
    severity = "error"

    def check_project(self, project) -> Iterable[Finding]:
        roots = [ref for ref in project.functions
                 if _is_hot_root(ref[1])]
        chains = project.reachable(roots)
        findings: List[Finding] = []
        for ref, chain in chains.items():
            module = project.module_of(ref)
            qualname = ref[1]
            for node in project.body_nodes(ref):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._offending(module, node)
                if hit is None:
                    continue
                label, why = hit
                root = project.display(chain[0], module.relpath)
                via = (" via " + " -> ".join(
                    project.display(r, module.relpath)
                    for r in chain[1:])
                    if len(chain) > 1 else "")
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=node.lineno,
                    message=(
                        f"hot-path-host-alloc: {label} inside "
                        f"'{qualname}' runs per dispatch (dispatch root "
                        f"'{root}'{via}) — {why}"),
                    severity=self.severity,
                    key=f"{label} in {qualname}",
                ))
        return findings

    # -- per-call classification --------------------------------------------
    def _offending(self, module: ModuleInfo,
                   call: ast.Call) -> Optional[Tuple[str, str]]:
        func = call.func
        dotted = module.dotted(func)
        if dotted is not None and dotted in ALLOC_CALLS:
            return f"{dotted}(...)", ALLOC_CALLS[dotted]
        if isinstance(func, ast.Attribute) and func.attr == "copy":
            return (".copy()",
                    "copies a host buffer per dispatch — reuse a "
                    "staging slab")
        if self._in_loop(module, call):
            if isinstance(func, ast.Attribute) and func.attr == "item":
                return (".item() in loop",
                        "one device→host sync per slot per tick — "
                        "fetch ONE packed token array instead")
            if isinstance(func, ast.Name) and func.id == "float" and \
                    call.args and isinstance(call.args[0], ast.Subscript):
                return ("float(x[...]) in loop",
                        "one device→host sync per slot per tick — "
                        "fetch ONE packed token array instead")
        return None

    @staticmethod
    def _in_loop(module: ModuleInfo, node: ast.AST) -> bool:
        cursor = module.parents.get(node)
        while cursor is not None:
            if isinstance(cursor, (ast.For, ast.While, ast.AsyncFor)):
                return True
            if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                return False
            cursor = module.parents.get(cursor)
        return False
