"""Tracing middleware: extract W3C tracecontext, open a request span.

Capability parity with ``pkg/gofr/http/middleware/tracer.go:15-32`` (span
named ``"METHOD /path"`` parented on the incoming ``traceparent``).
"""

from __future__ import annotations

from gofr_tpu.http.router import Middleware, WireHandler
from gofr_tpu.trace import Tracer, extract_traceparent


def tracing_middleware(tracer: Tracer) -> Middleware:
    def middleware(next_handler: WireHandler) -> WireHandler:
        async def handle(request):
            remote = extract_traceparent(request.headers.get("traceparent"))
            span = tracer.start_span(
                f"{request.method} {request.path}", remote_parent=remote
            )
            with span:
                span.set_attribute("http.method", request.method)
                span.set_attribute("http.target", request.path)
                request.context_values["span"] = span
                status, headers, body = await next_handler(request)
                span.set_attribute("http.status_code", status)
                if status >= 500:
                    span.set_status("ERROR")
                # clients/operators can join logs, exemplars, and the
                # flight recorder on this id without parsing traceparent
                headers = dict(headers or {})
                headers.setdefault("X-Trace-Id", span.trace_id)
                return status, headers, body
        return handle
    return middleware
