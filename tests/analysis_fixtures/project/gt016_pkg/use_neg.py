"""GT016 negatives: the lock held lexically, the lock held by every
caller (worklist coverage), a self-serializing pool, and read-only
access."""

from gt016_pkg.pool import SafePool, SharedPool


class LockedAdmitter:
    def __init__(self, pool: SharedPool, safe: SafePool):
        self.pool = pool
        self.safe = safe

    def admit(self):
        with self.pool.lock:
            return self.pool.alloc()         # locked: fine

    def admit_via_helper(self):
        with self.pool.lock:
            return self._locked_alloc()      # lock held by the caller

    def _locked_alloc(self):
        # only ever entered from under the lock above — caller-covered
        return self.pool.alloc()

    def admit_safe(self):
        return self.safe.alloc()             # self-serializing pool: fine

    def occupancy(self):
        return self.pool.peek()              # read-only: never flagged
