"""GT011 positive fixture: telemetry buffers that only ever grow."""

TICKS = []


def on_tick(entry):
    # module-level list grown per tick, never trimmed
    TICKS.append(entry)


class Recorder:
    def __init__(self):
        self.samples = []
        self.by_name = {}
        self.latest = None

    def record(self, value):
        # per-sample append with no bound in the whole module
        self.samples.append(value)
        self.latest = value

    def observe(self, name, value):
        # dict grows one key per observed name forever
        self.by_name[name] = value

    def build_schema(self):
        # not a recording hot path: one-shot setup may build structure
        self.schema = []
        self.schema.append("t")
        return self.schema


class Forensics:
    def __init__(self):
        self.crashes = []

    def note_crash(self, entry):
        # deliberate: crash forensics keep everything until process end
        self.crashes.append(entry)  # graftcheck: ignore[GT011]
