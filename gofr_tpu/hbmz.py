"""Device-memory attribution over HTTP: ``/debug/hbmz``.

ISSUE 10: ``memory_stats()`` says how many bytes the backend holds;
nobody could say WHOSE they are. The engine-side
``GenerationEngine.hbm_attribution`` splits what the serving stack
knowingly placed on device — params per model, the KV page pool divided
into free / prefix-pinned / decode / migrated pages, staging slabs —
and this page reconciles that against the backend figure. The
difference is rendered as an explicit ``unattributed`` residual (XLA
temporaries, executables, allocator fragmentation): an honest line
item, not an error, and the one to watch when it grows.

The same numbers feed the watchdog a real HBM-pressure signal:
:func:`hbm_occupancy` prefers the backend's ``bytes_in_use /
bytes_limit`` when the platform reports a limit (TPU/GPU), and falls
back to KV-pool occupancy (the serving-pressure proxy that also works
on CPU). ``enable_hbmz`` wires it as ``watchdog.hbm_fn``.

:func:`build_hbmz` is app-independent — ``bench.py`` and tests call it
with a bare container or engine; ``enable_hbmz`` is the HTTP binding.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

__all__ = ["build_hbmz", "hbm_occupancy", "enable_hbmz"]


def build_hbmz(container, metrics=None) -> Dict[str, Any]:
    """One attribution snapshot from whatever the container wired
    (engine or model registry — both duck-type ``hbm_attribution``)."""
    tpu = getattr(container, "tpu", None)
    attribution_fn = getattr(tpu, "hbm_attribution", None)
    if attribution_fn is None:
        return {"error": "no engine with hbm_attribution wired",
                "at": time.time()}
    report = attribution_fn()
    report["at"] = time.time()
    report["occupancy"] = hbm_occupancy(container)
    metrics = metrics if metrics is not None \
        else getattr(container, "metrics", None)
    if metrics is not None:
        if report.get("attributed_bytes") is not None:
            metrics.set_gauge("app_tpu_hbm_attributed_bytes",
                              float(report["attributed_bytes"]))
        if report.get("unattributed_bytes") is not None:
            metrics.set_gauge("app_tpu_hbm_unattributed_bytes",
                              float(report["unattributed_bytes"]))
    return report


def hbm_occupancy(container) -> Optional[float]:
    """HBM-pressure fraction in [0, 1] for the watchdog: backend
    ``bytes_in_use / bytes_limit`` when a limit is reported, else the
    KV page pool's occupancy, else ``None`` (signal unavailable —
    the watchdog must NOT treat that as pressure)."""
    try:
        import jax
        in_use = limit = 0
        for device in jax.local_devices():
            try:
                stats = device.memory_stats() or {}
            except Exception:
                continue
            if stats.get("bytes_limit"):
                in_use += int(stats.get("bytes_in_use", 0))
                limit += int(stats["bytes_limit"])
        if limit > 0:
            return min(1.0, in_use / limit)
    except Exception:
        pass
    tpu = getattr(container, "tpu", None)
    pool = getattr(tpu, "_pool", None)
    if pool is None:
        # registry: the shared pool, when one exists
        pool = getattr(tpu, "page_pool", None)
    if pool is not None and getattr(pool, "num_pages", 0):
        return pool.used_pages / pool.num_pages
    return None


def enable_hbmz(app, prefix: str = "/debug/hbmz") -> None:
    container = app.container
    watchdog = getattr(container, "watchdog", None)
    if watchdog is not None and hasattr(watchdog, "hbm_fn"):
        watchdog.hbm_fn = lambda: hbm_occupancy(container)

    def hbmz(ctx):
        return build_hbmz(container)

    app.get(prefix, hbmz)
