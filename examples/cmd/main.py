"""CLI example — parity with reference examples/using-cmd: sub-commands
with flags, plus an offline TPU predict command (CLI contexts fall back to
direct executor calls — no server loop needed)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import new_cmd


def hello(ctx):
    return f"Hello {ctx.param('name') or 'World'}!"


def classify(ctx):
    import asyncio

    import jax
    import numpy as np

    from gofr_tpu.models import resnet
    from gofr_tpu.tpu import Executor

    cfg = resnet.config("tiny")
    params = resnet.init(cfg, jax.random.PRNGKey(0))
    executor = Executor(ctx.logger, ctx.metrics)
    executor.register("resnet", lambda p, x: resnet.apply(p, cfg, x),
                      params, buckets=(1,))
    ctx.container.tpu = executor
    image = np.random.default_rng(0).standard_normal(
        (cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    logits = asyncio.run(ctx.predict("resnet", image))
    return {"label": int(logits.argmax())}


app = new_cmd()
app.sub_command("hello", hello, description="greet",
                help_text="hello -name=you")
app.sub_command("classify", classify,
                description="classify a random image offline")

if __name__ == "__main__":
    sys.exit(app.run() or 0)
