"""GT007 negative fixture: staged dispatch paths that copy at most once.

Parsed by graftcheck in tests, never imported.
"""

import jax.numpy as jnp
import numpy as np


class Executorish:
    def _dispatch(self, name, leaves, n, bucket):
        # writes into a preallocated staging slab row: no fresh alloc
        slab = self._staging.acquire((name, bucket))
        for buf, leaf in zip(slab.buffers, leaves):
            buf[:n] = leaf
            buf[n:] = 0
        # jnp.asarray is a device put, not a host alloc — never flagged
        return [jnp.asarray(buf) for buf in slab.buffers]

    def _make_slab(self, specs):
        # zeros/empty are how slabs are BUILT — allocation at setup time,
        # not per dispatch
        return [np.zeros(shape, dtype) for shape, dtype in specs]

    def predict(self, name, batch):
        # cold path: np.asarray here is fine — 'predict' is not a
        # dispatch root and nothing on one calls it
        return np.asarray(batch)

    def _dispatch_tick(self, tokens_dev, slots):
        # ONE packed fetch for the whole tick, then host-side indexing
        tokens = self._fetch_all(tokens_dev)
        return [int(tokens[i]) for i in slots]

    def _fetch_all(self, tokens_dev):
        return tokens_dev

    def _publish(self, tokens, slot):
        # float() outside a loop is a single scalar read, not per-slot
        return float(tokens[slot])
