"""GT012 negative fixture: shape-only workload capture — every store
keeps lengths, counts, and labels; content passes only through the
sanctioned shape extractors (len/min/max/sum/int/float/bool/hash)."""

from collections import deque


class ShapeOnlyRecorder:
    def __init__(self):
        self._ring = deque(maxlen=64)
        self._classes = {}

    def admit(self, request, cls):
        # shape only: the length leaves len(), never the ids themselves
        self._ring.append({
            "prompt_len": len(request.prompt_ids),
            "budget": int(request.budget),
            "cls": cls,
        })
        self._classes[cls] = self._classes.get(cls, 0) + 1

    def finish(self, request, event):
        # output token COUNT, finish label — both shape
        event["output_len"] = len(request.tokens)
        event["finish"] = request.status

    def snapshot(self):
        lens = [event["prompt_len"] for event in self._ring]
        return {
            "window": len(lens),
            "mean_prompt_len": (sum(lens) / len(lens)) if lens else None,
            "class_mix": dict(self._classes),
        }

    def export_trace(self):
        rows = []
        for event in self._ring:
            rows.append([event["prompt_len"], event["budget"]])
        return {"version": 1, "events": rows}
