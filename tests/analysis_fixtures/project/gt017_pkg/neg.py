"""GT017 negatives: lock released before awaiting, asyncio lock via
``async with``, snapshot iteration, and collect-then-mutate."""


class Engine:
    def __init__(self, pool, slots, alock):
        self._pool = pool
        self._slots = slots
        self._alock = alock

    async def fetch_unlocked(self, batch):
        with self._pool.lock:
            staged = self._stage(batch)        # lock released before await
        return await self._dispatch(staged)

    async def fetch_async_lock(self, batch):
        async with self._alock:                # asyncio lock: designed
            return await self._dispatch(batch)  # for cross-await holds

    async def drain_snapshot(self):
        for sid, slot in list(self._slots.items()):   # snapshot: safe
            await slot.drain()
            del self._slots[sid]

    async def drain_collect(self):
        doomed = []
        for sid, slot in self._slots.items():
            await slot.drain()
            doomed.append(sid)                 # mutate AFTER the loop
        for sid in doomed:
            del self._slots[sid]

    def _stage(self, batch):
        return batch

    async def _dispatch(self, batch):
        return batch
