"""BatchLane — the async inference plane: pub/sub generation jobs into
the WFQ ``batch`` class.

The framework's identity is pub/sub subscribers (PAPER.md), yet until
this lane the brokers sat unused by the TPU path while idle decode ticks
went to waste. The lane closes that gap: consumers pull JSON generation
jobs from a topic, submit them into the engine with **no deadline** — so
:func:`~gofr_tpu.tpu.sched.deadline_class` files them under the
weighted-fair ``batch`` class, which soaks idle capacity without
starving interactive traffic — and publish results back with the
consuming trace's traceparent (the result ``pubsub.publish`` span is a
child of this job's ``pubsub.consume`` span via the tracer contextvar,
exactly like the HTTP middlewares).

Contracts:

- **Job** (JSON): ``{"id": str, "prompt_ids": [int] | "prompt": str,
  "max_new_tokens": int, "eos_id": int|null, "sampling": {temperature,
  top_k, top_p, seed}, "response_format": {...}|null, "model":
  str|null, "result_topic": str|null}``. ``prompt`` (text) requires the
  lane to be built with an ``encode`` hook (the example wires the
  tokenizer); ``model`` routes through a ModelRegistry when one backs
  the lane.
- **Result**: ``{"id", "model", "tokens", "text"?, "finish_reason":
  "stop"|"length", "usage": {prompt_tokens, completion_tokens,
  total_tokens}}`` published to ``result_topic`` (job override wins).
- **Dead letter**: any per-job failure — malformed JSON, validation,
  grammar compile, engine error — becomes ``{"id", "error": {"type",
  "message"}, "job": <raw payload, truncated>}`` on the dead-letter
  topic. The job is committed either way; one poison pill must never
  kill the subscriber or wedge the partition.

Backpressure: before every pull the lane checks the engine's admission
depth (``admission_depth()`` — the same number behind
``app_tpu_admission_queue_depth``), the paged-KV free-page headroom
above the reserve watermark, and the degradation watchdog. Any signal
over threshold pauses consumption (``pause()`` on brokers that have one,
e.g. Kafka's partition fetcher; otherwise the lane simply stops pulling
and counts the pause itself in
``app_pubsub_consumer_paused_total{topic,reason}``) and resumes with
hysteresis (``resume_depth < pause_depth``) so the lane doesn't flap at
the boundary. The host queue is additionally bounded by the in-flight
semaphore — the lane can never buffer more than ``max_inflight`` jobs.

Lifecycle mirrors the engine: ``start()`` spawns the consumer loop,
``drain()`` stops pulling and waits for in-flight jobs, ``stop()``
drains then cancels stragglers. ``App.start``/``App.stop`` drive these
when ``BATCH_LANE_TOPIC`` is configured.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import time
from typing import Any, Callable, Dict, Optional, Set

from gofr_tpu.slo import STATE_DEGRADED
from gofr_tpu.tpu import faults

# how much of a malformed payload rides along in the dead-letter
# envelope — enough to debug, bounded so one 10MB blob can't amplify
_DEAD_LETTER_PAYLOAD_CAP = 4096

PAUSE_ADMISSION = "admission_depth"
PAUSE_KV_PAGES = "kv_pages"
PAUSE_DEGRADED = "degraded"


class JobError(ValueError):
    """A job this lane will never be able to run (parse/validation)."""


class BatchLane:
    """Subscriber-driven generation lane over one pub/sub topic."""

    def __init__(self, engine: Any, broker: Any, topic: str, *,
                 result_topic: Optional[str] = None,
                 dead_letter_topic: Optional[str] = None,
                 max_inflight: int = 8,
                 pause_depth: int = 64,
                 resume_depth: int = 16,
                 page_low_watermark: int = 0,
                 poll_s: float = 0.05,
                 default_max_new_tokens: int = 32,
                 encode: Optional[Callable[[str], list]] = None,
                 decode: Optional[Callable[[list], str]] = None,
                 watchdog: Any = None,
                 logger=None, metrics=None, tracer=None):
        if not topic:
            raise ValueError("BatchLane needs a topic")
        if resume_depth >= pause_depth:
            raise ValueError(
                f"resume_depth {resume_depth} must be < pause_depth "
                f"{pause_depth} (hysteresis)")
        # ``engine`` may be a GenerationEngine or a ModelRegistry — the
        # registry duck-types route(); jobs carry an optional "model"
        self._engine = engine
        self._broker = broker
        self.topic = str(topic)
        self.result_topic = result_topic or f"{self.topic}.results"
        self.dead_letter_topic = (dead_letter_topic
                                  or f"{self.topic}.dead-letter")
        self.max_inflight = int(max_inflight)
        self.pause_depth = int(pause_depth)
        self.resume_depth = int(resume_depth)
        self.page_low_watermark = int(page_low_watermark)
        self.poll_s = float(poll_s)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self._encode = encode
        self._decode = decode
        self.watchdog = watchdog
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self._sem = asyncio.Semaphore(self.max_inflight)
        self._jobs: Set[asyncio.Task] = set()
        self._task: Optional[asyncio.Task] = None
        self._draining = False
        self._paused = False
        self.jobs_ok = 0
        self.jobs_dead_lettered = 0
        self.pauses = 0
        self.resumes = 0

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> None:
        """Spawn the consumer loop (idempotent)."""
        if self._task is not None and not self._task.done():
            return
        self._draining = False
        from gofr_tpu.aio import spawn_logged
        self._task = spawn_logged(
            self._consume_loop(), self.logger,
            f"tpu.batch_lane.{self.topic}", metrics=self.metrics)

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop pulling new jobs, wait for in-flight ones. Returns True
        when everything landed within the timeout."""
        self._draining = True
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        deadline = time.monotonic() + timeout_s
        while self._jobs and time.monotonic() < deadline:
            await asyncio.sleep(min(self.poll_s, 0.05))
        return not self._jobs

    async def stop(self, grace_s: float = 10.0) -> None:
        """Drain, then cancel whatever refused to land."""
        if not await self.drain(grace_s):
            for job in list(self._jobs):
                job.cancel()
            if self._jobs:
                await asyncio.gather(*self._jobs, return_exceptions=True)
            if self.logger is not None:
                self.logger.warn(
                    "batch lane %s: cancelled in-flight jobs at stop",
                    self.topic)

    # -- consumer loop ------------------------------------------------------
    async def _consume_loop(self) -> None:
        while not self._draining:
            await self._backpressure_gate()
            if self._draining:
                return
            try:
                message = await self._broker.subscribe(self.topic)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                if self.logger is not None:
                    self.logger.error(
                        "batch lane %s receive error: %r", self.topic, exc)
                await asyncio.sleep(1.0)
                continue
            if message is None:  # broker closed
                return
            # the semaphore is the host-queue bound: at most max_inflight
            # jobs buffered/decoding — a flooded topic cannot OOM us
            await self._sem.acquire()
            task = asyncio.ensure_future(self._run_job(message))
            self._jobs.add(task)
            task.add_done_callback(self._job_done)
            self._set_inflight()

    def _job_done(self, task: asyncio.Task) -> None:
        self._jobs.discard(task)
        self._sem.release()
        self._set_inflight()
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None and self.logger is not None:
            # _run_job dead-letters its own failures; reaching here means
            # the error envelope itself failed — log, keep consuming
            self.logger.error("batch lane %s job task died: %r",
                              self.topic, exc)

    def _set_inflight(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("app_tpu_batch_lane_inflight",
                                   float(len(self._jobs)), topic=self.topic)

    # -- backpressure -------------------------------------------------------
    def _route(self, model: Optional[str] = None):
        route = getattr(self._engine, "route", None)
        if route is not None:
            return route(model or None)
        return self._engine

    def _observe_engine(self):
        """The engine whose admission/KV state gates consumption — the
        registry's default route, or the engine itself. None when the
        registry cannot serve at all (treated as DEGRADED-equivalent)."""
        try:
            return self._route(None)
        except Exception:
            return None

    def _pause_reason(self) -> Optional[str]:
        engine = self._observe_engine()
        if engine is None:
            return PAUSE_DEGRADED
        depth_fn = getattr(engine, "admission_depth", None)
        if depth_fn is not None and depth_fn() >= self.pause_depth:
            return PAUSE_ADMISSION
        headroom_fn = getattr(engine, "kv_free_headroom", None)
        if headroom_fn is not None:
            headroom = headroom_fn()
            if headroom is not None and headroom <= self.page_low_watermark:
                return PAUSE_KV_PAGES
        if (self.watchdog is not None
                and getattr(self.watchdog, "state", None) == STATE_DEGRADED):
            return PAUSE_DEGRADED
        return None

    def _may_resume(self) -> bool:
        engine = self._observe_engine()
        if engine is None:
            return False
        depth_fn = getattr(engine, "admission_depth", None)
        if depth_fn is not None and depth_fn() > self.resume_depth:
            return False
        headroom_fn = getattr(engine, "kv_free_headroom", None)
        if headroom_fn is not None:
            headroom = headroom_fn()
            if headroom is not None and headroom <= self.page_low_watermark:
                return False
        if (self.watchdog is not None
                and getattr(self.watchdog, "state", None) == STATE_DEGRADED):
            return False
        return True

    async def _backpressure_gate(self) -> None:
        reason = self._pause_reason()
        if reason is None:
            return
        self._paused = True
        self.pauses += 1
        if self.metrics is not None:
            self.metrics.set_gauge("app_tpu_batch_lane_paused", 1.0,
                                   topic=self.topic)
        # brokers with a real fetcher pause (kafka) stop their prefetch
        # and count the pause themselves; everything else just has this
        # loop stop pulling, so the lane owns the counter
        pause = getattr(self._broker, "pause", None)
        if pause is not None:
            pause(self.topic, reason=reason)
        elif self.metrics is not None:
            self.metrics.increment_counter(
                "app_pubsub_consumer_paused_total",
                topic=self.topic, reason=reason)
        if self.logger is not None:
            self.logger.warn("batch lane %s paused (%s)", self.topic, reason)
        while not self._draining:
            await asyncio.sleep(self.poll_s)
            if self._may_resume():
                break
        self._paused = False
        self.resumes += 1
        if self.metrics is not None:
            self.metrics.set_gauge("app_tpu_batch_lane_paused", 0.0,
                                   topic=self.topic)
        resume = getattr(self._broker, "resume", None)
        if resume is not None:
            resume(self.topic)
        if self.logger is not None and not self._draining:
            self.logger.info("batch lane %s resumed", self.topic)

    @property
    def paused(self) -> bool:
        return self._paused

    # -- per-job path -------------------------------------------------------
    def _parse(self, payload: bytes) -> Dict[str, Any]:
        try:
            data = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as exc:
            raise JobError(f"job is not JSON: {exc}") from exc
        if not isinstance(data, dict):
            raise JobError(f"job must be a JSON object, got "
                           f"{type(data).__name__}")
        if "prompt_ids" in data:
            ids = data["prompt_ids"]
            if (not isinstance(ids, list)
                    or not all(isinstance(t, int) for t in ids)):
                raise JobError("prompt_ids must be a list of ints")
            prompt_ids = ids
        elif "prompt" in data:
            if self._encode is None:
                raise JobError(
                    "text prompts need a tokenizer on this lane; "
                    "send prompt_ids")
            prompt_ids = self._encode(str(data["prompt"]))
        else:
            raise JobError("job needs prompt_ids or prompt")
        try:
            max_new = int(data.get("max_new_tokens",
                                   self.default_max_new_tokens))
            eos_raw = data.get("eos_id")
            eos_id = int(eos_raw) if eos_raw is not None else None
            sampling_raw = data.get("sampling") or {}
            if not isinstance(sampling_raw, dict):
                raise JobError("sampling must be an object")
            from gofr_tpu.tpu.generate import Sampling
            seed = sampling_raw.get("seed")
            sampling = Sampling(
                temperature=float(sampling_raw.get("temperature", 0.0)),
                top_k=int(sampling_raw.get("top_k", 0)),
                top_p=float(sampling_raw.get("top_p", 1.0)),
                seed=int(seed) if seed is not None else None)
        except (TypeError, ValueError) as exc:
            raise JobError(f"bad field value: {exc}") from exc
        response_format = data.get("response_format")
        if response_format is not None and not isinstance(response_format,
                                                          dict):
            raise JobError("response_format must be an object")
        return {
            "id": str(data.get("id", "")),
            "prompt_ids": prompt_ids,
            "max_new_tokens": max_new,
            "eos_id": eos_id,
            "sampling": sampling,
            "response_format": response_format,
            "model": data.get("model"),
            "result_topic": data.get("result_topic"),
        }

    async def _publish(self, topic: str, payload: Dict[str, Any]) -> None:
        # chaos site (ISSUE 14): a dropped broker publish sends the
        # result down the dead-letter path (and a dropped dead-letter
        # publish is logged and swallowed) — the job commits either
        # way, so one flaky broker can never wedge the partition
        faults.active().raise_if("broker_drop")
        body = json.dumps(payload).encode("utf-8")
        result = self._broker.publish(topic, body)
        if asyncio.iscoroutine(result):
            await result

    async def _dead_letter(self, job_id: str, payload: bytes,
                           exc: BaseException) -> None:
        self.jobs_dead_lettered += 1
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_tpu_batch_lane_jobs_total", outcome="dead_letter")
        if self.logger is not None:
            self.logger.error("batch lane %s job %s dead-lettered: %r",
                              self.topic, job_id or "<unknown>", exc)
        envelope = {
            "id": job_id or None,
            "error": {"type": type(exc).__name__, "message": str(exc)},
            "job": payload[:_DEAD_LETTER_PAYLOAD_CAP].decode(
                "utf-8", errors="replace"),
        }
        try:
            await self._publish(self.dead_letter_topic, envelope)
        except Exception as pub_exc:
            if self.logger is not None:
                self.logger.error(
                    "batch lane %s dead-letter publish failed: %r",
                    self.topic, pub_exc)

    async def _run_job(self, message: Any) -> None:
        # per-job consume span, continuing the producer's trace when the
        # broker carried a traceparent; held open across generation so
        # the result publish span lands inside it (contextvar parenting)
        remote = None
        try:
            from gofr_tpu.trace import extract_traceparent
            remote = extract_traceparent(
                message.header("traceparent") or "")
        except Exception:
            remote = None
        if self.tracer is not None:
            span_ctx = self.tracer.start_span("pubsub.consume",
                                              remote_parent=remote)
        else:
            span_ctx = contextlib.nullcontext()
        payload = message.value if isinstance(message.value, bytes) \
            else str(message.value).encode("utf-8")
        with span_ctx as span:
            if span is not None:
                span.set_attribute("topic", self.topic)
                span.set_attribute("lane", "batch")
            job_id = ""
            try:
                job = self._parse(payload)
                job_id = job["id"]
                engine = self._route(job["model"])
                start = getattr(engine, "start", None)
                if start is not None:
                    # idempotent; binds the serving loop on first use —
                    # apps start engines lazily (HTTP handlers do the
                    # same), so a lane job may be the first request in
                    await start()
                # no deadline on this task → deadline_class(None) files
                # the request under the WFQ "batch" class
                tokens = await engine.generate(
                    job["prompt_ids"],
                    max_new_tokens=job["max_new_tokens"],
                    eos_id=job["eos_id"],
                    sampling=job["sampling"],
                    response_format=job["response_format"])
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                if span is not None:
                    span.set_status("ERROR")
                if not job_id:
                    # best-effort id for the envelope even when the job
                    # failed validation after the JSON layer parsed
                    with contextlib.suppress(Exception):
                        raw = json.loads(payload.decode("utf-8"))
                        if isinstance(raw, dict):
                            job_id = str(raw.get("id", ""))
                await self._dead_letter(job_id, payload, exc)
                message.commit()
                return
            finish = "length"
            if len(tokens) < job["max_new_tokens"] or (
                    job["eos_id"] is not None and tokens
                    and tokens[-1] == job["eos_id"]):
                finish = "stop"
            result: Dict[str, Any] = {
                "id": job_id,
                "model": getattr(engine, "model_name", "generate"),
                "tokens": tokens,
                "finish_reason": finish,
                "usage": {
                    "prompt_tokens": len(job["prompt_ids"]),
                    "completion_tokens": len(tokens),
                    "total_tokens": len(job["prompt_ids"]) + len(tokens),
                },
            }
            if self._decode is not None:
                try:
                    result["text"] = self._decode(tokens)
                except Exception:
                    pass  # tokens are the contract; text is sugar
            try:
                await self._publish(job.get("result_topic")
                                    or self.result_topic, result)
            except Exception as exc:
                if span is not None:
                    span.set_status("ERROR")
                await self._dead_letter(job_id, payload, exc)
                message.commit()
                return
            self.jobs_ok += 1
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_tpu_batch_lane_jobs_total", outcome="ok")
            message.commit()

    # -- observability ------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "topic": self.topic,
            "result_topic": self.result_topic,
            "dead_letter_topic": self.dead_letter_topic,
            "inflight": len(self._jobs),
            "max_inflight": self.max_inflight,
            "paused": self._paused,
            "draining": self._draining,
            "jobs_ok": self.jobs_ok,
            "jobs_dead_lettered": self.jobs_dead_lettered,
            "pauses": self.pauses,
            "resumes": self.resumes,
            "pause_depth": self.pause_depth,
            "resume_depth": self.resume_depth,
            "page_low_watermark": self.page_low_watermark,
        }


def new_batch_lane(config, engine, container, *,
                   encode: Optional[Callable[[str], list]] = None,
                   decode: Optional[Callable[[list], str]] = None
                   ) -> Optional[BatchLane]:
    """Config-driven constructor: None unless ``BATCH_LANE_TOPIC`` is set
    and a broker + engine are wired. Knob catalog in
    docs/quick-start/configuration.md."""
    topic = config.get("BATCH_LANE_TOPIC")
    if not topic or container.pubsub is None or engine is None:
        return None
    return BatchLane(
        engine, container.pubsub, topic,
        result_topic=config.get("BATCH_LANE_RESULT_TOPIC"),
        dead_letter_topic=config.get("BATCH_LANE_DEAD_TOPIC"),
        max_inflight=config.get_int("BATCH_LANE_MAX_INFLIGHT", 8),
        pause_depth=config.get_int("BATCH_LANE_PAUSE_DEPTH", 64),
        resume_depth=config.get_int("BATCH_LANE_RESUME_DEPTH", 16),
        page_low_watermark=config.get_int(
            "BATCH_LANE_PAGE_LOW_WATERMARK", 0),
        poll_s=config.get_float("BATCH_LANE_POLL_S", 0.05),
        default_max_new_tokens=config.get_int(
            "BATCH_LANE_DEFAULT_MAX_NEW_TOKENS", 32),
        encode=encode, decode=decode,
        watchdog=container.watchdog,
        logger=container.logger, metrics=container.metrics,
        tracer=container.tracer)


__all__ = ["BatchLane", "JobError", "new_batch_lane",
           "PAUSE_ADMISSION", "PAUSE_KV_PAGES", "PAUSE_DEGRADED"]
