"""WebSocket upgrade route: handshake + connection loop.

Capability parity with ``pkg/gofr/http/middleware/web_socket.go`` (upgrade
when requested, store conn in hub keyed by Sec-WebSocket-Key 14-37) and
``pkg/gofr/gofr/websocket.go`` (App.WebSocket swaps ctx.Request for the
Connection 18-35; read-eval-write handled by the user handler).
"""

from __future__ import annotations

import asyncio
import urllib.parse

from gofr_tpu.context import Context
from gofr_tpu.websocket.connection import Connection, ConnectionClosed, ConnectionHub
from gofr_tpu.websocket.frames import accept_key

_hub = ConnectionHub()


def hub() -> ConnectionHub:
    return _hub


def make_ws_route(handler, container):
    """Build the wire handler for a websocket route. Returns 101 + an
    ``upgrade_protocol`` continuation the HTTP server runs after switching
    protocols (http/server.py serve loop)."""

    async def ws_wire_handler(request):
        if request.headers.get("upgrade", "").lower() != "websocket":
            return 426, {"Content-Type": "text/plain"}, b"upgrade required"
        key = request.headers.get("sec-websocket-key", "")
        if not key:
            return 400, {}, b"missing Sec-WebSocket-Key"

        query = urllib.parse.parse_qs(request.query or "")

        async def run_connection(transport, set_feed):
            connection = Connection(transport, key, request.path,
                                    path_params=dict(request.path_params),
                                    query_params=query)
            leftover = set_feed(connection.feed)
            if leftover:
                connection.feed(leftover)
            _hub.add(connection)
            ctx = Context(connection, container)
            try:
                result = handler(ctx)
                if asyncio.iscoroutine(result):
                    await result
            except ConnectionClosed:
                pass
            except Exception as exc:
                container.logger.error("websocket handler panic: %r", exc)
            finally:
                _hub.remove(key)
                connection.close()
                set_feed(None)

        request.context_values["upgrade_protocol"] = run_connection
        return 101, {
            "Upgrade": "websocket",
            "Connection": "Upgrade",
            "Sec-WebSocket-Accept": accept_key(key),
        }, b""

    return ws_wire_handler
