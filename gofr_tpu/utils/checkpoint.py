"""Checkpoint/resume for param/optimizer pytrees.

The reference's checkpoint analog is its migration journal (SURVEY.md §5:
versioned, journaled, resumes from max(version)); this is the model-side
equivalent: versioned step directories with atomic publish and
latest-step resolution, so a serving process or training loop resumes
exactly where it stopped.

Format: one ``arrays.npz`` (flattened leaves, keyed by pytree path) +
``tree.json`` (structure, dtypes, step metadata). Restoring onto a mesh:
pass ``sharding`` (a pytree of NamedShardings or one for all) and leaves
are device_put directly to their shards — the host never materialises more
than one leaf at a time beyond numpy's mmap window.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, Optional

import numpy as np


def _flatten(tree: Any) -> Dict[str, Any]:
    import jax
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = leaf
    return flat


def save_checkpoint(directory: str, tree: Any, step: int = 0,
                    metadata: Optional[Dict[str, Any]] = None) -> str:
    """Write ``directory/step_N`` atomically (tmpdir + rename). Returns the
    checkpoint path."""
    import jax
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"step_{step}")
    flat = {key: np.asarray(leaf) for key, leaf in _flatten(tree).items()}
    structure = jax.tree.structure(tree)
    # numpy's npz can't round-trip ml_dtypes extension types (bfloat16,
    # fp8): store them as same-width unsigned views, record the real dtype
    dtypes = {key: str(value.dtype) for key, value in flat.items()}
    stored = {}
    for key, value in flat.items():
        if value.dtype.name not in np.sctypeDict:
            value = value.view({1: np.uint8, 2: np.uint16,
                                4: np.uint32}[value.dtype.itemsize])
        stored[key] = value
    tmp = tempfile.mkdtemp(dir=directory, prefix=".tmp_ckpt_")
    try:
        np.savez(os.path.join(tmp, "arrays.npz"), **stored)
        with open(os.path.join(tmp, "tree.json"), "w") as handle:
            json.dump({
                "keys": list(flat.keys()),
                "treedef": str(structure),
                "dtypes": dtypes,
                "step": step,
                "metadata": metadata or {},
            }, handle)
        # Atomic publish even when overwriting: move the old copy aside to
        # the *discoverable* name ``step_N.old`` first, so a crash between
        # the two renames leaves either the visible checkpoint or the .old
        # fallback in place — latest_step/restore_checkpoint consult both.
        old = None
        if os.path.exists(final):
            old = final + ".old"
            if os.path.exists(old):
                shutil.rmtree(old, ignore_errors=True)
            os.rename(final, old)
        try:
            os.rename(tmp, final)
        except Exception:
            if old is not None and not os.path.exists(final):
                os.rename(old, final)  # roll the old checkpoint back in
            raise
        # the fallback is stale once the new copy is visible (also clears
        # a .old left by a previous crash when final itself was absent)
        shutil.rmtree(final + ".old", ignore_errors=True)
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


def latest_step(directory: str) -> Optional[int]:
    """Max step with a readable checkpoint — ``step_N`` or the ``step_N.old``
    fallback left by a crash mid-publish in save_checkpoint."""
    if not os.path.isdir(directory):
        return None
    steps = set()
    for name in os.listdir(directory):
        if name.startswith("step_"):
            tail = name[5:-4] if name.endswith(".old") else name[5:]
            if tail.isdigit():
                steps.add(int(tail))
    return max(steps) if steps else None


def _step_path(directory: str, step: int) -> str:
    """Resolve ``step_N``, falling back to ``step_N.old`` (crash window
    between save_checkpoint's two renames)."""
    path = os.path.join(directory, f"step_{step}")
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        return path + ".old"
    return path


def restore_checkpoint(directory: str, like: Any, step: Optional[int] = None,
                       sharding: Any = None) -> Any:
    """Restore into the structure of ``like`` (a pytree of arrays or
    ShapeDtypeStructs). ``step=None`` → latest. ``sharding``: one sharding
    for every leaf or a matching pytree of shardings."""
    import jax
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_path(directory, step)
    with open(os.path.join(path, "tree.json")) as handle:
        saved_dtypes = json.load(handle)["dtypes"]
    with np.load(os.path.join(path, "arrays.npz")) as archive:
        flat_like = _flatten(like)
        leaves = {}
        shard_tree = None
        if sharding is not None:
            is_single = not isinstance(sharding, (dict, list, tuple)) \
                and not hasattr(sharding, "keys")
            shard_tree = _flatten(sharding) if not is_single else None
        for key, leaf_like in flat_like.items():
            if key not in archive:
                raise KeyError(f"checkpoint missing leaf {key!r}")
            value = archive[key]
            saved_dtype = saved_dtypes.get(key)
            if saved_dtype and str(value.dtype) != saved_dtype:
                import ml_dtypes  # ships with jax
                value = value.view(np.dtype(getattr(ml_dtypes, saved_dtype,
                                                    saved_dtype)))
            dtype = getattr(leaf_like, "dtype", None)
            if dtype is not None and str(dtype) != str(value.dtype):
                value = value.astype(dtype)
            if sharding is not None:
                shard = shard_tree[key] if shard_tree is not None \
                    else sharding
                value = jax.device_put(value, shard)
            leaves[key] = value
    treedef = jax.tree.structure(like)
    ordered = [leaves[key] for key in flat_like.keys()]
    return jax.tree.unflatten(treedef, ordered)


def checkpoint_metadata(directory: str,
                        step: Optional[int] = None) -> Dict[str, Any]:
    if step is None:
        step = latest_step(directory)
    with open(os.path.join(_step_path(directory, step), "tree.json")) as f:
        return json.load(f)
