"""MoE + expert-parallelism tests on the virtual 8-CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import moe
from gofr_tpu.parallel import make_mesh, prune_specs, shard_pytree
from gofr_tpu.parallel.sharding import moe_param_specs


@pytest.fixture(scope="module")
def setup():
    cfg = moe.config("tiny")
    params = moe.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_moe_forward_shapes_and_aux(setup):
    cfg, params = setup
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.base.vocab_size)
    logits, aux = moe.forward(params, cfg, tokens)
    assert logits.shape == (2, 16, cfg.base.vocab_size)
    assert bool(jnp.isfinite(logits).all())
    # aux ≈ 1 for near-uniform routing, ≥ 1 by Cauchy-Schwarz
    assert 0.9 < float(aux) < float(cfg.n_experts)


def test_moe_loss_and_grads_finite(setup):
    cfg, params = setup
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                                cfg.base.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    loss, grads = jax.value_and_grad(
        lambda p: moe.loss_fn(p, cfg, tokens, targets))(params)
    assert bool(jnp.isfinite(loss))
    for leaf in jax.tree.leaves(grads):
        assert bool(jnp.isfinite(leaf).all())
    # routers receive gradient (they're on the fp32 gating path)
    assert float(jnp.abs(grads["layers"]["router"]).max()) > 0


def test_moe_ep_sharded_matches_replicated(setup):
    """Expert-parallel annotation must not change the math."""
    cfg, params = setup
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                                cfg.base.vocab_size)
    ref, _ = moe.forward(params, cfg, tokens)
    mesh = make_mesh({"dp": 2, "ep": 2, "tp": 2})
    specs = prune_specs(moe_param_specs(), mesh)
    sharded = shard_pytree(params, mesh, specs)
    out, _ = jax.jit(lambda p, t: moe.forward(p, cfg, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.1)
    assert sharded["layers"]["w_gate"].sharding.spec == \
        jax.sharding.PartitionSpec(None, "ep", None, "tp")


def test_moe_training_reduces_loss(setup):
    cfg, params = setup
    import optax
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 16), 0,
                                cfg.base.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: moe.loss_fn(p, cfg, tokens, targets))(params)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_capacity_drops_are_bounded(setup):
    """With capacity_factor >= n_experts every token must be kept, so the
    MoE output is dense (no silent zero rows)."""
    cfg, params = setup
    cfg_full = moe.config("tiny", capacity_factor=float(cfg.n_experts))
    tokens = jax.random.randint(jax.random.PRNGKey(5), (1, 8), 0,
                                cfg.base.vocab_size)
    logits_a, _ = moe.forward(params, cfg_full, tokens)
    # same params, tighter capacity: some tokens may drop to residual-only
    logits_b, _ = moe.forward(params, cfg, tokens)
    assert logits_a.shape == logits_b.shape
    assert bool(jnp.isfinite(logits_a).all())
