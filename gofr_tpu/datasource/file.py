"""Local file-system datasource with typed row readers.

Capability parity with ``pkg/gofr/datasource/file`` (fs.go:1-63 local FS
implementing the FileSystem contract; file.go:51-141 ``ReadAll`` returning a
JSON/CSV/text RowReader by extension).
"""

from __future__ import annotations

import csv
import io
import json
import os
import shutil
from typing import Iterator, List, Optional

from gofr_tpu.datasource import UP, health


class LocalFileSystem:
    """Local filesystem datasource (datasource/file.go contract).

    ``sandbox=True`` (default) confines every operation — including
    ``chdir`` and absolute paths — under the constructed root, so request
    data forwarded into fs calls cannot traverse out (``../`` or
    ``/etc/...`` raise PermissionError). Construct with ``sandbox=False``
    for trusted tooling that genuinely needs the whole host filesystem
    (the reference's Go file datasource mirrors os with no confinement).
    """

    def __init__(self, logger=None, root: str = ".", sandbox: bool = True):
        self.logger = logger
        self.root = os.path.abspath(root)
        self.sandbox = sandbox
        # realpath: the confinement check must compare symlink-resolved
        # paths, or a pre-existing symlink under root pointing outside it
        # would pass a plain prefix test (ADVICE r3)
        self._sandbox_root = os.path.realpath(self.root)

    def _full(self, name: str) -> str:
        base = name if os.path.isabs(name) else os.path.join(self.root, name)
        full = os.path.abspath(base)
        if self.sandbox:
            root = self._sandbox_root
            # resolve symlinks on the deepest existing ancestor so both
            # existing targets and to-be-created paths are checked against
            # where they will REALLY land
            resolved = os.path.realpath(full)
            if resolved != root and not resolved.startswith(root + os.sep):
                raise PermissionError(
                    f"path escapes filesystem root {root!r}: {name!r}")
        return full

    # -- FileSystem contract (datasource/file.go:10-63) ---------------------
    def create(self, name: str, content: bytes = b"") -> None:
        with open(self._full(name), "wb") as fh:
            fh.write(content)

    def read(self, name: str) -> bytes:
        with open(self._full(name), "rb") as fh:
            return fh.read()

    def write(self, name: str, content: bytes) -> None:
        self.create(name, content)

    def append(self, name: str, content: bytes) -> None:
        with open(self._full(name), "ab") as fh:
            fh.write(content)

    def remove(self, name: str) -> None:
        os.remove(self._full(name))

    def mkdir(self, name: str) -> None:
        os.makedirs(self._full(name), exist_ok=True)

    def remove_all(self, name: str) -> None:
        shutil.rmtree(self._full(name), ignore_errors=True)

    def rename(self, old: str, new: str) -> None:
        os.rename(self._full(old), self._full(new))

    def stat(self, name: str) -> dict:
        st = os.stat(self._full(name))
        return {"size": st.st_size, "mtime": st.st_mtime,
                "is_dir": os.path.isdir(self._full(name))}

    def list(self, name: str = ".") -> List[str]:
        return sorted(os.listdir(self._full(name)))

    def getwd(self) -> str:
        return os.path.abspath(self.root)

    def chdir(self, name: str) -> None:
        self.root = self._full(name)

    # -- typed row reading (datasource/file.go:51-141) ----------------------
    def read_all(self, name: str) -> "RowReader":
        ext = os.path.splitext(name)[1].lower()
        raw = self.read(name)
        if ext == ".json":
            return JSONRowReader(raw)
        if ext == ".jsonl":
            return JSONLRowReader(raw)
        if ext == ".csv":
            return CSVRowReader(raw)
        return TextRowReader(raw)

    def health_check(self) -> dict:
        return health(UP, root=self.getwd())


class RowReader:
    def __iter__(self) -> Iterator:
        raise NotImplementedError


class JSONRowReader(RowReader):
    def __init__(self, raw: bytes):
        doc = json.loads(raw.decode("utf-8"))
        self.rows = doc if isinstance(doc, list) else [doc]

    def __iter__(self):
        return iter(self.rows)


class JSONLRowReader(RowReader):
    """One JSON document per line — the LLM-dataset interchange format."""

    def __init__(self, raw: bytes):
        self.rows = []
        for number, line in enumerate(raw.decode("utf-8").splitlines(), 1):
            if not line.strip():
                continue
            try:
                self.rows.append(json.loads(line))
            except json.JSONDecodeError as exc:
                raise ValueError(f"jsonl line {number}: {exc}") from exc

    def __iter__(self):
        return iter(self.rows)


class CSVRowReader(RowReader):
    def __init__(self, raw: bytes):
        self.reader = csv.DictReader(io.StringIO(raw.decode("utf-8")))

    def __iter__(self):
        return iter(self.reader)


class TextRowReader(RowReader):
    def __init__(self, raw: bytes):
        self.lines = raw.decode("utf-8", "replace").splitlines()

    def __iter__(self):
        return iter(self.lines)
