"""GT010 negative fixture: bounded, paced, or escaping retry shapes.

Parsed by graftcheck in tests, never imported.
"""

import asyncio
import time


async def bounded_retry(transport, attempts=3):
    # the sanctioned shape (tpu/retry.py): a bounded for, no while True
    last = None
    for attempt in range(1, attempts + 1):
        try:
            return await transport.fetch()
        except Exception as exc:
            last = exc
    raise RuntimeError("attempts exhausted") from last


async def paced_poll(broker):
    # broad except but paced: a persistent failure degrades to a slow
    # poll, not a hot spin (the batch-lane consumer shape)
    while True:
        try:
            return await broker.subscribe("jobs")
        except Exception:
            await asyncio.sleep(1.0)


def escaping_loop(queue):
    # broad except that re-raises a subset: the failure can leave
    while True:
        try:
            queue.pop()
        except Exception as exc:
            if isinstance(exc, KeyboardInterrupt):
                raise
            time.sleep(0.1)


async def state_bounded(self_like, transport):
    # the loop test can go false — termination by state, not by luck
    while not self_like.draining:
        try:
            await transport.fetch()
        except Exception:
            continue


async def narrow_handler(transport):
    # a narrow handler is deliberate routing, not blind swallowing
    while True:
        try:
            return await transport.fetch()
        except ConnectionError:
            continue


def loop_body_paced(queue, stop):
    # the sleep lives in the loop body, not the handler: every
    # iteration is throttled, so the swallow cannot spin hot
    while True:
        try:
            queue.pop()
        except Exception:
            pass
        if stop.wait(1.0):
            return


async def cleanup_in_handler(transport, pending):
    # the inner try guards error-path cleanup inside a handler that
    # itself escapes — not a retried operation
    while True:
        try:
            return await transport.fetch()
        except Exception:
            for task in pending:
                try:
                    task.cancel()
                except Exception:
                    pass
            raise


async def try_wraps_loop(transport):
    # the try is OUTSIDE the loop: a caught failure exits, not retries
    try:
        while True:
            await transport.fetch()
    except Exception:
        return None
