"""Multi-host mesh helpers + profiler endpoint tests (single-host paths)."""

import json
import os

import jax
import pytest

from gofr_tpu.config import MapConfig
from gofr_tpu.parallel.multihost import (
    hybrid_mesh,
    initialize_distributed,
    process_info,
)
from tests.util import http_request, make_app, run, serving


def test_initialize_distributed_noop_without_coordinator():
    assert initialize_distributed(MapConfig({})) is False


def test_hybrid_mesh_single_host():
    mesh = hybrid_mesh({"dp": 4, "tp": 2}, {"dp_outer": 1})
    assert dict(mesh.shape) == {"dp_outer": 1, "dp": 4, "tp": 2}
    # dcn axis present but degenerate: sharding over it is a no-op
    mesh2 = hybrid_mesh({"dp": 8})
    assert dict(mesh2.shape) == {"dp": 8}


def test_hybrid_mesh_rejects_oversized_dcn():
    with pytest.raises(ValueError):
        hybrid_mesh({"dp": 4}, {"dp_outer": 2})  # only 1 process


def test_process_info():
    info = process_info()
    assert info["process_count"] == 1
    assert info["global_devices"] == 8


def test_profiler_endpoints(tmp_path):
    async def main():
        app = make_app()
        app.enable_profiler()
        trace_dir = str(tmp_path / "trace")
        async with serving(app) as port:
            started = await http_request(
                port, "POST", "/debug/profiler/start",
                body=json.dumps({"dir": trace_dir}).encode(),
                headers={"Content-Type": "application/json"})
            assert started.json()["data"]["status"] == "started"
            # profiling something gives the trace real content
            jax.block_until_ready(
                jax.jit(lambda x: x * 2)(jax.numpy.ones((8, 8))))
            stopped = await http_request(port, "POST",
                                         "/debug/profiler/stop")
            assert stopped.json()["data"]["status"] == "stopped"
            assert os.path.isdir(trace_dir)
            again = await http_request(port, "POST", "/debug/profiler/stop")
            assert again.json()["data"]["status"] == "not profiling"
    run(main())


def test_profiler_state_is_per_app(tmp_path):
    """Two apps in one process: one app's profiling session must not be
    visible through (or clobbered by) the other's endpoints."""
    async def main():
        app_a, app_b = make_app(), make_app()
        app_a.enable_profiler()
        app_b.enable_profiler()
        trace_dir = str(tmp_path / "trace-a")
        async with serving(app_a) as port_a:
            async with serving(app_b) as port_b:
                started = await http_request(
                    port_a, "POST", "/debug/profiler/start",
                    body=json.dumps({"dir": trace_dir}).encode(),
                    headers={"Content-Type": "application/json"})
                assert started.json()["data"]["status"] == "started"
                # B has its own state: it is not profiling, and its stop
                # must not end A's session
                other = await http_request(port_b, "POST",
                                           "/debug/profiler/stop")
                assert other.json()["data"]["status"] == "not profiling"
                stopped = await http_request(port_a, "POST",
                                             "/debug/profiler/stop")
                assert stopped.json()["data"]["status"] == "stopped"
                assert stopped.json()["data"]["dir"] == trace_dir
    run(main())
