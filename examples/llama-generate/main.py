"""Llama /generate endpoint — continuous-batching serving with HBM KV cache
(BASELINE.md config 5).

Serving engine: slot-based continuous batching (gofr_tpu.tpu.GenerationEngine)
— concurrent requests share decode steps; prompts prefill into per-slot KV
cache regions without recompiles. Uses the framework BPE tokenizer (C++
encode path when the toolchain is present).

For tensor parallelism over a slice set ``TPU_MESH=dp:1,tp:8``: the engine
shards params with gofr_tpu.parallel.llama_param_specs (Megatron column/row
specs) and the KV cache with llama_cache_specs (slots on dp, kv-heads on
tp); XLA inserts the all-reduces over ICI.

Multi-model serving (ISSUE 7): ``MODELS=big=small>cheap,cheap=tiny,moe=moe``
registers several named engines behind one ModelRegistry — ``name=preset``
entries, ``>fallback`` names the model DEGRADED traffic shifts to, the first
entry is the default. Co-resident llama models share one KV page pool when
``GENERATE_PAGED_KV=1``. Per-model routes:

POST /v1/{model}/generate and /v1/{model}/generate/stream — same bodies as
below, routed through the registry (503 when the model and its fallback
cannot serve).

POST /generate {"prompt": "...", "max_new_tokens": 32,
                "temperature": 0.8, "top_k": 40, "top_p": 0.95, "seed": 1}
POST /generate/stream — same body, Server-Sent-Events: one ``data:`` frame
per token as it is decoded (time-to-first-token = prefill latency), then a
final ``[DONE]`` frame. gRPC analog: server-streaming
``/gofr.Llama/generate`` (one JSON message per token).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import new_app
from gofr_tpu.tokenizer import Tokenizer


def build_app():
    import jax

    from gofr_tpu.models import llama, moe
    from gofr_tpu.tpu import (GenerationEngine, ModelRegistry,
                              ModelUnavailable, PagePool)
    from gofr_tpu.tpu.sched import parse_class_weights

    app = new_app()
    kv_int8 = os.environ.get("LLAMA_KV_INT8") == "1"
    paged_kv = os.environ.get("GENERATE_PAGED_KV") == "1"
    kv_page = int(os.environ.get("GENERATE_KV_PAGE", "32"))
    # SLO-class weighted-fair scheduling: admission interleaves deadline
    # classes by weight (docs/tpu/model-serving.md "SLO classes")
    class_weights = parse_class_weights(os.environ.get("SLO_CLASS_WEIGHTS"))
    # speculative decode: a cheap draft proposes GENERATE_SPEC_GAMMA
    # tokens per tick, the target verifies them in one batched forward
    draft_preset = os.environ.get("GENERATE_DRAFT_MODEL")
    spec_gamma = int(os.environ.get("GENERATE_SPEC_GAMMA", "4"))

    mesh = None
    if app.config.get("TPU_MESH"):
        from gofr_tpu.parallel import make_mesh
        axes = {}
        for part in str(app.config.get("TPU_MESH")).split(","):
            axis, _, size = part.partition(":")
            axes[axis.strip()] = int(size)
        mesh = make_mesh(axes)

    def model_config(preset):
        """`moe`/`moe-<preset>` → MoE variant; anything else is a llama
        preset. Byte-level vocab either way."""
        if preset == "moe" or preset.startswith("moe-"):
            base = preset[4:] if preset.startswith("moe-") else "tiny"
            return moe, moe.config(
                base=llama.config(base, vocab_size=256, kv_int8=kv_int8))
        return llama, llama.config(preset, vocab_size=256, kv_int8=kv_int8)

    def make_engine(preset, name, seed, with_draft, page_pool=None):
        module, cfg = model_config(preset)
        params = module.init(cfg, jax.random.PRNGKey(seed))
        draft_cfg = draft_params = None
        if with_draft and module is llama and draft_preset:
            draft_cfg = llama.config(draft_preset, vocab_size=256)
            draft_params = llama.init(draft_cfg, jax.random.PRNGKey(seed + 1))
        return GenerationEngine(
            cfg, params, mesh=mesh if module is llama else None,
            max_slots=int(os.environ.get("GENERATE_SLOTS", "8")),
            max_len=min(cfg.max_seq_len, 1024),
            # fused decode steps per host round trip (amortises dispatch;
            # the adaptive ladder drops back to 1 while admissions wait).
            # r5 measured K=8 ticks costing less device time than their own
            # dispatch on a high-latency host — 16 is the safer default, 32
            # for throughput-first serving (docs/tpu/benchmarking.md)
            steps_per_tick=int(os.environ.get("STEPS_PER_TICK", "16")),
            # decode ticks in flight before the oldest fetch must land:
            # token fetches overlap device compute and each other
            max_inflight_ticks=int(os.environ.get("INFLIGHT_TICKS", "4")),
            # prefix KV reuse: shared prompt prefixes (system prompts,
            # few-shot templates) prefill only their suffix against cached
            # KV pages; greedy outputs stay token-identical with bf16
            # caches (docs/tpu/model-serving.md "Prefix KV reuse")
            prefix_cache=(module is llama
                          and os.environ.get("GENERATE_PREFIX_CACHE") == "1"),
            prefix_cache_bytes=int(os.environ.get(
                "GENERATE_PREFIX_CACHE_BYTES", str(64 << 20))),
            # unified paged KV: one page pool shared by prefill output, the
            # prefix cache and decode (MoE serves dense — no paged step)
            paged_kv=paged_kv and module is llama,
            kv_page=kv_page,
            kv_pool_bytes=(int(os.environ["GENERATE_KV_POOL_BYTES"])
                           if "GENERATE_KV_POOL_BYTES" in os.environ
                           and page_pool is None else None),
            page_pool=page_pool,
            model_module=None if module is llama else module,
            model_name=name,
            draft_cfg=draft_cfg, draft_params=draft_params,
            spec_gamma=spec_gamma,
            class_weights=class_weights,
            logger=app.logger, metrics=app.container.metrics,
            # flight recorder: queue.wait/prefill/decode child spans per
            # request, engine-step spans with links, /debug/statusz views
            tracer=app.container.tracer,
            # SLO accounting: X-Request-Deadline-Ms classification (ok/
            # violated/expired), windowed TTFT quantiles, goodput vs raw
            # tokens/s — feeds /debug/varz and the degradation watchdog
            slo=app.container.slo)

    tokenizer = Tokenizer()  # byte-level; swap in a trained vocab via load()
    models_spec = os.environ.get("MODELS", "").strip()
    registry = None
    if models_spec:
        # "name=preset[>fallback]" entries, comma-separated, first=default
        registry = ModelRegistry(
            watchdog=getattr(app.container, "watchdog", None),
            logger=app.logger, metrics=app.container.metrics)
        parsed = []
        for part in models_spec.split(","):
            name, _, rest = part.strip().partition("=")
            preset, _, fallback = rest.partition(">")
            parsed.append((name.strip(), (preset or "small").strip(),
                           fallback.strip() or None))
        shared_pool = None
        if paged_kv:
            # co-resident llama engines share one page pool: page ids are
            # interchangeable, occupancy is chip-global
            _, pool_cfg = model_config(parsed[0][1])
            shared_pool = PagePool(
                pool_cfg, page=kv_page, mesh=mesh,
                budget_bytes=int(os.environ.get(
                    "GENERATE_KV_POOL_BYTES", str(256 << 20))),
                metrics=app.container.metrics)
            registry.page_pool = shared_pool
        for seed, (name, preset, fallback) in enumerate(parsed):
            module, cfg = model_config(preset)
            pool = shared_pool if module is llama else None
            eng = make_engine(preset, name, seed * 2, seed == 0,
                              page_pool=pool)
            registry.register(name, eng, fallback=fallback,
                              default=(seed == 0))
        engine = registry.engine()     # default model (admin accessor —
        app.container.tpu = registry   # entries are LOADING until warmup);
        #                                per-model health/statusz/varz/xlaz
    else:
        preset = os.environ.get("LLAMA_PRESET", "small")
        engine = make_engine(preset, "generate", 0, True)
        app.container.tpu = engine  # surfaces engine health at /.well-known
    app.enable_statusz()        # live queue/slot/KV-cache/timeline snapshot
    app.enable_varz()           # windowed SLO/goodput/saturation numbers
    app.enable_xlaz()           # compile ledger + prompt-bucket fit view

    @app.on_startup
    async def warm_engine():
        # precompile the decode ladder + prefill/insert executables before
        # the first request: a cold compile is seconds of request latency
        if registry is not None:
            for name in registry.models():
                eng = registry.engine(name)
                await registry.warmup(
                    name, prompt_counts=(1, eng.max_slots))
            await registry.start()
        else:
            await engine.warmup(prompt_counts=(1, engine.max_slots))
            await engine.start()

    @app.on_shutdown
    async def log_suggested_ladder():
        # close the bucket-tuning loop (docs/tpu/model-serving.md): the
        # padding-optimal prompt ladder for the traffic this process saw,
        # ready to paste into the next deploy's prompt_buckets
        fit = engine.xlaz()["models"]["prompt"]
        if fit["suggested_ladder"]:
            app.logger.info(
                "prompt-bucket fit at shutdown: configured=%s observed=%s "
                "suggested=%s", fit["ladder"],
                fit["observed_batch_sizes"], fit["suggested_ladder"])

    from gofr_tpu.http.errors import HTTPError
    from gofr_tpu.tpu.generate import Sampling

    class BadRequest(HTTPError):
        status_code = 400

    class Unavailable(HTTPError):
        status_code = 503

    def resolve_engine(ctx=None):
        """Default engine, or the registry route for /v1/{model}/..."""
        name = ctx.path_param("model") if ctx is not None else None
        if registry is None:
            if name:
                raise BadRequest(
                    "multi-model routing is off (set MODELS to enable)")
            return engine
        try:
            return registry.route(name or None)
        except KeyError as exc:
            raise BadRequest(str(exc)) from exc
        except ModelUnavailable as exc:
            raise Unavailable(str(exc)) from exc

    def parse_request(data):
        try:
            prompt_ids = tokenizer.encode(data["prompt"])[-512:]
            max_new = int(data.get("max_new_tokens", 32))
            seed = data.get("seed")
            # seed omitted → fresh entropy per request (two sampled
            # requests differ); an explicit seed reproduces a completion
            sampling = Sampling(
                temperature=float(data.get("temperature", 0.0)),
                top_k=int(data.get("top_k", 0)),
                top_p=float(data.get("top_p", 1.0)),
                seed=int(seed) if seed is not None else None)
        except KeyError as exc:
            raise BadRequest(f"missing field: {exc}") from exc
        except (TypeError, ValueError) as exc:
            raise BadRequest(f"bad field value: {exc}") from exc
        return prompt_ids, max_new, sampling

    async def start_stream(eng, data):
        """Validate + admit eagerly so bad requests fail with a 400 before
        any stream bytes are written."""
        prompt_ids, max_new, sampling = parse_request(data)
        try:
            return await eng.generate_stream(
                prompt_ids, max_new_tokens=max_new, sampling=sampling)
        except ValueError as exc:
            raise BadRequest(str(exc)) from exc

    async def generate(ctx):
        eng = resolve_engine(ctx)
        await eng.start()  # idempotent; binds to the serving loop
        prompt_ids, max_new, sampling = parse_request(ctx.bind())
        try:
            out = await eng.generate(prompt_ids, max_new_tokens=max_new,
                                     sampling=sampling)
        except ValueError as exc:
            raise BadRequest(str(exc)) from exc
        return {"completion": tokenizer.decode(out),
                "tokens": out, "model": eng.model_name,
                "engine": eng.stats()}

    async def generate_stream(ctx):
        from gofr_tpu.http.response import Stream
        eng = resolve_engine(ctx)
        await eng.start()
        stream = await start_stream(eng, ctx.bind())

        async def frames():
            import json
            try:
                async for token in stream:
                    yield json.dumps({"token": token,
                                      "text": tokenizer.decode([token])})
                yield "[DONE]"
            finally:
                # client disconnect acloses frames(); propagate to the
                # engine stream so the slot stops decoding
                await stream.aclose()

        # on_close covers the one path frames()'s finally cannot: the
        # client vanishing before the response writer ever starts the
        # generator (an unstarted generator's aclose skips the body)
        return Stream(frames(), sse=True, on_close=stream.cancel)

    async def generate_grpc_stream(ctx):
        eng = resolve_engine()
        await eng.start()
        stream = await start_stream(eng, ctx.request.payload)

        async def tokens():
            try:
                async for token in stream:
                    yield {"token": token,
                           "text": tokenizer.decode([token])}
            finally:
                await stream.aclose()   # RPC cancelled → free the slot

        return tokens()

    app.post("/generate", generate)
    app.post("/generate/stream", generate_stream)
    app.post("/v1/{model}/generate", generate)
    app.post("/v1/{model}/generate/stream", generate_stream)
    app.register_grpc_stream("Llama", "generate", generate_grpc_stream)
    return app


if __name__ == "__main__":
    build_app().run()
