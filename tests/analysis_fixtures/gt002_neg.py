"""GT002 negative fixture: every spawn's outcome is observed.

Parsed by graftcheck in tests, never imported.
"""

import asyncio

from gofr_tpu.aio import spawn_logged


async def worker():
    return 1


async def awaited_inline():
    return await asyncio.create_task(worker())


def returned():
    return asyncio.ensure_future(worker())


async def callback_attached():
    task = asyncio.create_task(worker())
    task.add_done_callback(lambda done: done.exception())
    return task


async def awaited_later():
    task = asyncio.ensure_future(worker())
    await asyncio.sleep(0)
    await task


def via_spawn_logged(logger, metrics):
    return spawn_logged(worker(), logger, "fixture.worker", metrics=metrics)
