"""Shared test helpers: run an App on ephemeral ports + tiny HTTP client."""

from __future__ import annotations

import asyncio
import contextlib
import json
from typing import Any, Dict, Optional, Tuple

from gofr_tpu.app import App
from gofr_tpu.container import new_mock_container


def run(coro):
    return asyncio.run(coro)


def make_app(config: Optional[Dict[str, str]] = None) -> App:
    container = new_mock_container(config)
    app = App(config=container.config, container=container)
    app.http_port = 0
    app.metrics_port = 0
    return app


class HTTPResult:
    def __init__(self, status: int, headers: Dict[str, str], body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    def json(self) -> Any:
        return json.loads(self.body.decode())


async def http_request(port: int, method: str, path: str,
                       body: bytes = b"",
                       headers: Optional[Dict[str, str]] = None) -> HTTPResult:
    """Minimal raw HTTP/1.1 client — also exercises our server's parser."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        head = f"{method} {path} HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
        for key, value in (headers or {}).items():
            head += f"{key}: {value}\r\n"
        head += f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
        writer.write(head.encode() + body)
        await writer.drain()
        raw = await reader.read()
    finally:
        writer.close()
        with contextlib.suppress(Exception):
            await writer.wait_closed()
    header_blob, _, payload = raw.partition(b"\r\n\r\n")
    lines = header_blob.split(b"\r\n")
    status = int(lines[0].split()[1])
    resp_headers: Dict[str, str] = {}
    for line in lines[1:]:
        name, _, value = line.decode("latin-1").partition(":")
        resp_headers[name.strip().lower()] = value.strip()
    return HTTPResult(status, resp_headers, payload)


def parse_chunked(payload: bytes) -> bytes:
    """Decode a chunked transfer-encoded body."""
    out = bytearray()
    rest = payload
    while rest:
        size_line, _, rest = rest.partition(b"\r\n")
        size = int(size_line.split(b";")[0], 16)
        if size == 0:
            break
        out.extend(rest[:size])
        rest = rest[size + 2:]   # skip chunk + trailing CRLF
    return bytes(out)


def parse_sse(body: bytes):
    """Split an SSE stream into its ``data:`` payload strings."""
    events = []
    for frame in body.split(b"\n\n"):
        for line in frame.split(b"\n"):
            if line.startswith(b"data: "):
                events.append(line[len(b"data: "):].decode())
    return events


@contextlib.asynccontextmanager
async def serving(app: App):
    await app.start()
    try:
        yield app._http_server.bound_port
    finally:
        await app.stop()
