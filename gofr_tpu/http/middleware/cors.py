"""CORS middleware with env-driven overrides.

Capability parity with ``pkg/gofr/http/middleware/cors.go`` (default ``*``
origin + allowed methods from the registered route table 13-57) and
``config.go:14-31`` (``ACCESS_CONTROL_*`` env overrides).
"""

from __future__ import annotations

from typing import Dict

from gofr_tpu.config import Config
from gofr_tpu.http.router import Middleware, Router, WireHandler

_OVERRIDABLE = {
    "ACCESS_CONTROL_ALLOW_ORIGIN": "Access-Control-Allow-Origin",
    "ACCESS_CONTROL_ALLOW_HEADERS": "Access-Control-Allow-Headers",
    "ACCESS_CONTROL_ALLOW_CREDENTIALS": "Access-Control-Allow-Credentials",
    "ACCESS_CONTROL_EXPOSE_HEADERS": "Access-Control-Expose-Headers",
    "ACCESS_CONTROL_MAX_AGE": "Access-Control-Max-Age",
}


def cors_middleware(config: Config, router: Router) -> Middleware:
    base_headers: Dict[str, str] = {
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Headers":
            "Authorization, Content-Type, x-requested-with, origin, true-client-ip, X-Correlation-ID",
    }
    for env_key, header in _OVERRIDABLE.items():
        value = config.get(env_key)
        if value:
            base_headers[header] = value

    def middleware(next_handler: WireHandler) -> WireHandler:
        async def handle(request):
            if request.method == "OPTIONS":
                methods = router.methods_for(request.path)
                allow = ", ".join(methods + ["OPTIONS"]) if methods else "OPTIONS"
                headers = dict(base_headers)
                headers["Access-Control-Allow-Methods"] = allow
                return 200, headers, b""
            status, headers, body = await next_handler(request)
            for name, value in base_headers.items():
                headers.setdefault(name, value)
            return status, headers, body
        return handle
    return middleware
