"""graftcheck CLI: ``python -m gofr_tpu.analysis [paths...]``.

Exit 0 = no unsuppressed findings beyond the committed baseline;
exit 1 = new findings (printed one per line as ``path:line: RULE msg``)
or unparseable files.

Modes beyond the plain scan:

- ``--sarif out.sarif`` — also write a SARIF 2.1.0 artifact for CI.
- ``--timings`` — per-rule wall-clock summary on stderr.
- ``--changed-only BASE`` — analyze only files changed vs the git rev
  ``BASE`` (plus untracked), reusing cached findings for the rest.
- ``--pragma-audit`` — report stale ``# graftcheck: ignore`` pragmas.
- ``--local`` — module-local v1 analysis (no project graph); the
  regression tests pin what interprocedural mode buys over this.
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys

from gofr_tpu.analysis import engine
from gofr_tpu.analysis.rules import ALL_RULES, default_rules


def _changed_files(base: str) -> set:
    """Repo-relative posix paths of *.py files changed vs ``base``,
    plus untracked ones — the working-tree delta a pre-commit run
    cares about."""
    changed = set()
    for args in (["git", "diff", "--name-only", base, "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(
            args, cwd=engine.ROOT, capture_output=True, text=True,
            check=False)
        if proc.returncode != 0:
            raise SystemExit(
                f"graftcheck: git failed: {' '.join(args)}: "
                f"{proc.stderr.strip()}")
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                changed.add(pathlib.PurePosixPath(line).as_posix())
    return changed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m gofr_tpu.analysis",
        description="graftcheck: serving-aware static analysis "
                    "(rule catalog: docs/references/static-analysis.md)")
    parser.add_argument(
        "paths", nargs="*", type=pathlib.Path,
        help="files/directories to scan (default: the gofr_tpu package)")
    parser.add_argument(
        "--baseline", type=pathlib.Path, default=engine.DEFAULT_BASELINE,
        help="grandfathered-findings file "
             "(default: scripts/graftcheck_baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every unsuppressed finding")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0")
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)")
    parser.add_argument(
        "--docs", type=pathlib.Path, default=None,
        help="metrics catalog for GT005 "
             "(default: docs/quick-start/observability.md)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    parser.add_argument(
        "--sarif", type=pathlib.Path, default=None, metavar="OUT",
        help="also write a SARIF 2.1.0 report to OUT")
    parser.add_argument(
        "--timings", action="store_true",
        help="print per-rule wall-clock timings to stderr")
    parser.add_argument(
        "--cache", type=pathlib.Path, default=engine.DEFAULT_CACHE,
        metavar="PATH",
        help="incremental cache file (default: .graftcheck_cache.json; "
             "safe to delete anytime)")
    parser.add_argument(
        "--no-cache", action="store_true",
        help="disable the incremental cache for this run")
    parser.add_argument(
        "--changed-only", default=None, metavar="BASE",
        help="analyze only files changed vs git rev BASE (plus "
             "untracked), reusing cached findings for the rest; "
             "cross-file rules (GT005/GT013) are skipped in this mode")
    parser.add_argument(
        "--pragma-audit", action="store_true",
        help="report stale '# graftcheck: ignore' pragmas and exit "
             "(1 if any are stale)")
    parser.add_argument(
        "--local", action="store_true",
        help="module-local analysis: disable the cross-module project "
             "graph (v1 behavior)")
    opts = parser.parse_args(argv)

    if opts.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.rule_id}  {cls.title}")
        return 0

    select = [token.strip() for token in opts.select.split(",")
              if token.strip()] or None
    options = {}
    if opts.docs is not None:
        options["docs_catalog"] = opts.docs
    rules = default_rules(select=select, **options)
    paths = opts.paths or [engine.PACKAGE]
    interprocedural = not opts.local

    if opts.pragma_audit:
        stale = engine.audit_pragmas(
            paths=paths, rules=rules, interprocedural=interprocedural)
        for pragma in stale:
            print(pragma.render(), file=sys.stderr)
        if stale:
            print(f"graftcheck: {len(stale)} stale pragma(s)",
                  file=sys.stderr)
            return 1
        print("graftcheck: pragma audit OK — every pragma still "
              "suppresses a live finding")
        return 0

    restrict = None
    if opts.changed_only is not None:
        restrict = _changed_files(opts.changed_only)

    cache_path = None if opts.no_cache else opts.cache
    baseline = {} if (opts.no_baseline or opts.write_baseline) \
        else engine.load_baseline(opts.baseline)
    report = engine.run(paths=paths, rules=rules, baseline=baseline,
                        interprocedural=interprocedural,
                        cache_path=cache_path, restrict=restrict)

    if opts.write_baseline:
        engine.write_baseline(opts.baseline, report.new_findings)
        print(f"graftcheck: wrote {len(report.new_findings)} grandfathered "
              f"finding(s) to {opts.baseline}")
        return 0

    if opts.sarif is not None:
        from gofr_tpu.analysis.sarif import write_sarif
        write_sarif(opts.sarif, report, rules)

    for error in report.parse_errors:
        print(error, file=sys.stderr)
    for finding in report.new_findings:
        print(finding.render(), file=sys.stderr)
    if opts.timings and report.timings:
        total = sum(report.timings.values())
        print("graftcheck: timings (s):", file=sys.stderr)
        for name, secs in sorted(report.timings.items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {name:<14} {secs:8.3f}", file=sys.stderr)
        print(f"  {'total':<14} {total:8.3f}", file=sys.stderr)
    if report.from_cache:
        print("graftcheck: warm cache hit — report reconstructed "
              "without parsing", file=sys.stderr)
    if report.stale_baseline:
        # informational: the debt shrank — tighten the pin so it can't grow
        print(f"graftcheck: note: {len(report.stale_baseline)} baseline "
              f"entr{'y is' if len(report.stale_baseline) == 1 else 'ies are'}"
              f" stale (fixed?) — regenerate with --write-baseline",
              file=sys.stderr)
    if report.exit_code:
        print(f"graftcheck: {len(report.new_findings)} new finding(s) "
              f"({report.files_scanned} files, "
              f"{len(report.baselined)} baselined, "
              f"{report.suppressed} pragma-suppressed)", file=sys.stderr)
        return 1
    print(f"graftcheck: OK ({report.files_scanned} files, "
          f"{report.cached_files} from cache, "
          f"{len(report.baselined)} baselined, "
          f"{report.suppressed} pragma-suppressed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
