"""Cross-framework parity: converted HF/torchvision weights must reproduce
the torch forward pass — validates RoPE/GQA/SwiGLU/LayerNorm/BN-fold
semantics against the canonical implementations, not just shapes."""

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from gofr_tpu.models import bert as bert_mod
from gofr_tpu.models import convert, llama as llama_mod, resnet as resnet_mod


def test_llama_parity_with_hf():
    transformers = pytest.importorskip("transformers")
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = llama_mod.config("tiny", dtype=jnp.float32)
    params = convert.from_torch_llama(hf_model.state_dict(), cfg)

    tokens = np.array([[3, 17, 92, 45, 8, 120]], np.int64)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(llama_mod.forward(params, cfg,
                                        jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_bert_parity_with_hf():
    transformers = pytest.importorskip("transformers")
    hf_cfg = transformers.BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, type_vocab_size=2,
        layer_norm_eps=1e-12, hidden_act="gelu")
    torch.manual_seed(0)
    hf_model = transformers.BertModel(hf_cfg).eval()

    cfg = bert_mod.config("tiny", dtype=jnp.float32)
    params = convert.from_torch_bert(hf_model.state_dict(), cfg)

    ids = np.array([[5, 9, 44, 2, 99, 1, 0, 0]], np.int64)
    mask = np.array([[1, 1, 1, 1, 1, 1, 0, 0]], np.int64)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids),
                       attention_mask=torch.from_numpy(mask))
    ours = bert_mod.apply(params, cfg, jnp.asarray(ids, jnp.int32),
                          jnp.asarray(mask, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours["sequence"]),
                               ref.last_hidden_state.numpy(),
                               atol=2e-4, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(ours["pooled"]),
                               ref.pooler_output.numpy(),
                               atol=2e-4, rtol=2e-3)


def _synthetic_resnet50_state(cfg, seed=0):
    """A state dict with torchvision's exact naming/shapes (see
    torchvision.models.resnet), random values."""
    torch.manual_seed(seed)
    state = {}

    def add_conv(name, bn, c_out, c_in, k):
        state[name + ".weight"] = torch.randn(c_out, c_in, k, k) * 0.05
        state[bn + ".weight"] = torch.rand(c_out) + 0.5
        state[bn + ".bias"] = torch.randn(c_out) * 0.1
        state[bn + ".running_mean"] = torch.randn(c_out) * 0.1
        state[bn + ".running_var"] = torch.rand(c_out) + 0.5

    add_conv("conv1", "bn1", 64, 3, 7)
    c_in = 64
    for stage_idx, n_blocks in enumerate(cfg.stage_sizes):
        c_mid = 64 * (2 ** stage_idx)
        for block_idx in range(n_blocks):
            p = f"layer{stage_idx + 1}.{block_idx}"
            add_conv(p + ".conv1", p + ".bn1", c_mid, c_in, 1)
            add_conv(p + ".conv2", p + ".bn2", c_mid, c_mid, 3)
            add_conv(p + ".conv3", p + ".bn3", c_mid * 4, c_mid, 1)
            if block_idx == 0:
                add_conv(p + ".downsample.0", p + ".downsample.1",
                         c_mid * 4, c_in, 1)
            c_in = c_mid * 4
    state["fc.weight"] = torch.randn(1000, 2048) * 0.05
    state["fc.bias"] = torch.randn(1000) * 0.1
    return state


def _torch_resnet50_forward(state, cfg, x):
    """Canonical ResNet-50 v1.5 forward in plain torch, driven directly
    off a torchvision-layout state dict (mirrors
    torchvision.models.resnet.ResNet._forward_impl: 7x7/2 pad3 stem →
    3x3/2 pad1 maxpool → bottleneck stages with stride on the 3x3 →
    global avgpool → fc). torchvision itself is not in this image, so the
    architecture is reimplemented here as the independent reference."""
    F = torch.nn.functional

    def conv_bn(x, conv, bn, stride, padding):
        x = F.conv2d(x, state[conv + ".weight"], stride=stride,
                     padding=padding)
        return F.batch_norm(
            x, state[bn + ".running_mean"], state[bn + ".running_var"],
            state[bn + ".weight"], state[bn + ".bias"],
            training=False, eps=1e-5)

    x = F.relu(conv_bn(x, "conv1", "bn1", 2, 3))
    x = F.max_pool2d(x, kernel_size=3, stride=2, padding=1)
    for stage_idx, n_blocks in enumerate(cfg.stage_sizes):
        for block_idx in range(n_blocks):
            p = f"layer{stage_idx + 1}.{block_idx}"
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            identity = x
            out = F.relu(conv_bn(x, p + ".conv1", p + ".bn1", 1, 0))
            out = F.relu(conv_bn(out, p + ".conv2", p + ".bn2", stride, 1))
            out = conv_bn(out, p + ".conv3", p + ".bn3", 1, 0)
            if block_idx == 0:
                identity = conv_bn(x, p + ".downsample.0",
                                   p + ".downsample.1", stride, 0)
            x = F.relu(out + identity)
    x = x.mean(dim=(2, 3))
    return F.linear(x, state["fc.weight"], state["fc.bias"])


def test_resnet50_parity_with_torch_reference():
    """Full-depth ResNet-50 forward parity: converted weights through our
    NHWC/folded-BN JAX model must reproduce the canonical torch forward
    (conv padding/stride placement, BN folding, pool semantics, head).
    96x96 input keeps CPU time sane while exercising every stride-2
    boundary case."""
    cfg = resnet_mod.config("50", dtype=jnp.float32)
    state = _synthetic_resnet50_state(cfg)
    params = convert.from_torch_resnet50(state, cfg)

    image = np.random.default_rng(0).standard_normal(
        (2, 96, 96, 3)).astype(np.float32)
    with torch.no_grad():
        ref = _torch_resnet50_forward(
            state, cfg, torch.from_numpy(image.transpose(0, 3, 1, 2))
        ).numpy()
    ours = np.asarray(resnet_mod.apply(params, cfg, jnp.asarray(image)))
    np.testing.assert_allclose(ours, ref, atol=5e-3, rtol=1e-2)


def test_resnet50_convert_structure_and_bn_fold():
    """No torchvision in the image: build a synthetic state dict with
    torchvision's exact naming/shapes, check the converted tree matches
    our init layout and that BN folding is mathematically right."""
    import jax

    cfg = resnet_mod.config("50", dtype=jnp.float32)

    state = {}

    def add_conv(name, bn, c_out, c_in, k):
        state[name + ".weight"] = torch.randn(c_out, c_in, k, k)
        state[bn + ".weight"] = torch.rand(c_out) + 0.5
        state[bn + ".bias"] = torch.randn(c_out)
        state[bn + ".running_mean"] = torch.randn(c_out)
        state[bn + ".running_var"] = torch.rand(c_out) + 0.5

    add_conv("conv1", "bn1", 64, 3, 7)
    c_in = 64
    for stage_idx, n_blocks in enumerate(cfg.stage_sizes):
        c_mid = 64 * (2 ** stage_idx)
        for block_idx in range(n_blocks):
            p = f"layer{stage_idx + 1}.{block_idx}"
            add_conv(p + ".conv1", p + ".bn1", c_mid, c_in, 1)
            add_conv(p + ".conv2", p + ".bn2", c_mid, c_mid, 3)
            add_conv(p + ".conv3", p + ".bn3", c_mid * 4, c_mid, 1)
            if block_idx == 0:
                add_conv(p + ".downsample.0", p + ".downsample.1",
                         c_mid * 4, c_in, 1)
            c_in = c_mid * 4
    state["fc.weight"] = torch.randn(1000, 2048)
    state["fc.bias"] = torch.randn(1000)

    params = convert.from_torch_resnet50(state, cfg)
    ref = jax.eval_shape(lambda k: resnet_mod.init(cfg, k),
                         jax.random.PRNGKey(0))
    assert jax.tree.structure(params) == jax.tree.structure(ref)
    for got, want in zip(jax.tree.leaves(params), jax.tree.leaves(ref)):
        assert got.shape == want.shape

    # BN fold correctness: conv(x)*scale+shift == BN(conv(x))
    x = torch.randn(1, 3, 16, 16)
    w = state["conv1.weight"]
    y = torch.nn.functional.conv2d(x, w, stride=2, padding=3)
    bn = torch.nn.BatchNorm2d(64).eval()
    bn.weight.data = state["bn1.weight"]
    bn.bias.data = state["bn1.bias"]
    bn.running_mean.data = state["bn1.running_mean"]
    bn.running_var.data = state["bn1.running_var"]
    with torch.no_grad():
        ref_out = bn(y).numpy()
    folded = (y.numpy().transpose(0, 2, 3, 1)
              * np.asarray(params["stem"]["scale"])
              + np.asarray(params["stem"]["shift"]))
    np.testing.assert_allclose(folded.transpose(0, 3, 1, 2), ref_out,
                               atol=1e-4, rtol=1e-4)
