"""API-key auth middleware (``X-API-KEY`` header).

Capability parity with ``pkg/gofr/http/middleware/apikey_auth.go:21-68``
(static key list or validator callback, container-aware variant).
"""

from __future__ import annotations

import json
from typing import Callable, Optional, Sequence

from gofr_tpu.http.router import Middleware, WireHandler
from gofr_tpu.http.middleware.basic_auth import _is_well_known


def api_key_auth_middleware(
    keys: Sequence[str] = (),
    validate: Optional[Callable[..., bool]] = None,
    container=None,
) -> Middleware:
    key_set = set(keys)

    def middleware(next_handler: WireHandler) -> WireHandler:
        async def handle(request):
            if _is_well_known(request.path):
                return await next_handler(request)
            key = request.headers.get("x-api-key", "")
            if validate is not None:
                ok = validate(container, key) if container is not None else validate(key)
            else:
                ok = key in key_set
            if not ok:
                body = json.dumps({"error": {"message": "Unauthorized"}}).encode()
                return 401, {"Content-Type": "application/json"}, body
            return await next_handler(request)
        return handle
    return middleware
