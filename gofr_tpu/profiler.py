"""On-demand XLA profiling over HTTP.

The reference exposes no profiler (SURVEY.md §5 "no pprof endpoints");
for a TPU serving process a trace is the first diagnostic, so the
framework wires jax.profiler behind two admin routes:

  POST /debug/profiler/start {"dir": "/tmp/trace"}   → starts a trace
  POST /debug/profiler/stop                          → stops, returns dir

The captured directory is TensorBoard/XProf-compatible. Routes are only
registered via ``app.enable_profiler()`` — never on by default.

State is per-``enable_profiler`` call (i.e. per App), not module-global:
two App instances in one process (tests, embedded apps) must not see each
other's profiling session through a shared dict. jax.profiler itself is
process-wide, so concurrent *starts* from two apps still race at the JAX
layer — but one app stopping can no longer clobber another's bookkeeping.
"""

from __future__ import annotations

import threading


def enable_profiler(app, prefix: str = "/debug/profiler") -> None:
    state = {"dir": None}
    lock = threading.Lock()

    def start(ctx):
        import jax
        body = ctx.bind() or {}
        trace_dir = body.get("dir") or "/tmp/gofr_tpu_trace"
        with lock:
            if state["dir"] is not None:
                return {"status": "already profiling",
                        "dir": state["dir"]}
            jax.profiler.start_trace(trace_dir)
            state["dir"] = trace_dir
        ctx.logger.info("profiler started -> %s", trace_dir)
        return {"status": "started", "dir": trace_dir}

    def stop(ctx):
        import jax
        with lock:
            if state["dir"] is None:
                return {"status": "not profiling"}
            jax.profiler.stop_trace()
            trace_dir, state["dir"] = state["dir"], None
        ctx.logger.info("profiler stopped, trace in %s", trace_dir)
        return {"status": "stopped", "dir": trace_dir}

    app.post(f"{prefix}/start", start)
    app.post(f"{prefix}/stop", stop)
