"""GT001 event-loop-block: blocking calls reachable from ``async def``.

One careless ``block_until_ready()`` / ``.item()`` / ``time.sleep()`` in
an async path stalls every in-flight request — the loop that runs
``DynamicBatcher`` and ``GenerationEngine`` is the only thread accepting
work. Every device wait in the serving stack is hand-offloaded via
``run_in_executor`` (``gofr_tpu/tpu/generate.py`` dispatch/fetch); this
rule makes that discipline machine-checked.

Detection (v2, whole-program): take every function reachable from an
``async def`` along the *project* call graph — through ``from x import
y`` helpers, typed ``self.engine.step()`` receivers, and duck-typed
collaborators, across any number of modules — without a thread hop, and
flag:

- ``time.sleep`` (use ``await asyncio.sleep``),
- ``jax.block_until_ready`` / any ``.block_until_ready()`` method,
- ``jax.device_get`` and ``np.asarray`` / ``np.array`` (device→host
  sync when handed a device value),
- ``.item()`` (scalar device sync),
- un-awaited ``.acquire()`` on a lock-named receiver (``await
  lock.acquire()`` on an asyncio lock is fine; a bare call is a
  thread-lock wait). The receiver's name must look like a lock
  (``lock``/``mutex``/``sem``/``cond`` in its last segment) — the
  staging pool's ``acquire()`` is a slab lease, not a wait,
- ``concurrent.futures`` waits (``cf.wait``, dotted ``.result`` on the
  futures module),
- builtin ``open()`` and ``socket.create_connection`` (sync I/O).

Functions *passed* to ``run_in_executor`` / ``asyncio.to_thread`` never
get a call edge, so offloaded work is naturally exempt — even when the
offloaded closure lives two modules away. Suppress a deliberate
host-side use with ``# graftcheck: ignore[GT001]`` plus a justification
comment.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

# fully-dotted callables that block the calling thread
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep() parks the whole event loop — use "
                  "'await asyncio.sleep(...)' or offload",
    "jax.block_until_ready": "jax.block_until_ready() is a device sync",
    "jax.device_get": "jax.device_get() is a device->host sync",
    "numpy.asarray": "np.asarray() on a device value is a device->host "
                     "sync",
    "numpy.array": "np.array() on a device value is a device->host sync",
    "socket.create_connection": "sync socket connect",
    "concurrent.futures.wait": "concurrent.futures.wait() blocks",
}

# method names that block regardless of receiver type
BLOCKING_METHODS = {
    "block_until_ready": "a device sync",
    "item": ".item() synchronously copies a device scalar to host",
}


class EventLoopBlockRule(Rule):
    rule_id = "GT001"
    title = "event-loop-block"
    severity = "error"

    def check_project(self, project) -> Iterable[Finding]:
        chains = project.reachable(project.async_roots())
        findings: List[Finding] = []
        for ref, chain in chains.items():
            module = project.module_of(ref)
            qualname = ref[1]
            for node in project.body_nodes(ref):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._blocking(module, node)
                if hit is None:
                    continue
                label, why = hit
                root = project.display(chain[0], module.relpath)
                via = (" via " + " -> ".join(
                    project.display(r, module.relpath)
                    for r in chain[1:])
                    if len(chain) > 1 else "")
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=node.lineno,
                    message=(
                        f"event-loop-block: {label} inside '{qualname}' "
                        f"runs on the event loop (async root "
                        f"'{root}'{via}) — {why}; offload with "
                        f"run_in_executor/asyncio.to_thread"),
                    severity=self.severity,
                    key=f"{label} in {qualname}",
                ))
        return findings

    def _blocking(self, module: ModuleInfo,
                  call: ast.Call) -> Optional[Tuple[str, str]]:
        func = call.func
        dotted = module.dotted(func)
        if dotted is not None and dotted in BLOCKING_DOTTED:
            return f"{dotted}(...)", BLOCKING_DOTTED[dotted]
        if isinstance(func, ast.Name) and func.id == "open" and \
                "open" not in module.import_aliases:
            return "open(...)", "sync file I/O"
        if isinstance(func, ast.Attribute):
            if func.attr in BLOCKING_METHODS:
                return f".{func.attr}()", BLOCKING_METHODS[func.attr]
            if func.attr == "acquire" and \
                    not isinstance(module.parents.get(call), ast.Await) \
                    and self._lockish_receiver(func.value):
                return (".acquire()",
                        "un-awaited lock acquire blocks the thread "
                        "(asyncio locks are 'await lock.acquire()' / "
                        "'async with lock')")
        return None

    @staticmethod
    def _lockish_receiver(expr: ast.AST) -> bool:
        parts = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if isinstance(expr, ast.Name):
            parts.append(expr.id)
        if not parts:
            return True  # unknown receiver shape: keep the old behavior
        last = parts[0].lower()
        return any(tok in last for tok in ("lock", "mutex", "sem", "cond"))
