"""BERT-base encoder for the streaming-embeddings gRPC path.

North star config 3 (BASELINE.json): "grpc-server streaming BERT-base
embeddings (dynamic batching)". No reference analog (SURVEY.md §2.7).
Same TPU-first recipe as llama.py: stacked layers + ``lax.scan``, bf16
matmuls, fp32 norms/softmax, static shapes (fixed max_len with an
attention mask so every batch compiles to the same executable — the
dynamic batcher pads into these buckets).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.ops import attention, layer_norm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


PRESETS = {
    "tiny": BertConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                       ffn_dim=128, max_len=64),
    "base": BertConfig(),
}


def config(preset: str = "base", **overrides) -> BertConfig:
    return dataclasses.replace(PRESETS[preset], **overrides)


def init(cfg: BertConfig, key: jax.Array) -> Dict[str, Any]:
    keys = jax.random.split(key, 12)
    dt = cfg.dtype
    d, f, l_count = cfg.dim, cfg.ffn_dim, cfg.n_layers

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dt)

    return {
        "tok_emb": dense(keys[0], (cfg.vocab_size, d), d),
        "pos_emb": dense(keys[1], (cfg.max_len, d), d),
        "type_emb": dense(keys[2], (cfg.type_vocab, d), d),
        "emb_norm_w": jnp.ones((d,), dt),
        "emb_norm_b": jnp.zeros((d,), dt),
        "layers": {
            "wq": dense(keys[3], (l_count, d, d), d),
            "wk": dense(keys[4], (l_count, d, d), d),
            "wv": dense(keys[5], (l_count, d, d), d),
            "wo": dense(keys[6], (l_count, d, d), d),
            "bq": jnp.zeros((l_count, d), dt),
            "bk": jnp.zeros((l_count, d), dt),
            "bv": jnp.zeros((l_count, d), dt),
            "bo": jnp.zeros((l_count, d), dt),
            "attn_norm_w": jnp.ones((l_count, d), dt),
            "attn_norm_b": jnp.zeros((l_count, d), dt),
            "w_in": dense(keys[7], (l_count, d, f), d),
            "b_in": jnp.zeros((l_count, f), dt),
            "w_out": dense(keys[8], (l_count, f, d), f),
            "b_out": jnp.zeros((l_count, d), dt),
            "ffn_norm_w": jnp.ones((l_count, d), dt),
            "ffn_norm_b": jnp.zeros((l_count, d), dt),
        },
        "pool_w": dense(keys[9], (d, d), d),
        "pool_b": jnp.zeros((d,), dt),
    }


def apply(params: Dict[str, Any], cfg: BertConfig, token_ids: jnp.ndarray,
          attention_mask: jnp.ndarray | None = None,
          type_ids: jnp.ndarray | None = None) -> Dict[str, jnp.ndarray]:
    """token_ids (B, S) int32 → {"sequence": (B,S,D), "pooled": (B,D),
    "mean": (B,D)} — mean is the masked mean-pooled embedding (the usual
    sentence-embedding output)."""
    b, s = token_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((b, s), jnp.int32)
    if type_ids is None:
        type_ids = jnp.zeros((b, s), jnp.int32)

    x = (params["tok_emb"][token_ids]
         + params["pos_emb"][None, :s]
         + params["type_emb"][type_ids])
    x = layer_norm(x, params["emb_norm_w"], params["emb_norm_b"], cfg.norm_eps)

    # (B,1,1,1,T) mask matching ops.attention's grouped-score layout
    mask = attention_mask[:, None, None, None, :].astype(bool)

    def body(x, layer):
        q = (x @ layer["wq"] + layer["bq"]).reshape(b, s, cfg.n_heads,
                                                    cfg.head_dim)
        k = (x @ layer["wk"] + layer["bk"]).reshape(b, s, cfg.n_heads,
                                                    cfg.head_dim)
        v = (x @ layer["wv"] + layer["bv"]).reshape(b, s, cfg.n_heads,
                                                    cfg.head_dim)
        attn = attention(q, k, v, mask).reshape(b, s, -1)
        x = layer_norm(x + attn @ layer["wo"] + layer["bo"],
                       layer["attn_norm_w"], layer["attn_norm_b"],
                       cfg.norm_eps)
        h = jax.nn.gelu((x @ layer["w_in"] + layer["b_in"])
                        .astype(jnp.float32)).astype(x.dtype)
        x = layer_norm(x + h @ layer["w_out"] + layer["b_out"],
                       layer["ffn_norm_w"], layer["ffn_norm_b"], cfg.norm_eps)
        return x, None

    x, _ = lax.scan(body, x, params["layers"])

    pooled = jnp.tanh((x[:, 0] @ params["pool_w"] + params["pool_b"])
                      .astype(jnp.float32))
    weights = attention_mask.astype(jnp.float32)[..., None]
    mean = ((x.astype(jnp.float32) * weights).sum(axis=1)
            / jnp.maximum(weights.sum(axis=1), 1.0))
    return {"sequence": x, "pooled": pooled, "mean": mean}
