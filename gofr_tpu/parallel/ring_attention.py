"""Ring attention: exact causal attention over sequence-sharded Q/K/V.

Long-context sequence parallelism (no reference analog — SURVEY.md §5
"long-context: absent"; first-class here per the build goal). Each device
holds a contiguous sequence shard; K/V blocks rotate around the ``sp``
ring via ``lax.ppermute`` (ICI neighbour exchange) while a flash-style
online softmax accumulates (m, l, acc) in fp32 — so the full (S, S) score
matrix never materialises and per-device memory is O(S_local · S_local).

Design: one ``lax.fori_loop`` over ring steps inside ``shard_map``;
each step is one GQA block-attention (MXU) + one ppermute, which XLA
overlaps (compute on block i while block i+1 is in flight on ICI).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, causal):
    """One GQA block: q (B,S,Hq,D) vs k/v (B,T,Hkv,D); fp32 partial-softmax
    stats. Returns (scores_exp @ v, row_max, row_sum) with shapes
    ((B,S,Hq,D) f32, (B,K,G,S) f32, (B,K,G,S) f32)."""
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, s, hkv, g, d)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k).astype(jnp.float32)
    scores = scores * (d ** -0.5)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]            # (S, T)
        scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
    m = scores.max(axis=-1)                                # (B,K,G,S)
    p = jnp.exp(scores - m[..., None])
    l = p.sum(axis=-1)                                     # (B,K,G,S)
    pv = jnp.einsum("bkgst,btkd->bskgd", p, v.astype(jnp.float32))
    return pv.reshape(b, s, hq, d), m, l


def _ring_body(q, k, v, axis_name: str, causal: bool):
    """Runs on one shard inside shard_map."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    q_pos = my * s + jnp.arange(s)

    acc = jnp.zeros((b, s, hq, d), jnp.float32)
    m = jnp.full((b, hkv, g, s), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, g, s), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        k_blk, v_blk, acc, m, l = carry
        blk_idx = (my - i) % n
        k_pos = blk_idx * s + jnp.arange(s)
        pv, m_blk, l_blk = _block_attend(q, k_blk, v_blk, q_pos, k_pos,
                                         causal)
        m_new = jnp.maximum(m, m_blk)
        corr_old = jnp.exp(m - m_new)
        corr_blk = jnp.exp(m_blk - m_new)
        l = l * corr_old + l_blk * corr_blk
        # broadcast (B,K,G,S) stats onto (B,S,Hq,D) accumulators
        def to_act(stat):
            return stat.transpose(0, 3, 1, 2).reshape(b, s, hq)[..., None]
        acc = acc * to_act(corr_old) + pv * to_act(corr_blk)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, acc, m_new, l

    _, _, acc, m, l = lax.fori_loop(0, n, step, (k, v, acc, m, l))
    l_act = l.transpose(0, 3, 1, 2).reshape(b, s, hq)[..., None]
    return (acc / jnp.maximum(l_act, 1e-30)).astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True,
                   batch_axis: Optional[str] = None,
                   head_axis: Optional[str] = None) -> jnp.ndarray:
    """Exact (causal) attention with Q/K/V sharded on the sequence axis.

    q (B, S, Hq, D), k/v (B, S, Hkv, D) — S sharded ``axis_name``-ways,
    optionally B on ``batch_axis`` (dp) and heads on ``head_axis`` (tp),
    so sp composes with dp×tp without gathering heads. Returns q's sharding.
    """
    spec = P(batch_axis, axis_name, head_axis, None)
    body = functools.partial(_ring_body, axis_name=axis_name, causal=causal)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
