"""Grammar-constrained decoding: host-side token-mask engine.

Compiles a grammar — a limited regex dialect or a JSON schema lowered to
that dialect — into a byte-level DFA, then lifts the DFA to *token*
granularity against the serving tokenizer's vocabulary: for each DFA
state we materialise a cached boolean "token allowed" mask and a float32
additive logit-bias row (0 for allowed tokens, ``NEG_BIAS`` for
disallowed ones). The engine copies the row for each constrained slot
into a per-tick host slab that ships to the device through the existing
``TransferCoalescer`` frame and is added to the decode logits before
argmax/sampling — so greedy output under a fixed grammar is
bit-reproducible (same mask → same biased logits → same argmax) across
dense/paged KV and coalesced/uncoalesced uploads.

Byte-level on purpose: the repo tokenizer (``gofr_tpu/tokenizer.py``) is
byte-level BPE (ids 0..255 are raw bytes; merged ids concatenate their
children), so walking a token means walking its byte expansion through
the DFA. Multi-byte UTF-8 literals in a pattern compile to byte
sequences; ``.`` matches any byte except ``\\n``.

Everything here is cold-path host code (grammar compile happens at
admission, mask rows are cached per (grammar, state)); the only hot-path
work is a row copy into a preallocated slab in the engine.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

# Additive bias for disallowed tokens. Finite (not -inf) so temperature
# scaling and top-k renormalisation in the sampled path never produce
# NaNs, yet far below any real logit so argmax/softmax mass is zero.
NEG_BIAS = np.float32(-1e9)

_ALL_BYTES = (1 << 256) - 1
_MAX_DFA_STATES = 4096
_MAX_PATTERN_LEN = 4096


class GrammarError(ValueError):
    """Raised for malformed patterns/schemas or resource-limit blowups."""


# -- token byte table ---------------------------------------------------------

def token_byte_table(tokenizer=None, vocab_size: Optional[int] = None
                     ) -> List[bytes]:
    """Byte expansion of every vocab id, in id order.

    Works off the tokenizer's ``merges`` list (byte-level BPE: id ``i`` <
    256 is ``bytes([i])``; merge ``j`` yields id ``256+j`` concatenating
    its pair). Ids past the derivable range (padded vocabs) map to
    ``b""`` and are never allowed by any grammar.
    """
    merges = list(getattr(tokenizer, "merges", None) or [])
    size = vocab_size if vocab_size is not None else 256 + len(merges)
    table: List[bytes] = [bytes([i]) for i in range(min(256, size))]
    for j, (left, right) in enumerate(merges):
        if 256 + j >= size:
            break
        table.append(table[left] + table[right])
    while len(table) < size:
        table.append(b"")
    return table


# -- regex → NFA (Thompson construction over bytes) ---------------------------

class _NFA:
    """States have epsilon edges plus byte-class edges (mask → dst).
    Masks are 256-bit ints; bit b set means byte b is accepted."""

    def __init__(self):
        self.eps: List[List[int]] = []
        self.edges: List[List[Tuple[int, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def add_eps(self, src: int, dst: int) -> None:
        self.eps[src].append(dst)

    def add_edge(self, src: int, mask: int, dst: int) -> None:
        self.edges[src].append((mask, dst))


_CLASS_D = 0
for _b in range(ord("0"), ord("9") + 1):
    _CLASS_D |= 1 << _b
_CLASS_W = _CLASS_D | (1 << ord("_"))
for _b in range(ord("a"), ord("z") + 1):
    _CLASS_W |= 1 << _b
for _b in range(ord("A"), ord("Z") + 1):
    _CLASS_W |= 1 << _b
_CLASS_S = 0
for _b in b" \t\r\n\f\v":
    _CLASS_S |= 1 << _b
_CLASS_DOT = _ALL_BYTES & ~(1 << ord("\n"))

_ESCAPE_CLASSES = {
    "d": _CLASS_D, "D": _ALL_BYTES & ~_CLASS_D,
    "w": _CLASS_W, "W": _ALL_BYTES & ~_CLASS_W,
    "s": _CLASS_S, "S": _ALL_BYTES & ~_CLASS_S,
}
_ESCAPE_CHARS = {"n": ord("\n"), "t": ord("\t"), "r": ord("\r"),
                 "f": ord("\f"), "v": ord("\v"), "0": 0}


class _Parser:
    """Recursive-descent parser for the supported dialect:
    literals (incl. multi-byte UTF-8), ``.``, escapes (``\\d \\w \\s``
    + negations, ``\\xHH``, control chars, escaped metachars),
    ``[...]`` classes with ranges and negation, grouping ``(...)``,
    alternation ``|``, and repetition ``* + ? {m} {m,} {m,n}``.
    Anchors/backrefs/lookaround are rejected — token masking needs a
    pure DFA."""

    def __init__(self, pattern: str):
        if len(pattern) > _MAX_PATTERN_LEN:
            raise GrammarError(
                f"pattern too long ({len(pattern)} > {_MAX_PATTERN_LEN})")
        self.src = pattern
        self.pos = 0
        self.nfa = _NFA()

    def parse(self) -> Tuple[int, int]:
        start, accept = self._alternation()
        if self.pos != len(self.src):
            raise GrammarError(
                f"unexpected {self.src[self.pos]!r} at {self.pos}")
        return start, accept

    def _peek(self) -> str:
        return self.src[self.pos] if self.pos < len(self.src) else ""

    def _take(self) -> str:
        ch = self.src[self.pos]
        self.pos += 1
        return ch

    # fragment = (start, accept)
    def _alternation(self) -> Tuple[int, int]:
        frags = [self._concat()]
        while self._peek() == "|":
            self._take()
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        start, accept = self.nfa.state(), self.nfa.state()
        for fragment_start, fragment_accept in frags:
            self.nfa.add_eps(start, fragment_start)
            self.nfa.add_eps(fragment_accept, accept)
        return start, accept

    def _concat(self) -> Tuple[int, int]:
        start = self.nfa.state()
        accept = start
        while self._peek() not in ("", "|", ")"):
            fragment_start, fragment_accept = self._repeat()
            self.nfa.add_eps(accept, fragment_start)
            accept = fragment_accept
        return start, accept

    def _repeat(self) -> Tuple[int, int]:
        frag = self._atom()
        while self._peek() in ("*", "+", "?", "{"):
            op = self._peek()
            if op == "{":
                frag = self._bounded(frag)
                continue
            self._take()
            start, accept = self.nfa.state(), self.nfa.state()
            fragment_start, fragment_accept = frag
            self.nfa.add_eps(start, fragment_start)
            self.nfa.add_eps(fragment_accept, accept)
            if op in ("*", "?"):
                self.nfa.add_eps(start, accept)
            if op in ("*", "+"):
                self.nfa.add_eps(fragment_accept, fragment_start)
            frag = (start, accept)
        return frag

    def _bounded(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        # {m}, {m,}, {m,n} — expand by re-parsing the atom's source slice
        # is fragile, so instead duplicate the fragment structurally.
        brace = self.pos
        self._take()  # '{'
        spec = ""
        while self._peek() not in ("", "}"):
            spec += self._take()
        if self._peek() != "}":
            raise GrammarError(f"unterminated {{...}} at {brace}")
        self._take()
        if "," in spec:
            lo_s, hi_s = spec.split(",", 1)
            lo = int(lo_s) if lo_s.strip() else 0
            hi = int(hi_s) if hi_s.strip() else lo + 64
        else:
            lo = hi = int(spec)
        if not (0 <= lo <= hi <= 256):
            raise GrammarError(f"bad repetition bounds {{{spec}}}")
        start = self.nfa.state()
        accept = start
        tails: List[int] = []
        for i in range(hi):
            copy_start, copy_accept = self._copy_fragment(frag)
            self.nfa.add_eps(accept, copy_start)
            if i >= lo:
                tails.append(accept)
            accept = copy_accept
        end = self.nfa.state()
        self.nfa.add_eps(accept, end)
        for tail in tails:
            self.nfa.add_eps(tail, end)
        if lo == 0 and hi == 0:
            self.nfa.add_eps(start, end)
        return start, end

    def _copy_fragment(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        """Deep-copy the subgraph reachable from frag's start."""
        start, accept = frag
        mapping: Dict[int, int] = {}
        stack = [start, accept]
        while stack:
            node = stack.pop()
            if node in mapping:
                continue
            mapping[node] = self.nfa.state()
            for dst in list(self.nfa.eps[node]):
                stack.append(dst)
            for _, dst in list(self.nfa.edges[node]):
                stack.append(dst)
        for node, clone in mapping.items():
            for dst in self.nfa.eps[node]:
                self.nfa.add_eps(clone, mapping[dst])
            for mask, dst in self.nfa.edges[node]:
                self.nfa.add_edge(clone, mask, mapping[dst])
        return mapping[start], mapping[accept]

    def _atom(self) -> Tuple[int, int]:
        ch = self._peek()
        if ch == "":
            raise GrammarError("unexpected end of pattern")
        if ch == "(":
            self._take()
            if self._peek() == "?":  # (?:...) non-capturing; others rejected
                self._take()
                if self._peek() != ":":
                    raise GrammarError("lookaround/backrefs unsupported")
                self._take()
            frag = self._alternation()
            if self._peek() != ")":
                raise GrammarError("unbalanced parenthesis")
            self._take()
            return frag
        if ch == "[":
            return self._byte_fragment(self._char_class())
        if ch == ".":
            self._take()
            return self._byte_fragment(_CLASS_DOT)
        if ch == "\\":
            return self._escape_fragment()
        if ch in ")|*+?{}]":
            raise GrammarError(f"unexpected {ch!r} at {self.pos}")
        self._take()
        return self._literal_fragment(ch)

    def _literal_fragment(self, ch: str) -> Tuple[int, int]:
        encoded = ch.encode("utf-8")
        start = self.nfa.state()
        node = start
        for byte in encoded:
            nxt = self.nfa.state()
            self.nfa.add_edge(node, 1 << byte, nxt)
            node = nxt
        return start, node

    def _byte_fragment(self, mask: int) -> Tuple[int, int]:
        start, accept = self.nfa.state(), self.nfa.state()
        self.nfa.add_edge(start, mask, accept)
        return start, accept

    def _escape_fragment(self) -> Tuple[int, int]:
        self._take()  # backslash
        if self._peek() == "":
            raise GrammarError("trailing backslash")
        ch = self._take()
        if ch in _ESCAPE_CLASSES:
            return self._byte_fragment(_ESCAPE_CLASSES[ch])
        return self._byte_fragment(1 << self._escape_byte(ch))

    def _escape_byte(self, ch: str) -> int:
        if ch in _ESCAPE_CHARS:
            return _ESCAPE_CHARS[ch]
        if ch == "x":
            hexpair = self.src[self.pos:self.pos + 2]
            if len(hexpair) != 2:
                raise GrammarError("truncated \\xHH escape")
            self.pos += 2
            return int(hexpair, 16)
        if ch in ".^$*+?()[]{}|\\/\"'-":
            return ord(ch)
        raise GrammarError(f"unsupported escape \\{ch}")

    def _char_class(self) -> int:
        self._take()  # '['
        negate = False
        if self._peek() == "^":
            negate = True
            self._take()
        mask = 0
        first = True
        while True:
            ch = self._peek()
            if ch == "":
                raise GrammarError("unterminated character class")
            if ch == "]" and not first:
                self._take()
                break
            first = False
            low = self._class_member()
            if low < 0:  # multi-byte escape class like \d inside [...]
                mask |= -low - 1
                continue
            if self._peek() == "-" and self.src[self.pos + 1:self.pos + 2] \
                    not in ("]", ""):
                self._take()
                high = self._class_member()
                if high < 0 or high < low:
                    raise GrammarError("bad character-class range")
                for byte in range(low, high + 1):
                    mask |= 1 << byte
            else:
                mask |= 1 << low
        if negate:
            mask = _ALL_BYTES & ~mask
        return mask

    def _class_member(self) -> int:
        """One class member → byte value, or -(mask+1) for escape classes."""
        ch = self._take()
        if ch == "\\":
            if self._peek() == "":
                raise GrammarError("trailing backslash in class")
            esc = self._take()
            if esc in _ESCAPE_CLASSES:
                return -(_ESCAPE_CLASSES[esc] + 1)
            return self._escape_byte(esc)
        code = ch.encode("utf-8")
        if len(code) != 1:
            raise GrammarError(
                "non-ASCII characters unsupported inside [...] classes")
        return code[0]


# -- lazy subset-construction DFA ---------------------------------------------

class _DFA:
    """NFA → DFA by lazy subset construction: transitions are computed
    per (state, byte) on first use and memoised, so negated classes and
    ``.`` never force a full 256-way table walk upfront. Dead state is
    ``-1``."""

    def __init__(self, nfa: _NFA, start: int, accept: int):
        self._nfa = nfa
        self._accept_nfa = accept
        self._ids: Dict[frozenset, int] = {}
        self._sets: List[frozenset] = []
        self._accepting: List[bool] = []
        self._trans: Dict[Tuple[int, int], int] = {}
        self.start = self._intern(self._closure({start}))

    def _closure(self, states) -> frozenset:
        seen = set(states)
        stack = list(states)
        eps = self._nfa.eps
        while stack:
            node = stack.pop()
            for dst in eps[node]:
                if dst not in seen:
                    seen.add(dst)
                    stack.append(dst)
        return frozenset(seen)

    def _intern(self, closed: frozenset) -> int:
        sid = self._ids.get(closed)
        if sid is None:
            if len(self._sets) >= _MAX_DFA_STATES:
                raise GrammarError(
                    f"grammar DFA exceeds {_MAX_DFA_STATES} states")
            sid = len(self._sets)
            self._ids[closed] = sid
            self._sets.append(closed)
            self._accepting.append(self._accept_nfa in closed)
        return sid

    def step(self, state: int, byte: int) -> int:
        if state < 0:
            return -1
        key = (state, byte)
        dest = self._trans.get(key)
        if dest is not None:
            return dest
        bit = 1 << byte
        moved = set()
        edges = self._nfa.edges
        for node in self._sets[state]:
            for mask, dst in edges[node]:
                if mask & bit:
                    moved.add(dst)
        dest = self._intern(self._closure(moved)) if moved else -1
        self._trans[key] = dest
        return dest

    def accepting(self, state: int) -> bool:
        return state >= 0 and self._accepting[state]

    @property
    def n_states(self) -> int:
        return len(self._sets)


# -- JSON schema → regex ------------------------------------------------------

_REGEX_META = set(".^$*+?()[]{}|\\")


def _regex_escape(text: str) -> str:
    return "".join("\\" + ch if ch in _REGEX_META else ch for ch in text)


_JSON_STRING = ('"([^"\\\\\\x00-\\x1f]|\\\\["\\\\/bfnrt]'
                '|\\\\u[0-9a-fA-F]{4})*"')
_JSON_INTEGER = "-?(0|[1-9][0-9]*)"
_JSON_NUMBER = _JSON_INTEGER + "(\\.[0-9]+)?([eE][+-]?[0-9]+)?"

_MAX_SCHEMA_DEPTH = 16


def schema_to_regex(schema, depth: int = 0) -> str:
    """Lower a (restricted) JSON schema to the regex dialect above,
    matching *canonical* JSON: no whitespace, object keys in declared
    order, all declared properties present. That canonical form is what
    the bias mask steers the model to emit."""
    if depth > _MAX_SCHEMA_DEPTH:
        raise GrammarError("schema nesting too deep")
    if not isinstance(schema, dict):
        raise GrammarError("schema must be an object")
    if "const" in schema:
        return _regex_escape(json.dumps(schema["const"],
                                        separators=(",", ":")))
    if "enum" in schema:
        choices = [_regex_escape(json.dumps(value, separators=(",", ":")))
                   for value in schema["enum"]]
        if not choices:
            raise GrammarError("empty enum")
        return "(" + "|".join(choices) + ")"
    if "anyOf" in schema or "oneOf" in schema:
        subs = schema.get("anyOf") or schema.get("oneOf")
        return "(" + "|".join(schema_to_regex(sub, depth + 1)
                              for sub in subs) + ")"
    kind = schema.get("type")
    if kind == "string":
        if "pattern" in schema:
            return '"' + schema["pattern"] + '"'
        return _JSON_STRING
    if kind == "integer":
        return _JSON_INTEGER
    if kind == "number":
        return _JSON_NUMBER
    if kind == "boolean":
        return "(true|false)"
    if kind == "null":
        return "null"
    if kind == "object":
        properties = schema.get("properties", {})
        if not properties:
            raise GrammarError("object schema needs explicit properties")
        parts = []
        for key, sub in properties.items():
            parts.append('"' + _regex_escape(key) + '":'
                         + schema_to_regex(sub, depth + 1))
        return "\\{" + ",".join(parts) + "\\}"
    if kind == "array":
        item = schema_to_regex(schema.get("items", {"type": "integer"}),
                               depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", 8))
        if not (0 <= lo <= hi <= 64):
            raise GrammarError("array bounds out of range (0..64)")
        if hi == 0:
            return "\\[\\]"
        body = f"({item})(,({item})){{{max(lo - 1, 0)},{hi - 1}}}"
        if lo == 0:
            return "\\[(" + body + ")?\\]"
        return "\\[" + body + "\\]"
    raise GrammarError(f"unsupported schema type {kind!r}")


# -- compiled grammar: token-level masks over the DFA -------------------------

class CompiledGrammar:
    """A byte-DFA lifted to token granularity, with per-state cached
    boolean allowed-masks and float32 bias rows. Shared across requests
    via :class:`GrammarCache`; per-request position lives in
    :class:`GrammarWalker`."""

    def __init__(self, pattern: str, token_table: List[bytes],
                 eos_id: Optional[int], source_key: str = ""):
        parser = _Parser(pattern)
        start, accept = parser.parse()
        self.pattern = pattern
        self.source_key = source_key or pattern
        self.dfa = _DFA(parser.nfa, start, accept)
        self.token_table = token_table
        self.vocab = len(token_table)
        self.eos_id = eos_id
        self._rows: Dict[int, np.ndarray] = {}
        self._allowed: Dict[int, np.ndarray] = {}
        self._open_count: Dict[int, int] = {}  # allowed non-eos tokens
        self._token_dest: Dict[Tuple[int, int], int] = {}
        self.mask_builds = 0
        self.mask_hits = 0

    @property
    def start(self) -> int:
        return self.dfa.start

    def token_dest(self, state: int, token_id: int) -> int:
        key = (state, token_id)
        dest = self._token_dest.get(key)
        if dest is not None:
            return dest
        if token_id == self.eos_id:
            dest = state if self.dfa.accepting(state) else -1
        else:
            expansion = (self.token_table[token_id]
                         if 0 <= token_id < self.vocab else b"")
            if not expansion:
                dest = -1
            else:
                dest = state
                for byte in expansion:
                    dest = self.dfa.step(dest, byte)
                    if dest < 0:
                        break
        self._token_dest[key] = dest
        return dest

    def _build_state(self, state: int) -> None:
        allowed = np.zeros((self.vocab,), dtype=bool)
        open_count = 0
        for token_id in range(self.vocab):
            if token_id == self.eos_id:
                allowed[token_id] = self.dfa.accepting(state)
            elif self.token_dest(state, token_id) >= 0:
                allowed[token_id] = True
                open_count += 1
        row = np.zeros((self.vocab,), dtype=np.float32)
        row[~allowed] = NEG_BIAS
        self._allowed[state] = allowed
        self._rows[state] = row
        self._open_count[state] = open_count
        self.mask_builds += 1

    def bias_row(self, state: int) -> np.ndarray:
        """Cached float32 (vocab,) additive-bias row for ``state``.
        Callers must treat it as read-only (copy into slabs)."""
        row = self._rows.get(state)
        if row is None:
            self._build_state(state)
            row = self._rows[state]
        else:
            self.mask_hits += 1
        return row

    def allowed_mask(self, state: int) -> np.ndarray:
        if state not in self._allowed:
            self._build_state(state)
        return self._allowed[state]

    def open_count(self, state: int) -> int:
        if state not in self._open_count:
            self._build_state(state)
        return self._open_count[state]

    def accepting(self, state: int) -> bool:
        return self.dfa.accepting(state)

    def fullmatch(self, token_ids) -> bool:
        """Would this token sequence be a complete grammar match?
        (Test/validation helper — not used on the serving path.)"""
        state = self.start
        for token_id in token_ids:
            if token_id == self.eos_id:
                return self.dfa.accepting(state)
            state = self.token_dest(state, token_id)
            if state < 0:
                return False
        return self.dfa.accepting(state)

    def stats(self) -> dict:
        return {"dfa_states": self.dfa.n_states,
                "cached_state_masks": len(self._rows),
                "mask_builds": self.mask_builds,
                "mask_hits": self.mask_hits}


class GrammarWalker:
    """Per-request cursor over a shared :class:`CompiledGrammar`."""

    __slots__ = ("grammar", "state", "violated")

    def __init__(self, grammar: CompiledGrammar):
        self.grammar = grammar
        self.state = grammar.start
        self.violated = False

    def bias_row(self) -> np.ndarray:
        return self.grammar.bias_row(self.state)

    def advance(self, token_id: int) -> bool:
        """Consume one emitted token. Returns False (and flags
        ``violated``) if the token falls outside the grammar — the
        engine finishes the slot rather than emitting garbage."""
        dest = self.grammar.token_dest(self.state, token_id)
        if dest < 0:
            self.violated = True
            return False
        self.state = dest
        return True

    @property
    def accepting(self) -> bool:
        return self.grammar.accepting(self.state)

    @property
    def must_stop(self) -> bool:
        """No non-eos continuation exists — the match is complete (or
        the walk is dead); the engine should finish the slot."""
        return self.violated or self.grammar.open_count(self.state) == 0


# -- grammar cache ------------------------------------------------------------

def canonical_source(response_format: dict) -> Tuple[str, str]:
    """Normalise a request ``response_format`` → (kind, canonical source).
    Supported: {"type": "regex", "pattern": ...} and
    {"type": "json_schema", "schema": {...}} (also accepts the nested
    OpenAI-style {"json_schema": {"schema": ...}} shape)."""
    if not isinstance(response_format, dict):
        raise GrammarError("response_format must be an object")
    kind = response_format.get("type")
    if kind == "regex":
        pattern = response_format.get("pattern")
        if not isinstance(pattern, str) or not pattern:
            raise GrammarError("regex response_format needs 'pattern'")
        return "regex", pattern
    if kind == "json_schema":
        schema = response_format.get("schema")
        if schema is None:
            nested = response_format.get("json_schema")
            if isinstance(nested, dict):
                # OpenAI nests {"json_schema": {"name", "schema": {...}}};
                # a bare {"json_schema": {<schema>}} is the schema itself
                inner = nested.get("schema")
                schema = inner if isinstance(inner, dict) else nested
        if not isinstance(schema, dict):
            raise GrammarError("json_schema response_format needs 'schema'")
        return "json_schema", json.dumps(schema, sort_keys=True,
                                         separators=(",", ":"))
    raise GrammarError(f"unsupported response_format type {kind!r}")


class GrammarCache:
    """LRU of :class:`CompiledGrammar`, keyed by (kind, canonical source,
    eos_id). One cache per engine (it is bound to the engine's token
    table), so repeat jobs against the same grammar pay compilation and
    per-state mask construction exactly once."""

    def __init__(self, token_table: List[bytes], max_entries: int = 32):
        self.token_table = token_table
        self.max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[Tuple[str, str, Optional[int]], CompiledGrammar]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, response_format: dict,
            eos_id: Optional[int]) -> CompiledGrammar:
        kind, source = canonical_source(response_format)
        key = (kind, source, eos_id)
        grammar = self._entries.get(key)
        if grammar is not None:
            self._entries.move_to_end(key)
            self.hits += 1
            return grammar
        self.misses += 1
        pattern = source if kind == "regex" else schema_to_regex(
            json.loads(source))
        grammar = CompiledGrammar(pattern, self.token_table, eos_id,
                                  source_key=f"{kind}:{source}")
        self._entries[key] = grammar
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
        return grammar

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        return {"entries": len(self._entries), "hits": self.hits,
                "misses": self.misses}
