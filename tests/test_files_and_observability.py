"""Depth tests: file datasource + row readers, SQL query builder
dialects, zipkin trace exporter wire format, remote log-level poller,
websocket server-initiated push, and cron job scheduling/isolation —
reference pkg/gofr/datasource/file / trace / logging test coverage."""

import asyncio
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from gofr_tpu.container import new_mock_container
from tests.util import http_request, make_app, run, serving


# -- file datasource ----------------------------------------------------------

def test_filesystem_crud_and_stat(tmp_path):
    from gofr_tpu.datasource.file import LocalFileSystem
    fs = LocalFileSystem(root=str(tmp_path))
    fs.create("a.txt", b"hello")
    assert fs.read("a.txt") == b"hello"
    fs.append("a.txt", b" world")
    assert fs.read("a.txt") == b"hello world"
    fs.mkdir("sub")
    fs.write("sub/b.txt", b"nested")
    assert sorted(fs.list(".")) == ["a.txt", "sub"]
    info = fs.stat("a.txt")
    assert info["size"] == 11 and not info["is_dir"]
    assert fs.stat("sub")["is_dir"]
    fs.rename("a.txt", "c.txt")
    assert fs.read("c.txt") == b"hello world"
    fs.remove("c.txt")
    fs.remove_all("sub")
    assert fs.list(".") == []
    assert fs.health_check()["status"] == "UP"


def test_filesystem_sandbox_blocks_traversal(tmp_path):
    from gofr_tpu.datasource.file import LocalFileSystem
    (tmp_path / "inner").mkdir()
    fs = LocalFileSystem(root=str(tmp_path / "inner"))
    with pytest.raises(PermissionError):
        fs.read("../" * 10 + "etc/passwd")
    with pytest.raises(PermissionError):
        fs.read("/etc/passwd")
    with pytest.raises(PermissionError):
        fs.chdir("..")                       # can't escape via chdir
    fs.write("ok.txt", b"x")                 # normal ops unaffected
    assert fs.read("ok.txt") == b"x"
    # chdir stays confined to the ORIGINAL root, not the moved cwd
    (tmp_path / "ok2.txt").write_bytes(b"y")
    fs2 = LocalFileSystem(root=str(tmp_path))
    fs2.chdir("inner")
    assert fs2.read("ok.txt") == b"x"
    assert fs2.read("../ok2.txt") == b"y"    # up to original root: fine
    with pytest.raises(PermissionError):
        fs2.read("../../outside")
    # opt-out for trusted tooling (reference semantics: mirrors os)
    unsandboxed = LocalFileSystem(root=str(tmp_path), sandbox=False)
    assert unsandboxed.read("/etc/hostname") is not None


def test_row_readers(tmp_path):
    from gofr_tpu.datasource.file import LocalFileSystem
    fs = LocalFileSystem(root=str(tmp_path))

    fs.write("rows.json", json.dumps(
        [{"id": 1, "name": "ada"}, {"id": 2, "name": "gus"}]).encode())
    rows = list(fs.read_all("rows.json"))
    assert rows == [{"id": 1, "name": "ada"}, {"id": 2, "name": "gus"}]

    fs.write("one.json", json.dumps({"id": 3}).encode())
    assert list(fs.read_all("one.json")) == [{"id": 3}]

    fs.write("rows.csv", b"id,name\n1,ada\n2,gus\n")
    rows = list(fs.read_all("rows.csv"))
    assert rows == [{"id": "1", "name": "ada"},
                    {"id": "2", "name": "gus"}]

    fs.write("notes.txt", b"line one\nline two")
    assert list(fs.read_all("notes.txt")) == ["line one", "line two"]


# -- SQL query builder --------------------------------------------------------

def test_query_builder_dialect_placeholders():
    from gofr_tpu.datasource.sql.query_builder import (
        delete_by_query, insert_query, select_all_query, select_by_query,
        update_by_query)
    sqlite_insert = insert_query("sqlite", "user", ["id", "name"])
    assert "?" in sqlite_insert and "%s" not in sqlite_insert
    pg_insert = insert_query("postgres", "user", ["id", "name"])
    assert "%s" in pg_insert and "?" not in pg_insert
    assert select_all_query("sqlite", "user") == "SELECT * FROM user"
    assert "WHERE id" in select_by_query("sqlite", "user", "id")
    update = update_by_query("mysql", "user", ["name", "age"], "id")
    assert "name" in update and "WHERE id" in update and "%s" in update
    assert "DELETE FROM user" in delete_by_query("sqlite", "user", "id")


# -- zipkin exporter ----------------------------------------------------------

class _SpanSink(BaseHTTPRequestHandler):
    received = []

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        _SpanSink.received.append(json.loads(self.rfile.read(length)))
        self.send_response(202)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, *args):
        pass


def test_zipkin_exporter_wire_format():
    from gofr_tpu.config import MapConfig
    from gofr_tpu.trace.tracer import new_tracer
    _SpanSink.received = []
    server = HTTPServer(("127.0.0.1", 0), _SpanSink)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        tracer = new_tracer(MapConfig({
            "APP_NAME": "svc-a",
            "TRACE_EXPORTER": "zipkin",
            "TRACER_URL":
                f"http://127.0.0.1:{server.server_port}/api/v2/spans"}))
        with tracer.start_span("parent") as parent:
            parent.set_attribute("uri", "/x")
            with tracer.start_span("child"):
                pass
        tracer.shutdown()
        deadline = time.time() + 5.0
        while time.time() < deadline and not _SpanSink.received:
            time.sleep(0.02)
        assert _SpanSink.received, "no spans posted to the zipkin sink"
        spans = [s for batch in _SpanSink.received for s in batch]
        names = {s["name"] for s in spans}
        assert {"parent", "child"} <= names
        by_name = {s["name"]: s for s in spans}
        # zipkin v2 contract: shared traceId, child carries parentId
        assert by_name["child"]["traceId"] == by_name["parent"]["traceId"]
        assert by_name["child"]["parentId"] == by_name["parent"]["id"]
        assert by_name["parent"]["localEndpoint"]["serviceName"] == "svc-a"
        assert by_name["parent"]["tags"]["uri"] == "/x"
    finally:
        server.shutdown()


# -- remote log level poller --------------------------------------------------

def test_remote_log_level_poller():
    from gofr_tpu.logging import Level, new_silent_logger
    from gofr_tpu.logging.remote_level import start_remote_level_poller

    class _LevelServer(BaseHTTPRequestHandler):
        level = "DEBUG"

        def do_GET(self):
            # reference remotelogger response shape
            body = json.dumps(
                {"data": [{"serviceLevel":
                           {"logLevel": _LevelServer.level}}]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    server = HTTPServer(("127.0.0.1", 0), _LevelServer)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    logger = new_silent_logger()
    poller = None
    try:
        poller = start_remote_level_poller(
            logger, f"http://127.0.0.1:{server.server_port}/configs",
            interval=0.05)
        deadline = time.time() + 5.0
        while time.time() < deadline and logger.level != Level.DEBUG:
            time.sleep(0.02)
        assert logger.level == Level.DEBUG
        _LevelServer.level = "ERROR"
        deadline = time.time() + 5.0
        while time.time() < deadline and logger.level != Level.ERROR:
            time.sleep(0.02)
        assert logger.level == Level.ERROR
    finally:
        if poller is not None:
            poller.stop()       # don't leak a 20 Hz thread into the run
            poller.join(timeout=2.0)
        server.shutdown()


# -- websocket server push ----------------------------------------------------

def test_websocket_server_initiated_messages():
    """Server can push multiple messages before the client says anything
    (reference websocket.go WriteMessage surface)."""
    from gofr_tpu.websocket.frames import decode_frame

    async def main():
        app = make_app()

        async def feed(ctx):
            for i in range(3):
                await ctx.write_message(f"tick {i}")
            await ctx.read_message()     # wait for the client ack

        app.websocket("/feed", feed)
        async with serving(app) as port:
            import base64
            import os as _os
            key = base64.b64encode(_os.urandom(16)).decode()
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            writer.write((
                f"GET /feed HTTP/1.1\r\nHost: x\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n").encode())
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            assert b"101" in head.split(b"\r\n")[0]
            got = []
            buffer = b""
            while len(got) < 3:
                chunk = await asyncio.wait_for(reader.read(256), 10.0)
                assert chunk, "server closed before all pushes arrived"
                buffer += chunk
                while True:
                    frame = decode_frame(buffer)
                    if frame is None:
                        break
                    _opcode, _fin, payload, consumed = frame
                    got.append(payload.decode())
                    buffer = buffer[consumed:]
            assert got == ["tick 0", "tick 1", "tick 2"]
            writer.close()
    run(main())


# -- cron scheduling ----------------------------------------------------------

def test_cron_job_exception_isolated_and_next_runs():
    """A throwing job must not kill the crontab; later jobs still fire
    (drive _run_job directly — deterministic, no minute-long sleeps)."""
    from gofr_tpu.cron import Crontab
    container = new_mock_container()
    crontab = Crontab(container)
    calls = []

    def bad(ctx):
        calls.append("bad")
        raise RuntimeError("job exploded")

    def good(ctx):
        calls.append("good")

    crontab.add_job("* * * * *", "bad-job", bad)
    crontab.add_job("* * * * *", "good-job", good)
    when = time.localtime()
    assert all(job.due(when) for job in crontab.jobs)

    async def main():
        for job in crontab.jobs:
            await crontab._run_job(job)    # bad job must not raise out
        for job in crontab.jobs:
            await crontab._run_job(job)
    run(main())
    assert calls.count("bad") == 2 and calls.count("good") == 2


def test_filesystem_sandbox_resolves_symlinks(tmp_path):
    """A pre-existing symlink under root pointing outside it must not
    defeat the confinement check (ADVICE r3: realpath, not abspath)."""
    import os
    import pytest
    from gofr_tpu.datasource.file import LocalFileSystem
    outside = tmp_path / "outside"
    outside.mkdir()
    (outside / "secret.txt").write_bytes(b"top secret")
    root = tmp_path / "root"
    root.mkdir()
    os.symlink(str(outside), str(root / "link"))
    fs = LocalFileSystem(root=str(root))
    with pytest.raises(PermissionError):
        fs.read("link/secret.txt")
    with pytest.raises(PermissionError):
        fs.create("link/new.txt", b"x")
    # non-symlinked paths still work
    fs.create("ok.txt", b"fine")
    assert fs.read("ok.txt") == b"fine"
