"""Archive helpers for upload handling.

Capability parity with ``pkg/gofr/file`` (zip.go:12-18: Zip archive
expansion with a 100 MB decompression-bomb guard, used by the multipart
binder).
"""

from __future__ import annotations

import io
import os
import zipfile
from typing import Dict

MAX_UNZIP_BYTES = 100 * 1024 * 1024  # zip.go bomb guard


class ZipBombError(Exception):
    pass


def unzip_bytes(data: bytes,
                max_bytes: int = MAX_UNZIP_BYTES) -> Dict[str, bytes]:
    """Expand a zip archive held in memory → {name: content}. Refuses
    archives whose declared OR actual expansion exceeds ``max_bytes``, and
    rejects path-traversal member names."""
    out: Dict[str, bytes] = {}
    total = 0
    with zipfile.ZipFile(io.BytesIO(data)) as archive:
        declared = sum(info.file_size for info in archive.infolist())
        if declared > max_bytes:
            raise ZipBombError(
                f"archive declares {declared} bytes > limit {max_bytes}")
        for info in archive.infolist():
            if info.is_dir():
                continue
            name = info.filename
            if name.startswith("/") or ".." in name.split("/"):
                raise ZipBombError(f"unsafe member path {name!r}")
            content = archive.read(info)
            total += len(content)
            if total > max_bytes:  # actual beats declared (lying headers)
                raise ZipBombError(f"expansion exceeded limit {max_bytes}")
            out[name] = content
    return out


def unzip_to_dir(data: bytes, directory: str,
                 max_bytes: int = MAX_UNZIP_BYTES) -> int:
    """Expand to disk under ``directory``; returns file count."""
    files = unzip_bytes(data, max_bytes)
    for name, content in files.items():
        path = os.path.join(directory, name)
        os.makedirs(os.path.dirname(path) or directory, exist_ok=True)
        with open(path, "wb") as handle:
            handle.write(content)
    return len(files)
