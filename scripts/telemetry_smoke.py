#!/usr/bin/env python
"""Tier-1 telemetry smoke: a seeded goodput cliff must be *seen*.

A tiny engine (forced host devices) serves real traffic while a
``TimeSeriesStore`` samples a delivered-tokens counter on a synthetic
1 Hz clock and records sampled decode-tick anatomy. After a healthy
baseline, a seeded ``nan_logits`` fault plan poisons every request —
each one quarantines, delivered tokens flatline, and the smoke asserts
the full detection path the telemetry plane exists for:

1. the change-point detector raises a ``down`` anomaly on the delivered
   rate within one trigger window of the cliff,
2. the watchdog reason names the offending signal (this is the string
   that flips the replica DEGRADED in statusz),
3. sampled tick anatomy recorded real phase timings at the configured
   cadence, and
4. the store's memory stays inside its documented bucket bound and a
   cursor delta pull returns the sampled history.

Prints ``telemetry smoke: OK`` and exits 0, or raises with the failing
property. Budget: a few seconds on 8 host CPU devices.
"""

import asyncio
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    import jax

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.metrics.timeseries import (MAX_BUCKETS_PER_SIGNAL,
                                             TimeSeriesStore)
    from gofr_tpu.models import llama
    from gofr_tpu.tpu import faults
    from gofr_tpu.tpu.generate import GenerationEngine

    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    container = new_mock_container()
    engine = GenerationEngine(cfg, params, max_slots=2, max_len=32,
                              prompt_buckets=(8,), kv_page=4,
                              paged_kv=True, prefix_cache=False,
                              logger=container.logger,
                              metrics=container.metrics)

    # short detector window so the smoke stays in seconds: 12 eligible
    # baseline buckets (past the 5-bucket guard), 3-sample trigger
    store = TimeSeriesStore(metrics=container.metrics,
                            detector_min_baseline=12,
                            detector_trigger_after=3,
                            tick_sample=2)
    delivered = {"n": 0}
    store.register("delivered_tok_s", lambda: float(delivered["n"]),
                   kind="counter", watch="down")

    prompt, budget = [9, 8, 7], 4

    async def run() -> None:
        engine.attach_telemetry(store, every=store.tick_sample)
        await engine.start()
        try:
            t = 0.0
            store.sample(now=t)          # counter priming sample
            # healthy baseline: one request per synthetic second
            for _ in range(20):
                tokens = await asyncio.wait_for(engine.generate(
                    prompt, max_new_tokens=budget), 60.0)
                delivered["n"] += len(tokens)
                t += 1.0
                store.sample(now=t)
            assert store.watchdog_reasons() == [], \
                "healthy baseline raised an anomaly"

            # the cliff: every request hits seeded NaN logits and
            # quarantines — delivered tokens flatline at the same cadence
            plan = faults.FaultPlan("nan_logits", seed=11)
            faults.install(plan)
            raised = None
            for _ in range(6):
                try:
                    tokens = await asyncio.wait_for(engine.generate(
                        prompt, max_new_tokens=budget), 60.0)
                    delivered["n"] += len(tokens)
                except Exception:
                    pass                  # the poison path: zero delivered
                t += 1.0
                store.sample(now=t)
                raised = store.anomalies()["active"].get("delivered_tok_s")
                if raised is not None:
                    break
            assert plan.fired("nan_logits") >= 1, \
                "the armed fault never fired — the smoke proved nothing"
            assert raised is not None and raised["direction"] == "down", \
                f"goodput cliff went undetected: {store.anomalies()}"
            reasons = store.watchdog_reasons()
            assert any("delivered_tok_s down" in r for r in reasons), \
                f"watchdog reason does not name the signal: {reasons}"

            anatomy = store.tick_anatomy()
            assert anatomy["recorded"] >= 1, "no tick anatomy sampled"
            assert anatomy["phases"]["device_wait_s"]["mean_s"] > 0.0
            info = store.memory_info()
            assert info["buckets_held"] <= MAX_BUCKETS_PER_SIGNAL, info
            delta = store.delta(None)
            assert delta["samples"], "cursor delta returned no history"

            # the timez page serves the same history as aligned series
            from types import SimpleNamespace

            from gofr_tpu.timez import build_timez
            app = SimpleNamespace(container=SimpleNamespace(
                app_name="smoke", app_version="0", telemetry=store))
            page = build_timez(app, tier="1s")
            series = page["series"]
            assert series["t"], "timez served an empty time axis"
            assert len(series["series"]["delivered_tok_s"]) == \
                len(series["t"]), "timez series misaligned with axis"
        finally:
            faults.reset()
            await engine.stop()

    asyncio.run(run())
    print("telemetry smoke: OK")


if __name__ == "__main__":
    main()
