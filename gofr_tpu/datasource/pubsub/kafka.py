"""Kafka backend — pure-Python wire-protocol client, no driver dependency.

Capability parity with ``pkg/gofr/datasource/pubsub/kafka`` (kafka.go:42-105
client + dial + writer config; Publish 127-165 w/ counters; Subscribe
167-220 lazily creating a per-topic reader; commit-on-success via
``kafkaMessage.Commit``; Create/DeleteTopic 247-264; health.go). The
reference wraps segmentio/kafka-go; this zero-egress image has no Kafka
driver, so the client speaks the wire protocol directly:

  Metadata v1 · Produce v2 (message-set v1 + CRC32) · Fetch v2 ·
  ListOffsets v1 · OffsetFetch v1 · OffsetCommit v2 ·
  CreateTopics v0 · DeleteTopics v0 · FindCoordinator v0 ·
  JoinGroup v0 · SyncGroup v0 · Heartbeat v0 · LeaveGroup v0

Consumer model (kafka.go:167-220, 234-242 semantics): each subscribed
topic runs a poller thread that is one *member of the consumer group* —
it joins through the group coordinator (JoinGroup/SyncGroup), fetches
only its assigned partitions, heartbeats, and rebalances when membership
changes, so two instances of a service in one group split a topic's
partitions instead of double-processing them, and a member's partitions
are reclaimed by survivors when it dies. The elected leader computes
range assignment client-side (the standard "consumer" embedded protocol).
``KAFKA_GROUP_MODE=static`` falls back to the r3 behaviour (every
consumer fetches all partitions; offsets still on the broker) for
brokers without group coordination. Commit-on-success:
``Message.commit()`` advances the group offset, fenced by the member's
generation in group mode.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from gofr_tpu.datasource.pubsub.base import (
    Message,
    PubSub,
    decode_trace_envelope,
    encode_trace_envelope,
)

API_PRODUCE, API_FETCH, API_LIST_OFFSETS, API_METADATA = 0, 1, 2, 3
API_OFFSET_COMMIT, API_OFFSET_FETCH = 8, 9
API_FIND_COORDINATOR, API_JOIN_GROUP = 10, 11
API_HEARTBEAT, API_LEAVE_GROUP, API_SYNC_GROUP = 12, 13, 14
API_CREATE_TOPICS, API_DELETE_TOPICS = 19, 20

# group-coordination error codes (Kafka protocol)
ERR_COORDINATOR_LOADING = 14
ERR_COORDINATOR_NOT_AVAILABLE = 15
ERR_NOT_COORDINATOR = 16
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER = 25
ERR_REBALANCE_IN_PROGRESS = 27


class KafkaError(Exception):
    pass


class KafkaOffsetOutOfRange(KafkaError):
    """Fetch error 1: committed offset expired (retention) or invalid —
    the consumer must reset to the earliest available offset."""


class KafkaRebalance(KafkaError):
    """Group membership changed (heartbeat/commit returned 22/25/27):
    the member must rejoin and resync its assignment. ``reset_member``
    means the coordinator no longer knows us (error 25) and the next
    join must request a fresh member id."""

    def __init__(self, message: str, reset_member: bool = False):
        super().__init__(message)
        self.reset_member = reset_member


# -- primitive codecs --------------------------------------------------------

def _string(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode()
    return struct.pack(">h", len(raw)) + raw


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.offset = 0

    def int8(self):  return self._unpack(">b", 1)
    def int16(self): return self._unpack(">h", 2)
    def int32(self): return self._unpack(">i", 4)
    def int64(self): return self._unpack(">q", 8)

    def _unpack(self, fmt, size):
        value = struct.unpack_from(fmt, self.data, self.offset)[0]
        self.offset += size
        return value

    def string(self) -> Optional[str]:
        n = self.int16()
        if n == -1:
            return None
        raw = self.data[self.offset:self.offset + n]
        self.offset += n
        return raw.decode()

    def raw_bytes(self) -> Optional[bytes]:
        n = self.int32()
        if n == -1:
            return None
        raw = self.data[self.offset:self.offset + n]
        self.offset += n
        return raw


def encode_message_set(items: List[Tuple[bytes, bytes]]) -> bytes:
    """Message-set v1 (magic 1): [offset][size][crc][magic][attrs][ts][k][v]."""
    out = bytearray()
    timestamp = int(time.time() * 1000)
    for key, value in items:
        body = (struct.pack(">bbq", 1, 0, timestamp) + _bytes(key or None)
                + _bytes(value))
        crc = zlib.crc32(body) & 0xFFFFFFFF
        message = struct.pack(">I", crc) + body
        out += struct.pack(">q", 0) + struct.pack(">i", len(message)) + message
    return bytes(out)


def decode_message_set(data: bytes, queue_offset: int
                       ) -> List[Tuple[int, bytes, bytes]]:
    """→ [(offset, key, value)]; tolerates a truncated trailing message."""
    out: List[Tuple[int, bytes, bytes]] = []
    reader = _Reader(data)
    while reader.offset + 12 <= len(data):
        offset = reader.int64()
        size = reader.int32()
        if reader.offset + size > len(data):
            break
        end = reader.offset + size
        reader.int32()                       # crc (trusting TCP checksums)
        magic = reader.int8()
        attrs = reader.int8()
        if magic >= 1:
            reader.int64()                   # timestamp
        key = reader.raw_bytes() or b""
        value = reader.raw_bytes() or b""
        if attrs & 0x07:
            raise KafkaError("compressed message sets not supported")
        if offset >= queue_offset:
            out.append((offset, key, value))
        reader.offset = end
    return out


# -- consumer embedded protocol (range assignment) ---------------------------

def encode_consumer_metadata(topics: List[str]) -> bytes:
    """ConsumerProtocolSubscription v0: the member's topic list, carried
    inside JoinGroup so the elected leader can compute assignments."""
    out = struct.pack(">h", 0) + struct.pack(">i", len(topics))
    for topic in sorted(topics):
        out += _string(topic)
    return out + _bytes(b"")


def decode_consumer_metadata(data: bytes) -> List[str]:
    reader = _Reader(data)
    reader.int16()                              # version
    return [reader.string() for _ in range(reader.int32())]


def encode_member_assignment(assignment: Dict[str, List[int]]) -> bytes:
    """ConsumerProtocolAssignment v0: topic → partitions."""
    out = struct.pack(">h", 0) + struct.pack(">i", len(assignment))
    for topic in sorted(assignment):
        out += _string(topic) + struct.pack(">i", len(assignment[topic]))
        for partition in sorted(assignment[topic]):
            out += struct.pack(">i", partition)
    return out + _bytes(b"")


def decode_member_assignment(data: bytes) -> Dict[str, List[int]]:
    if not data:
        return {}
    reader = _Reader(data)
    reader.int16()                              # version
    out: Dict[str, List[int]] = {}
    for _ in range(reader.int32()):
        topic = reader.string()
        out[topic] = [reader.int32() for _ in range(reader.int32())]
    return out


def range_assign(members: Dict[str, List[str]],
                 partitions_by_topic: Dict[str, List[int]]
                 ) -> Dict[str, Dict[str, List[int]]]:
    """Range assignment (Kafka's default): per topic, split the sorted
    partition list into contiguous ranges over the topic's subscribers in
    member-id order; the first ``extra`` members get one more partition.
    Deterministic, so every member computing it agrees."""
    out: Dict[str, Dict[str, List[int]]] = {m: {} for m in members}
    for topic, partitions in partitions_by_topic.items():
        subscribers = sorted(m for m, topics in members.items()
                             if topic in topics)
        if not subscribers:
            continue
        parts = sorted(partitions)
        base, extra = divmod(len(parts), len(subscribers))
        start = 0
        for index, member in enumerate(subscribers):
            take = base + (1 if index < extra else 0)
            if take:
                out[member][topic] = parts[start:start + take]
            start += take
    return out


class _Broker:
    """One TCP connection + request/response correlation."""

    def __init__(self, host: str, port: int, client_id: str,
                 timeout: float = 10.0):
        self.host = host
        self.port = port
        self.client_id = client_id
        self.timeout = timeout
        self.correlation = 0
        self.lock = threading.Lock()
        self.sock = None
        self.closed = False
        self._connect()

    def _connect(self) -> None:
        if self.closed:
            raise KafkaError("broker handle is closed")
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        self.sock = sock
        if self.closed:   # close() raced the reconnect: don't leak it
            sock.close()
            raise KafkaError("broker handle is closed")

    def call(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        # One reconnect-and-reissue on transport failure (dead socket —
        # broker restart), the same treatment as the Redis wire client.
        # Standard Kafka at-least-once semantics: a retried Produce whose
        # first attempt partially landed may duplicate, never lose.
        with self.lock:
            try:
                response = self._exchange(api_key, api_version, body)
            except OSError:
                self._connect()     # refuses after close(): no leaks
                response = self._exchange(api_key, api_version, body)
            expected = self.correlation
        reader = _Reader(response)
        correlation = reader.int32()
        if correlation != expected:
            raise KafkaError("correlation id mismatch")
        return reader

    def _exchange(self, api_key: int, api_version: int,
                  body: bytes) -> bytes:
        self.correlation += 1
        header = (struct.pack(">hhi", api_key, api_version,
                              self.correlation)
                  + _string(self.client_id))
        payload = header + body
        self.sock.sendall(struct.pack(">i", len(payload)) + payload)
        size = struct.unpack(">i", self._read(4))[0]
        return self._read(size)

    def _read(self, n: int) -> bytes:
        data = b""
        while len(data) < n:
            chunk = self.sock.recv(n - len(data))
            if not chunk:
                raise ConnectionError("broker connection closed")
            data += chunk
        return data

    def close(self):
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass


class _PartitionFetcher(threading.Thread):
    """One fetch loop per assigned partition over its OWN broker
    connection (parity: kafka-go gives every reader its own dialer,
    kafka.go:181-186): a slow partition leader — or an empty partition
    long-polling at the broker — no longer head-of-line blocks its
    siblings, and heartbeats move to the coordinator loop instead of
    interleaving with fetch latency. Errors are recorded on ``.error``
    and end the thread; the owning poller notices and restarts the
    assignment pass."""

    def __init__(self, client: "KafkaClient", topic: str, partition: int,
                 resolve_offset, q: "queue.Queue", make_committer,
                 stop: threading.Event):
        super().__init__(daemon=True, name=f"kafka-{topic}[{partition}]")
        self.client = client
        self.topic = topic
        self.partition = partition
        # resolved lazily INSIDE this thread (committed-or-earliest): an
        # unreachable leader during offset lookup must stall only this
        # partition, not the poller's whole assignment pass
        self.resolve_offset = resolve_offset
        self.offset: Optional[int] = None
        self.q = q
        self.make_committer = make_committer
        self.stop_event = stop
        self.error: Optional[BaseException] = None

    def _stopping(self) -> bool:
        return self.stop_event.is_set() or self.client._closed

    def _paused(self) -> bool:
        return self.client.is_paused(self.topic)

    def _sleep(self, seconds: float) -> None:
        """Interruptible sleep: long connection backoffs must still honor
        stop() promptly (_stop_fetchers joins with a 5 s timeout)."""
        deadline = time.monotonic() + seconds
        while not self._stopping():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(0.1, remaining))

    def run(self) -> None:
        client = self.client
        conn: Optional[_Broker] = None
        offset_failures = 0
        conn_backoff = 0.5
        try:
            while not self._stopping():
                if self._paused():
                    # backpressure pause (client.pause(topic)): stop
                    # issuing fetches — the connection stays up, offsets
                    # stay put, and resume is just the flag clearing
                    self._sleep(0.1)
                    continue
                started = time.monotonic()
                try:
                    if conn is None:
                        host, port = client._leader_addr(self.topic,
                                                         self.partition)
                        conn = _Broker(host, port, client.client_id)
                    if self.offset is None:
                        try:
                            self.offset = self.resolve_offset(
                                self.partition)
                            offset_failures = 0
                        except KafkaError:
                            # coordinator loading / moved leadership
                            # during offset lookup is usually transient
                            # and partition-local: retry here a few times
                            # instead of tearing down every sibling — but
                            # a PERSISTENT failure (desynced shared
                            # handle, authz error) must escalate to the
                            # poller, whose full rejoin refreshes the
                            # coordinator connection this loop never
                            # could
                            offset_failures += 1
                            if offset_failures >= 6:
                                raise
                            client.logger.warn(
                                "kafka %s[%d]: offset resolution failed "
                                "(%d/6), retrying", self.topic,
                                self.partition, offset_failures)
                            time.sleep(0.5)
                            continue
                    batch = client._fetch(self.topic, self.partition,
                                          self.offset, broker=conn)
                except KafkaOffsetOutOfRange:
                    # retention expired past our offset: reset to earliest
                    try:
                        self.offset = client._earliest_offset(
                            self.topic, self.partition)
                        offset_failures = 0
                    except (OSError, ConnectionError, KafkaError):
                        offset_failures += 1
                        if offset_failures >= 6:
                            raise
                        client.logger.warn(
                            "kafka %s[%d]: earliest-offset reset failed "
                            "(%d/6), retrying", self.topic,
                            self.partition, offset_failures)
                        time.sleep(0.5)
                    continue
                except (OSError, ConnectionError):
                    # leader down/moved or dead conn: heal in-place on a
                    # fresh socket — dying here would tear down every
                    # sibling fetcher for one partition's outage. The
                    # metadata refresh is equally non-fatal: bootstrap
                    # being down too (whole-cluster restart) just means
                    # retry next pass. Backoff doubles toward 10 s so a
                    # long outage isn't a half-second reconnect hammer,
                    # and the refresh is throttled topic-wide (every
                    # sibling fetcher hits this path at once).
                    if conn is not None:
                        conn.close()
                        conn = None
                    try:
                        client._refresh_metadata_throttled(self.topic)
                    except (OSError, ConnectionError, KafkaError):
                        pass
                    self._sleep(conn_backoff)
                    conn_backoff = min(conn_backoff * 2, 10.0)
                    continue
                conn_backoff = 0.5   # successful fetch: connection healthy
                for offset, key, value in batch:
                    self.offset = offset + 1
                    # unwrap the opt-in trace envelope (base.py): the
                    # publisher's traceparent surfaces as a message
                    # header, exactly like inmem's native headers
                    traceparent, value = decode_trace_envelope(value)
                    metadata: Dict[str, Any] = {"partition": self.partition,
                                                "offset": offset}
                    if traceparent is not None:
                        metadata["traceparent"] = traceparent
                    message = Message(
                        self.topic, value, key,
                        metadata=metadata,
                        committer=self.make_committer(self.partition,
                                                      offset + 1))
                    while not self._stopping():
                        try:
                            self.q.put(message, timeout=0.5)
                            break
                        except queue.Full:
                            continue
                if not batch:
                    # a broker honouring fetch_max_wait_ms already parked
                    # us server-side; only top up if it returned early,
                    # so an empty partition never busy-spins
                    remaining = client.fetch_max_wait_ms / 1000.0 \
                        - (time.monotonic() - started)
                    if remaining > 0:
                        time.sleep(min(remaining, 0.5))
        except BaseException as exc:  # noqa: BLE001 — reported to poller
            self.error = exc
        finally:
            if conn is not None:
                conn.close()


class KafkaClient(PubSub):
    def __init__(self, config, logger, metrics, tracer=None):
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        broker = config.get_or_default("PUBSUB_BROKER",
                                       config.get_or_default("KAFKA_BROKER",
                                                             "localhost:9092"))
        host, _, port = broker.partition(":")
        self.bootstrap = (host, int(port or 9092))
        self.group = config.get_or_default("CONSUMER_ID", "gofr-tpu")
        self.client_id = config.get_or_default("APP_NAME", "gofr-tpu-app")
        self.fetch_max_wait_ms = config.get_int("KAFKA_FETCH_MAX_WAIT_MS", 250)
        # "group": broker-coordinated membership + range assignment
        # (kafka.go:167-220 semantics). "static": every consumer fetches
        # all partitions (r3 behaviour; brokers without group support).
        self.group_mode = config.get_or_default("KAFKA_GROUP_MODE",
                                                "group").lower()
        self.session_timeout_ms = config.get_int(
            "KAFKA_SESSION_TIMEOUT_MS", 10000)
        self.heartbeat_interval_ms = config.get_int(
            "KAFKA_HEARTBEAT_INTERVAL_MS", 3000)
        # how often pollers re-learn leadership + partition counts;
        # tests shrink it to exercise partition growth quickly
        self.metadata_refresh_s = config.get_float(
            "KAFKA_METADATA_REFRESH_S", 30.0)
        self._memberships: Dict[str, Tuple[Any, str, int]] = {}
        self._group_conns: Dict[str, "_Broker"] = {}
        self._brokers: Dict[Tuple[str, int], _Broker] = {}
        self._meta_lock = threading.Lock()
        self._leaders: Dict[Tuple[str, int], Tuple[str, int]] = {}
        self._meta_refreshed_at: Dict[str, float] = {}
        self._queues: Dict[str, "queue.Queue[Optional[Message]]"] = {}
        self._pollers: Dict[str, threading.Thread] = {}
        # per-topic backpressure flags checked by every partition fetcher
        # (pause()/resume() below) — set means "stop issuing fetches"
        self._pause_events: Dict[str, threading.Event] = {}
        self._closed = False
        self._broker(self.bootstrap)  # fail fast if unreachable
        logger.info("kafka connected %s:%d group=%s", *self.bootstrap,
                    self.group)

    def _broker(self, addr: Tuple[str, int]) -> _Broker:
        # N per-partition fetchers + the event loop's committers race this
        # cache; a bare check-then-insert would leak the loser's socket.
        # Connect OUTSIDE the lock (it can block up to the 10 s timeout),
        # publish under it, close the losing duplicate.
        broker = self._brokers.get(addr)
        if broker is not None:
            return broker
        candidate = _Broker(addr[0], addr[1], self.client_id)
        with self._meta_lock:
            broker = self._brokers.setdefault(addr, candidate)
        if broker is not candidate:
            candidate.close()
        return broker

    # -- metadata / leader routing -----------------------------------------
    def _refresh_metadata(self, topic: str) -> List[int]:
        reader = self._broker(self.bootstrap).call(
            API_METADATA, 1, struct.pack(">i", 1) + _string(topic))
        nodes: Dict[int, Tuple[str, int]] = {}
        for _ in range(reader.int32()):          # brokers
            node_id = reader.int32()
            host = reader.string()
            port = reader.int32()
            reader.string()                      # rack
            nodes[node_id] = (host, port)
        reader.int32()                           # controller id
        partitions: List[int] = []
        for _ in range(reader.int32()):          # topics
            reader.int16()                       # topic error
            name = reader.string()
            reader.int8()                        # is_internal
            for _ in range(reader.int32()):
                reader.int16()                   # partition error
                partition = reader.int32()
                leader = reader.int32()
                for _ in range(reader.int32()):  # replicas
                    reader.int32()
                for _ in range(reader.int32()):  # isr
                    reader.int32()
                if name == topic:
                    partitions.append(partition)
                    if leader in nodes:
                        with self._meta_lock:
                            self._leaders[(topic, partition)] = nodes[leader]
        with self._meta_lock:
            self._meta_refreshed_at[topic] = time.monotonic()
        return sorted(partitions)

    def _refresh_metadata_throttled(self, topic: str,
                                    min_interval: float = 1.0) -> None:
        """Topic-wide refresh rate limit. When a broker dies, every one of
        the topic's partition fetchers hits its reconnect path at once and
        each would issue an identical Metadata request per backoff tick —
        a refresh stampede against the (possibly still recovering)
        bootstrap broker. Only one fetcher per interval refreshes; the
        rest reuse its result from the shared leader cache."""
        with self._meta_lock:
            last = self._meta_refreshed_at.get(topic)
            if last is not None \
                    and time.monotonic() - last < min_interval:
                return
            # claim the interval before the slow lock-free refresh so
            # racing fetchers skip instead of queueing up behind it
            self._meta_refreshed_at[topic] = time.monotonic()
        self._refresh_metadata(topic)

    def _leader_addr(self, topic: str, partition: int) -> Tuple[str, int]:
        addr = self._leaders.get((topic, partition))
        if addr is None:
            self._refresh_metadata(topic)
            addr = self._leaders.get((topic, partition), self.bootstrap)
        return addr

    def _leader(self, topic: str, partition: int) -> _Broker:
        return self._broker(self._leader_addr(topic, partition))

    # -- produce ------------------------------------------------------------
    def publish(self, topic: str, payload: bytes, key: bytes = b"") -> None:
        self.metrics.increment_counter("app_pubsub_publish_total_count",
                                       topic=topic)
        # cross-service trace propagation: message-set v1 has no record
        # headers, so when a trace is in flight the traceparent rides in
        # the opt-in byte envelope (base.py). Publishes outside a span
        # keep the raw wire payload byte-for-byte unchanged.
        span = None
        if self.tracer is not None:
            from gofr_tpu.trace import current_span, format_traceparent
            if current_span() is not None:
                span = self.tracer.start_span("pubsub.publish")
                span.set_attribute("topic", topic)
                span.set_attribute("backend", "KAFKA")
                payload = encode_trace_envelope(format_traceparent(span),
                                                payload)
        try:
            self._publish_raw(topic, payload, key)
        except Exception:
            if span is not None:
                span.set_status("ERROR")
            raise
        finally:
            if span is not None:
                span.finish()

    def _publish_raw(self, topic: str, payload: bytes,
                     key: bytes = b"") -> None:
        partitions = self._refresh_metadata(topic) or [0]
        partition = (zlib.crc32(key) % len(partitions)) if key \
            else int(time.time() * 1e6) % len(partitions)
        message_set = encode_message_set([(key, payload)])
        body = (struct.pack(">hi", 1, 10000)          # acks=1, timeout
                + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition)
                + _bytes(message_set))
        reader = self._leader(topic, partition).call(API_PRODUCE, 2, body)
        for _ in range(reader.int32()):
            reader.string()                           # topic
            for _ in range(reader.int32()):
                reader.int32()                        # partition
                error = reader.int16()
                reader.int64()                        # base offset
                reader.int64()                        # log append time
                if error:
                    raise KafkaError(f"produce error code {error}")
        self.metrics.increment_counter("app_pubsub_publish_success_count",
                                       topic=topic)

    # -- offsets ------------------------------------------------------------
    def _committed_offset(self, topic: str, partition: int,
                          broker: Optional["_Broker"] = None) -> int:
        """OffsetFetch v1. Group offsets live on the coordinator, so group
        mode must read them there — on a multi-broker cluster asking the
        bootstrap node returns NOT_COORDINATOR, and silently treating
        that as "no commit" would reset the partition to earliest."""
        body = (_string(self.group) + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition))
        reader = (broker or self._broker(self.bootstrap)).call(
            API_OFFSET_FETCH, 1, body)
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()
                offset = reader.int64()
                reader.string()                       # metadata
                error = reader.int16()
                if error:
                    raise KafkaError(f"offset fetch error {error}")
                return max(0, offset)
        return 0

    def _earliest_offset(self, topic: str, partition: int) -> int:
        body = (struct.pack(">i", -1) + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iq", partition, -2))   # -2 = earliest
        reader = self._leader(topic, partition).call(API_LIST_OFFSETS, 1,
                                                     body)
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()
                error = reader.int16()
                reader.int64()                        # timestamp
                offset = reader.int64()
                if error:
                    raise KafkaError(f"list offsets error {error}")
                return offset
        return 0

    def _commit_offset(self, topic: str, partition: int, offset: int,
                       generation: int = -1, member_id: str = "",
                       broker: Optional["_Broker"] = None) -> None:
        """OffsetCommit v2. In group mode the commit carries the member's
        generation so a fenced (rebalanced-away) member cannot clobber the
        new owner's progress."""
        body = (_string(self.group) + struct.pack(">i", generation)
                + _string(member_id)
                + struct.pack(">q", -1)
                + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iq", partition, offset) + _string(None))
        reader = (broker or self._coordinator_broker()).call(
            API_OFFSET_COMMIT, 2, body)
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()
                error = reader.int16()
                if error in (ERR_ILLEGAL_GENERATION, ERR_UNKNOWN_MEMBER,
                             ERR_REBALANCE_IN_PROGRESS):
                    raise KafkaRebalance(f"offset commit fenced ({error})")
                if error:
                    self.logger.error("kafka offset commit error %d", error)

    # -- group coordination (kafka.go:167-220 scale-out semantics) ----------
    def _coordinator_broker(self) -> _Broker:
        addr = getattr(self, "_coordinator_addr", None)
        return self._broker(addr or self.bootstrap)

    def _find_coordinator_addr(self) -> Tuple[str, int]:
        reader = self._broker(self.bootstrap).call(
            API_FIND_COORDINATOR, 0, _string(self.group))
        error = reader.int16()
        if error:
            raise KafkaError(f"find coordinator error {error}")
        reader.int32()                            # node id
        host = reader.string()
        port = reader.int32()
        self._coordinator_addr = (host, port)
        return (host, port)

    def _group_conn(self, topic: str, addr: Tuple[str, int]) -> _Broker:
        """Dedicated coordinator connection per topic membership. A
        JoinGroup BLOCKS server-side until the rebalance barrier
        completes; on a shared connection that would stall every other
        request from this client (heartbeats of other memberships,
        commits), so group traffic never rides the shared broker cache."""
        conn = self._group_conns.get(topic)
        if conn is None or (conn.host, conn.port) != addr or conn.closed:
            if conn is not None:
                conn.close()
            # a JoinGroup response can be held server-side for the whole
            # rebalance window (dead members time out of their session),
            # so this socket's timeout must comfortably exceed it
            conn = _Broker(addr[0], addr[1], self.client_id,
                           timeout=max(30.0,
                                       self.session_timeout_ms / 1000 * 3))
            self._group_conns[topic] = conn
        return conn

    def _join_group(self, coordinator: _Broker, topic: str,
                    member_id: str):
        """JoinGroup v0 → (generation, member_id, is_leader, members
        metadata map — non-empty only for the leader)."""
        metadata = encode_consumer_metadata([topic])
        body = (_string(self.group)
                + struct.pack(">i", self.session_timeout_ms)
                + _string(member_id) + _string("consumer")
                + struct.pack(">i", 1) + _string("range") + _bytes(metadata))
        reader = coordinator.call(API_JOIN_GROUP, 0, body)
        error = reader.int16()
        if error == ERR_UNKNOWN_MEMBER:
            raise KafkaRebalance("join: unknown member id",
                                 reset_member=True)
        if error:
            raise KafkaError(f"join group error {error}")
        generation = reader.int32()
        reader.string()                           # protocol ("range")
        leader_id = reader.string()
        my_id = reader.string()
        members: Dict[str, List[str]] = {}
        for _ in range(reader.int32()):
            mid = reader.string()
            meta = reader.raw_bytes() or b""
            members[mid] = decode_consumer_metadata(meta)
        return generation, my_id, my_id == leader_id, members

    def _sync_group(self, coordinator: _Broker, generation: int,
                    member_id: str,
                    assignments: Optional[Dict[str, Dict[str, List[int]]]]
                    ) -> Dict[str, List[int]]:
        """SyncGroup v0. The leader ships every member's assignment; the
        coordinator hands each member its own back."""
        entries = assignments or {}
        body = (_string(self.group) + struct.pack(">i", generation)
                + _string(member_id) + struct.pack(">i", len(entries)))
        for mid in sorted(entries):
            body += _string(mid) + _bytes(
                encode_member_assignment(entries[mid]))
        reader = coordinator.call(API_SYNC_GROUP, 0, body)
        error = reader.int16()
        if error in (ERR_UNKNOWN_MEMBER, ERR_ILLEGAL_GENERATION,
                     ERR_REBALANCE_IN_PROGRESS):
            raise KafkaRebalance(f"sync: rebalance ({error})",
                                 reset_member=error == ERR_UNKNOWN_MEMBER)
        if error:
            raise KafkaError(f"sync group error {error}")
        return decode_member_assignment(reader.raw_bytes() or b"")

    def _heartbeat(self, coordinator: _Broker, generation: int,
                   member_id: str) -> None:
        body = (_string(self.group) + struct.pack(">i", generation)
                + _string(member_id))
        reader = coordinator.call(API_HEARTBEAT, 0, body)
        error = reader.int16()
        if error in (ERR_UNKNOWN_MEMBER, ERR_ILLEGAL_GENERATION,
                     ERR_REBALANCE_IN_PROGRESS):
            raise KafkaRebalance(f"heartbeat: rebalance ({error})",
                                 reset_member=error == ERR_UNKNOWN_MEMBER)
        if error:
            raise KafkaError(f"heartbeat error {error}")

    def _leave_group(self, member_id: str,
                     broker: Optional[_Broker] = None) -> None:
        try:
            body = _string(self.group) + _string(member_id)
            (broker or self._coordinator_broker()).call(
                API_LEAVE_GROUP, 0, body)
        except Exception:  # noqa: BLE001 — best effort on shutdown; the
            pass           # session timeout evicts us anyway

    def _rejoin(self, topic: str, member_id: str):
        """One find-coordinator → join → (leader assigns) → sync cycle.
        Returns (coordinator, generation, member_id, my partitions)."""
        # refresh before joining: every member (not just the elected
        # leader) re-learns partition leadership here, so a moved leader
        # or stale cache heals on the rebalance path
        self._refresh_metadata(topic)
        addr = self._find_coordinator_addr()
        coordinator = self._group_conn(topic, addr)
        generation, member_id, is_leader, members = self._join_group(
            coordinator, topic, member_id)
        assignments = None
        if is_leader:
            all_topics = sorted({t for topics in members.values()
                                 for t in topics})
            partitions_by_topic = {
                t: self._refresh_metadata(t) for t in all_topics}
            assignments = range_assign(members, partitions_by_topic)
        my_assignment = self._sync_group(coordinator, generation, member_id,
                                         assignments)
        self._memberships[topic] = (coordinator, member_id, generation)
        return coordinator, generation, member_id, \
            sorted(my_assignment.get(topic, []))

    # -- fetch loop (per-topic reader, kafka.go:181-186) --------------------
    def _poll_topic(self, topic: str) -> None:
        if self.group_mode == "static":
            self._poll_topic_static(topic)
        else:
            self._poll_topic_group(topic)

    def _spawn_fetchers(self, topic: str, partitions: List[int],
                        resolve_offset, make_committer,
                        stop: "threading.Event"
                        ) -> Dict[int, "_PartitionFetcher"]:
        fetchers = {
            partition: _PartitionFetcher(self, topic, partition,
                                         resolve_offset,
                                         self._queues[topic],
                                         make_committer, stop)
            for partition in partitions}
        for fetcher in fetchers.values():
            fetcher.start()
        return fetchers

    @staticmethod
    def _check_fetchers(fetchers: Dict[int, "_PartitionFetcher"]) -> None:
        for fetcher in fetchers.values():
            if not fetcher.is_alive():
                raise fetcher.error or KafkaError(
                    f"fetcher for partition {fetcher.partition} died")

    @staticmethod
    def _stop_fetchers(fetchers: Dict[int, "_PartitionFetcher"],
                       stop: "threading.Event") -> None:
        stop.set()
        for fetcher in fetchers.values():
            fetcher.join(timeout=5.0)

    def _poll_topic_group(self, topic: str) -> None:
        """Group-coordinated fetch loop: join the consumer group, fetch
        only the partitions the leader assigned to this member, heartbeat,
        and rejoin on any membership change (kafka.go:167-220, 234-242:
        two instances in one group split partitions; a dead member's
        partitions are reclaimed by survivors after its session times
        out)."""
        q = self._queues[topic]
        backoff = 0.1
        heartbeat_s = self.heartbeat_interval_ms / 1000.0
        member_id = ""
        while not self._closed:
            try:
                (coordinator, generation, member_id,
                 partitions) = self._rejoin(topic, member_id)
                self.logger.info(
                    "kafka group %s member %s gen %d: assigned %s%r",
                    self.group, member_id, generation, topic, partitions)
                # one fetcher thread + dedicated connection per assigned
                # partition (kafka.go:181-186: kafka-go reader-per-
                # partition concurrency): a slow partition leader or an
                # empty long-polling partition can't head-of-line block
                # its siblings, and each fetcher resolves its own
                # committed-or-earliest start offset so a dead leader
                # stalls only its partition. Group offsets live on the
                # coordinator (shared broker cache — its calls are
                # locked, and commits must NOT ride the group conn: a
                # rebalance blocks that conn server-side for seconds
                # while commit() runs on the app's event loop).
                def resolve_offset(partition):
                    committed = self._committed_offset(
                        topic, partition, self._coordinator_broker())
                    return committed or self._earliest_offset(topic,
                                                              partition)

                def make_committer(partition, next_offset):
                    return self._make_committer(topic, partition,
                                                next_offset, generation,
                                                member_id)

                stop = threading.Event()
                fetchers = self._spawn_fetchers(topic, partitions,
                                                resolve_offset,
                                                make_committer, stop)
                known_partition_count = len(self._refresh_metadata(topic))
                refresh_at = time.monotonic() + self.metadata_refresh_s
                try:
                    # the poller thread is now the pure coordinator loop:
                    # heartbeat on schedule (no longer entangled with
                    # fetch latency or a slow consumer's queue drain),
                    # watch fetcher health, detect partition growth
                    while not self._closed:
                        self._heartbeat(coordinator, generation, member_id)
                        deadline = time.monotonic() + heartbeat_s
                        while time.monotonic() < deadline \
                                and not self._closed:
                            self._check_fetchers(fetchers)
                            time.sleep(0.05)
                        backoff = 0.1
                        if time.monotonic() >= refresh_at:
                            # re-learn leadership (moves heal without an
                            # error) and detect partition growth, which
                            # the group must rebalance over (the
                            # coordinator won't tell us)
                            current = len(self._refresh_metadata(topic))
                            refresh_at = time.monotonic() + self.metadata_refresh_s
                            if current != known_partition_count:
                                raise KafkaRebalance(
                                    f"partition count changed "
                                    f"{known_partition_count} -> {current}")
                finally:
                    self._stop_fetchers(fetchers, stop)
            except KafkaRebalance as exc:
                if self._closed:
                    break
                if getattr(exc, "reset_member", False):
                    member_id = ""
                self.logger.info("kafka %s rebalancing: %s", topic, exc)
                continue          # rejoin promptly, no backoff
            except Exception as exc:
                if self._closed:
                    break
                self.logger.error(
                    "kafka group poller %s errored (retrying in %.1fs): %r",
                    topic, backoff, exc)
                time.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
        membership = self._memberships.pop(topic, None)
        if membership is not None:
            self._leave_group(membership[1], membership[0])
        q.put(None)

    def _poll_topic_static(self, topic: str) -> None:
        """Static fetch loop (every partition, no group coordination).
        Survives broker outages: an errored pass (fetch/metadata failure
        beyond call()'s one immediate reconnect) backs off and retries
        from the committed offset instead of dying — otherwise the first
        multi-second restart would permanently kill the subscription
        while publish happily recovers."""
        q = self._queues[topic]
        backoff = 0.1
        while not self._closed:
            try:
                partitions = self._refresh_metadata(topic)
                if not partitions:
                    # topic doesn't exist yet (or metadata stale): retry
                    # via the backoff path instead of idling forever
                    raise KafkaError(f"no partitions for topic {topic!r}")

                def resolve_offset(partition):
                    committed = self._committed_offset(topic, partition)
                    return committed or self._earliest_offset(topic,
                                                              partition)

                def make_committer(partition, next_offset):
                    return self._make_committer(topic, partition,
                                                next_offset)

                # per-partition fetcher threads (see _PartitionFetcher):
                # this loop just watches health and partition growth
                stop = threading.Event()
                fetchers = self._spawn_fetchers(topic, partitions,
                                                resolve_offset,
                                                make_committer, stop)
                refresh_at = time.monotonic() + self.metadata_refresh_s
                healthy_at = time.monotonic() + 2.0
                try:
                    while not self._closed:
                        self._check_fetchers(fetchers)
                        if time.monotonic() >= healthy_at:
                            # only a *sustained* healthy pass resets the
                            # backoff — fetchers dying right after spawn
                            # (non-retryable fetch error) must keep
                            # escalating toward the 10 s cap, not hot-loop
                            backoff = 0.1
                        if time.monotonic() >= refresh_at:
                            # periodically re-learn partitions (growth
                            # after subscribe) without waiting for error
                            refresh_at = time.monotonic() \
                                + self.metadata_refresh_s
                            for partition in self._refresh_metadata(topic):
                                if partition not in fetchers:
                                    fetcher = _PartitionFetcher(
                                        self, topic, partition,
                                        resolve_offset, q,
                                        make_committer, stop)
                                    fetcher.start()
                                    fetchers[partition] = fetcher
                        time.sleep(0.05)
                finally:
                    self._stop_fetchers(fetchers, stop)
            except Exception as exc:
                if self._closed:
                    break
                self.logger.error(
                    "kafka poller %s errored (retrying in %.1fs): %r",
                    topic, backoff, exc)
                time.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
        q.put(None)

    def _make_committer(self, topic, partition, next_offset,
                        generation: int = -1, member_id: str = "",
                        broker: Optional["_Broker"] = None):
        return lambda: self._commit_offset(topic, partition, next_offset,
                                           generation, member_id, broker)

    def _fetch(self, topic: str, partition: int, offset: int,
               broker: Optional[_Broker] = None
               ) -> List[Tuple[int, bytes, bytes]]:
        body = (struct.pack(">iii", -1, self.fetch_max_wait_ms, 1)
                + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, offset, 4 * 1024 * 1024))
        conn = broker if broker is not None \
            else self._leader(topic, partition)
        reader = conn.call(API_FETCH, 2, body)
        reader.int32()                                # throttle time
        out: List[Tuple[int, bytes, bytes]] = []
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()                        # partition
                error = reader.int16()
                reader.int64()                        # high watermark
                message_set = reader.raw_bytes() or b""
                if error == 1:
                    raise KafkaOffsetOutOfRange(
                        f"offset {offset} out of range for "
                        f"{topic}/{partition}")
                if error:
                    raise KafkaError(f"fetch error code {error}")
                out.extend(decode_message_set(message_set, offset))
        return out

    # -- backpressure (ISSUE 11) -------------------------------------------
    def pause(self, topic: str, reason: str = "backpressure") -> None:
        """Stop this topic's partition fetchers from issuing fetches —
        connections stay up, offsets stay put, the consumer group keeps
        heartbeating (no rebalance). Idempotent; only the unpaused→paused
        transition is counted in
        ``app_pubsub_consumer_paused_total{topic,reason}``."""
        with self._meta_lock:
            event = self._pause_events.setdefault(topic, threading.Event())
        if not event.is_set():
            event.set()
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_pubsub_consumer_paused_total",
                    topic=topic, reason=reason)
            self.logger.info("kafka %s: consumer paused (%s)", topic,
                             reason)

    def resume(self, topic: str) -> None:
        """Clear a ``pause`` — fetchers pick up from their held offsets
        on their next loop pass. Idempotent."""
        event = self._pause_events.get(topic)
        if event is not None and event.is_set():
            event.clear()
            self.logger.info("kafka %s: consumer resumed", topic)

    def is_paused(self, topic: str) -> bool:
        event = self._pause_events.get(topic)
        return event is not None and event.is_set()

    async def subscribe(self, topic: str) -> Optional[Message]:
        import asyncio
        self.metrics.increment_counter("app_pubsub_subscribe_total_count",
                                       topic=topic)
        if topic not in self._pollers:
            self._queues[topic] = queue.Queue(maxsize=65536)
            poller = threading.Thread(target=self._poll_topic, args=(topic,),
                                      daemon=True, name=f"kafka-{topic}")
            self._pollers[topic] = poller
            poller.start()
        message = await asyncio.get_running_loop().run_in_executor(
            None, self._queues[topic].get)
        if message is not None:
            self.metrics.increment_counter(
                "app_pubsub_subscribe_success_count", topic=topic)
        return message

    # -- topic admin (kafka.go:247-264) -------------------------------------
    def create_topic(self, topic: str, partitions: int = 1,
                     replication: int = 1) -> None:
        body = (struct.pack(">i", 1) + _string(topic)
                + struct.pack(">ih", partitions, replication)
                + struct.pack(">i", 0)                # assignments
                + struct.pack(">i", 0)                # configs
                + struct.pack(">i", 10000))           # timeout
        reader = self._broker(self.bootstrap).call(API_CREATE_TOPICS, 0, body)
        for _ in range(reader.int32()):
            reader.string()
            error = reader.int16()
            if error and error != 36:                 # 36 = already exists
                raise KafkaError(f"create topic error {error}")

    def delete_topic(self, topic: str) -> None:
        body = (struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 10000))
        reader = self._broker(self.bootstrap).call(API_DELETE_TOPICS, 0, body)
        for _ in range(reader.int32()):
            reader.string()
            error = reader.int16()
            if error and error != 3:                  # 3 = unknown topic
                raise KafkaError(f"delete topic error {error}")

    def health_check(self) -> dict:
        try:
            self._broker(self.bootstrap).call(
                API_METADATA, 1, struct.pack(">i", 0))
            return {"status": "UP",
                    "details": {"backend": "KAFKA",
                                "broker": f"{self.bootstrap[0]}:"
                                          f"{self.bootstrap[1]}",
                                "group": self.group}}
        except Exception as exc:
            return {"status": "DOWN", "details": {"error": repr(exc)}}

    def close(self) -> None:
        self._closed = True
        # leave the group eagerly so the coordinator rebalances survivors
        # now rather than after the session timeout
        for conn, member_id, _ in list(self._memberships.values()):
            self._leave_group(member_id, conn)
        self._memberships.clear()
        for q in self._queues.values():
            q.put(None)
        for conn in list(self._group_conns.values()):
            conn.close()
        for broker in list(self._brokers.values()):
            broker.close()
