"""Pub/sub contracts and the Message request adapter.

Reference: ``pkg/gofr/datasource/pubsub/interface.go:11-30`` (Publisher,
Subscriber, Committer, Client) and ``message.go:13-107`` (Message satisfies
the framework Request contract: ``param("topic")``, scalar/JSON ``bind``).
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

# -- trace-context envelope ---------------------------------------------------
# Kafka's message-set v1 wire format (datasource/pubsub/kafka.py) has no
# native record headers, so cross-service trace propagation uses a tiny
# opt-in byte envelope around the payload: MAGIC + uint16 traceparent
# length + traceparent + original payload. Applied only when a span is
# active at publish time; consumers that don't know the envelope still see
# a payload whose first bytes are the magic (never valid JSON/UTF-8 text),
# and gofr-tpu consumers unwrap it transparently.
_TRACE_MAGIC = b"\x00GTR1"


def encode_trace_envelope(traceparent: str, payload: bytes) -> bytes:
    """Wrap ``payload`` with a ``traceparent`` header (W3C string)."""
    header = traceparent.encode("ascii", "replace")
    return _TRACE_MAGIC + struct.pack(">H", len(header)) + header + payload


def decode_trace_envelope(raw: bytes) -> Tuple[Optional[str], bytes]:
    """Unwrap a trace envelope → (traceparent, payload). Non-enveloped
    input returns ``(None, raw)`` unchanged — safe on any byte stream."""
    if not raw.startswith(_TRACE_MAGIC):
        return None, raw
    offset = len(_TRACE_MAGIC)
    if len(raw) < offset + 2:
        return None, raw
    (length,) = struct.unpack_from(">H", raw, offset)
    offset += 2
    if len(raw) < offset + length:
        return None, raw
    header = raw[offset:offset + length].decode("ascii", "replace")
    return header, raw[offset + length:]


class Message:
    """A received message; doubles as the handler's ``request``."""

    __slots__ = ("topic", "value", "key", "metadata", "_committer", "committed")

    def __init__(self, topic: str, value: bytes, key: bytes = b"",
                 metadata: Optional[Dict[str, Any]] = None, committer=None):
        self.topic = topic
        self.value = value
        self.key = key
        self.metadata = metadata or {}
        self._committer = committer
        self.committed = False

    # -- Request contract (pubsub/message.go:35-107) ------------------------
    def param(self, key: str) -> str:
        if key == "topic":
            return self.topic
        return str(self.metadata.get(key, ""))

    def path_param(self, key: str) -> str:
        return self.param(key)

    def bind(self, target: Any = None) -> Any:
        """Scalar or JSON decode of the payload (message.go:60-107)."""
        text = self.value.decode("utf-8", "replace")
        if target is None:
            try:
                return json.loads(text)
            except ValueError:
                return text
        if target in (str,):
            return text
        if target in (int, float):
            return target(text.strip())
        try:
            data = json.loads(text)
        except ValueError as exc:
            raise ValueError(f"cannot bind message on {self.topic!r}") from exc
        from gofr_tpu.http.request import _bind_into
        return _bind_into(target, data)

    def header(self, key: str) -> str:
        return str(self.metadata.get(key, ""))

    # -- Committer contract (interface.go:27-30) ----------------------------
    def commit(self) -> None:
        if self._committer is not None and not self.committed:
            self._committer()
        self.committed = True

    def to_log(self):
        return {"topic": self.topic, "bytes": len(self.value)}


class PubSub:
    """Client contract: Publisher + Subscriber + topic admin + health
    (interface.go:19-26)."""

    def publish(self, topic: str, payload: bytes, key: bytes = b"") -> None:
        raise NotImplementedError

    async def subscribe(self, topic: str) -> Optional[Message]:
        """Blocking receive of one message from the topic (returns None on
        backend shutdown)."""
        raise NotImplementedError

    def create_topic(self, topic: str) -> None:
        raise NotImplementedError

    def delete_topic(self, topic: str) -> None:
        raise NotImplementedError

    def health_check(self) -> dict:
        raise NotImplementedError

    def close(self) -> None:
        pass
