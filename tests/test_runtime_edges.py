"""Runtime-edge depth tests: handler timeout/panic isolation, context
binding into dataclasses, in-memory broker semantics, dynamic-batcher
error propagation and coalescing, executor oversized-batch splitting and
dispatch/fetch parity — reference pkg/gofr/handler_test.go /
grpc/http transport tests style."""

import asyncio
import dataclasses
import json
import time

import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from tests.util import http_request, make_app, run, serving


# -- handler semantics --------------------------------------------------------

def test_sync_handler_runs_off_loop():
    """A blocking sync handler must not freeze the event loop: a
    concurrent async route stays responsive."""
    async def main():
        app = make_app()
        release = asyncio.Event()

        def blocking(ctx):
            time.sleep(0.5)
            return {"done": True}

        async def ping(ctx):
            return {"pong": True}

        app.get("/block", blocking)
        app.get("/ping", ping)
        async with serving(app) as port:
            block_task = asyncio.ensure_future(
                http_request(port, "GET", "/block"))
            await asyncio.sleep(0.05)   # blocking handler is running
            t0 = time.perf_counter()
            pong = await http_request(port, "GET", "/ping")
            assert pong.status == 200
            assert time.perf_counter() - t0 < 0.3
            assert (await block_task).json()["data"]["done"] is True
    run(main())


# -- context binding ----------------------------------------------------------

def test_bind_into_dataclass_and_query_params():
    @dataclasses.dataclass
    class Order:
        order_id: str = ""
        quantity: int = 0

    async def main():
        app = make_app()

        async def create(ctx):
            order = ctx.bind(Order)
            assert isinstance(order, Order)
            return {"order_id": order.order_id,
                    "quantity": order.quantity,
                    "tag": ctx.param("tag"),
                    "tags": ctx.params("tag")}

        app.post("/orders", create)
        async with serving(app) as port:
            result = await http_request(
                port, "POST", "/orders?tag=a&tag=b",
                body=json.dumps({"order_id": "o1", "quantity": 3}).encode(),
                headers={"Content-Type": "application/json"})
            data = result.json()["data"]
            assert data == {"order_id": "o1", "quantity": 3,
                            "tag": "a", "tags": ["a", "b"]}
    run(main())


def test_bind_form_urlencoded():
    async def main():
        app = make_app()
        app.post("/form", lambda ctx: ctx.bind())
        async with serving(app) as port:
            result = await http_request(
                port, "POST", "/form", body=b"name=ada&age=36",
                headers={"Content-Type":
                         "application/x-www-form-urlencoded"})
            assert result.json()["data"] == {"name": "ada", "age": "36"}
    run(main())


# -- in-memory broker ---------------------------------------------------------

def test_inmem_broker_fifo_and_commit(mock_container):
    broker = mock_container.pubsub

    async def main():
        for i in range(3):
            broker.publish("events", f"m{i}".encode())
        got = [await asyncio.wait_for(broker.subscribe("events"), 5.0)
               for _ in range(3)]
        assert [m.value for m in got] == [b"m0", b"m1", b"m2"]
        got[0].commit()
        assert got[0].committed
    run(main())


def test_inmem_broker_topic_isolation(mock_container):
    broker = mock_container.pubsub

    async def main():
        broker.publish("a", b"for-a")
        broker.publish("b", b"for-b")
        message = await asyncio.wait_for(broker.subscribe("b"), 5.0)
        assert message.value == b"for-b"
    run(main())


# -- dynamic batcher ----------------------------------------------------------

def _executor(mock_container, fn=None, buckets=(1, 2, 4, 8)):
    from gofr_tpu.tpu import Executor
    executor = Executor(mock_container.logger, mock_container.metrics)
    executor.register("m", fn or (lambda p, x: x * 2.0), {},
                      buckets=buckets)
    return executor


def test_batcher_coalesces_concurrent_requests(mock_container):
    from gofr_tpu.tpu import DynamicBatcher
    executor = _executor(mock_container)
    batcher = DynamicBatcher(executor, max_batch=8, max_delay_ms=20.0,
                             logger=mock_container.logger)

    async def main():
        outs = await asyncio.gather(*[
            batcher.predict("m", np.full((2,), i, np.float32))
            for i in range(6)])
        for i, out in enumerate(outs):
            np.testing.assert_allclose(out, [2.0 * i] * 2)
        # 6 examples coalesced into few executes, not 6
        count = mock_container.metrics.value("app_tpu_requests_total",
                                             model="m")
        assert count is not None and count <= 3
    run(main())


def test_batcher_propagates_model_failure(mock_container):
    from gofr_tpu.tpu import DynamicBatcher

    def exploding(p, x):
        raise ValueError("bad batch")

    executor = _executor(mock_container, fn=exploding)
    batcher = DynamicBatcher(executor, max_batch=4, max_delay_ms=5.0,
                             logger=mock_container.logger)

    async def main():
        with pytest.raises(Exception):
            await batcher.predict("m", np.ones((2,), np.float32))
    run(main())


def test_batcher_full_batch_flushes_before_timer(mock_container):
    from gofr_tpu.tpu import DynamicBatcher
    executor = _executor(mock_container)
    batcher = DynamicBatcher(executor, max_batch=4, max_delay_ms=10_000.0,
                             logger=mock_container.logger)

    async def main():
        t0 = time.perf_counter()
        outs = await asyncio.wait_for(asyncio.gather(*[
            batcher.predict("m", np.ones((1,), np.float32))
            for _ in range(4)]), 5.0)
        # max_batch reached → flush NOW, not after the 10 s deadline
        assert time.perf_counter() - t0 < 2.0
        assert len(outs) == 4
    run(main())


# -- executor -----------------------------------------------------------------

def test_executor_splits_oversized_batches(mock_container):
    executor = _executor(mock_container, buckets=(1, 2, 4))
    batch = np.arange(11, dtype=np.float32)
    out = executor.predict("m", batch)
    np.testing.assert_allclose(out, batch * 2.0)


def test_executor_dispatch_fetch_matches_predict(mock_container):
    executor = _executor(mock_container)
    batch = np.arange(3, dtype=np.float32)
    direct = executor.predict("m", batch)
    handle = executor.dispatch("m", batch)
    fetched = executor.fetch(handle)
    np.testing.assert_allclose(fetched, direct)
    assert executor.is_warm("m", 3)
    assert not executor.is_warm("missing", 1)
    with pytest.raises(ValueError):
        executor.dispatch("m", np.ones((99,), np.float32))
    with pytest.raises(KeyError):
        executor.dispatch("missing", batch)


def test_executor_unknown_model_raises(mock_container):
    executor = _executor(mock_container)
    with pytest.raises(KeyError, match="not registered"):
        executor.predict("nope", np.ones((1,), np.float32))


def test_executor_pads_and_slices(mock_container):
    recorded = []

    def spy(p, x):
        recorded.append(x.shape[0])
        return x + 1.0

    executor = _executor(mock_container, fn=spy, buckets=(4, 8))
    out = executor.predict("m", np.zeros((3,), np.float32))
    assert out.shape == (3,)          # padding sliced off
    assert recorded[-1] == 4          # padded up to the 4-bucket
