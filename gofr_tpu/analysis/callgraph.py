"""Module-local call graph: which functions run *on the asyncio loop*.

GT001 needs "is this blocking call reachable from an ``async def``
without a thread hop?". The graph is deliberately module-local — cheap,
predictable, and conservative in the right direction: an edge only
exists when the callee is a plain call we can resolve (``foo(...)``,
``self.bar(...)``, ``cls.baz(...)``). Callables that are *passed* to
``run_in_executor`` / ``asyncio.to_thread`` / thread constructors appear
as arguments, not calls, so the thread hop falls out of the graph for
free — exactly the hand-offload idiom the serving stack uses
(``gofr_tpu/tpu/generate.py`` dispatch/fetch, batcher cold path).

Loop-scheduled callbacks are still loop context: ``loop.call_soon(fn)``,
``loop.call_later(delay, fn)`` and ``task.add_done_callback(fn)`` run
their target on the loop, so they contribute edges too.

Lambdas are treated as part of their enclosing function: the dominant
idiom here is immediate invocation (``jax.tree.map(lambda ...)``,
``sorted(key=...)``), and missing a blocking call inside one would be a
false negative on the hot path.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gofr_tpu.analysis.engine import ModuleInfo

# callback argument positions that execute on the event loop
_LOOP_CALLBACK_ARG = {
    "call_soon": 0,
    "call_soon_threadsafe": 0,
    "call_later": 1,
    "call_at": 1,
    "add_done_callback": 0,
}


class FunctionNode:
    __slots__ = ("qualname", "node", "is_async", "calls", "class_name")

    def __init__(self, qualname: str, node: ast.AST, is_async: bool,
                 class_name: Optional[str]):
        self.qualname = qualname
        self.node = node
        self.is_async = is_async
        self.class_name = class_name
        self.calls: List[Tuple[str, ast.Call]] = []  # (callee key, site)


class CallGraph:
    """Functions of one module + resolvable call edges between them."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.functions: Dict[str, FunctionNode] = {}
        self._collect(module.tree, prefix="", class_name=None)
        for node in self.functions.values():
            self._edges(node)

    # -- collection ---------------------------------------------------------
    def _collect(self, tree: ast.AST, prefix: str,
                 class_name: Optional[str]) -> None:
        for child in ast.iter_child_nodes(tree):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                self.functions[qual] = FunctionNode(
                    qual, child,
                    isinstance(child, ast.AsyncFunctionDef), class_name)
                self._collect(child, prefix=f"{qual}.<locals>.",
                              class_name=class_name)
            elif isinstance(child, ast.ClassDef):
                self._collect(child, prefix=f"{child.name}.",
                              class_name=child.name)
            else:
                self._collect(child, prefix=prefix, class_name=class_name)

    # -- body iteration: a function's own statements, lambdas inlined ------
    def body_nodes(self, fn: FunctionNode):
        """Yield every AST node executed *as part of* this function:
        descends into lambdas and comprehensions but not into nested
        ``def``s (those are separate graph nodes, only live if called)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn.node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- edges --------------------------------------------------------------
    def _resolve(self, fn: FunctionNode, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            # nearest scope first: a sibling nested def, then module level
            local = f"{fn.qualname}.<locals>.{func.id}"
            if local in self.functions:
                return local
            if func.id in self.functions:
                return func.id
        elif isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            if func.value.id in ("self", "cls") and fn.class_name:
                method = f"{fn.class_name}.{func.attr}"
                if method in self.functions:
                    return method
        return None

    def _callback_target(self, fn: FunctionNode,
                         call: ast.Call) -> Optional[str]:
        """Resolve loop-scheduled callbacks: call_soon/call_later/
        add_done_callback targets run on the loop."""
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        index = _LOOP_CALLBACK_ARG.get(func.attr)
        if index is None or len(call.args) <= index:
            return None
        target = call.args[index]
        if isinstance(target, ast.Name):
            local = f"{fn.qualname}.<locals>.{target.id}"
            if local in self.functions:
                return local
            if target.id in self.functions:
                return target.id
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id in ("self", "cls") and fn.class_name:
            method = f"{fn.class_name}.{target.attr}"
            if method in self.functions:
                return method
        return None

    def _edges(self, fn: FunctionNode) -> None:
        for node in self.body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = self._resolve(fn, node)
            if callee is not None:
                fn.calls.append((callee, node))
            callback = self._callback_target(fn, node)
            if callback is not None:
                fn.calls.append((callback, node))

    # -- reachability -------------------------------------------------------
    def loop_reachable(self) -> Dict[str, List[str]]:
        """Map of function qualname → call chain from an async root, for
        every function that executes on the event loop. Roots are all
        ``async def``s; edges never cross a thread hop (see module doc)."""
        chains: Dict[str, List[str]] = {}
        stack: List[Tuple[str, List[str]]] = [
            (name, [name]) for name, fn in self.functions.items()
            if fn.is_async]
        while stack:
            name, chain = stack.pop()
            if name in chains:
                continue
            chains[name] = chain
            for callee, _site in self.functions[name].calls:
                if callee not in chains:
                    stack.append((callee, chain + [callee]))
        return chains
