import asyncio
import json

from tests.util import make_app, run, serving


def test_publish_subscribe_roundtrip():
    async def main():
        app = make_app()
        received = asyncio.Event()
        seen = {}

        def on_order(ctx):
            seen["data"] = ctx.bind()
            seen["topic"] = ctx.request.param("topic")
            received.set()

        app.subscribe("orders", on_order)
        async with serving(app):
            app.container.pubsub.publish(
                "orders", json.dumps({"id": 7}).encode())
            await asyncio.wait_for(received.wait(), timeout=5)
        assert seen["data"] == {"id": 7}
        assert seen["topic"] == "orders"
    run(main())


def test_subscriber_panic_does_not_kill_loop():
    async def main():
        app = make_app()
        calls = []
        done = asyncio.Event()

        def flaky(ctx):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("first message explodes")
            done.set()

        app.subscribe("t", flaky)
        async with serving(app):
            app.container.pubsub.publish("t", b"1")
            app.container.pubsub.publish("t", b"2")
            await asyncio.wait_for(done.wait(), timeout=5)
        assert len(calls) == 2
    run(main())


def test_message_bind_scalars():
    from gofr_tpu.datasource.pubsub.base import Message
    msg = Message("t", b"42")
    assert msg.bind(int) == 42
    assert msg.bind(str) == "42"
    msg2 = Message("t", b'{"a": 1}')
    assert msg2.bind() == {"a": 1}
    msg3 = Message("t", b"not-json")
    assert msg3.bind() == "not-json"


def test_commit_on_success_semantics():
    from gofr_tpu.datasource.pubsub.base import Message
    committed = []
    msg = Message("t", b"x", committer=lambda: committed.append(1))
    msg.commit()
    msg.commit()
    assert committed == [1]  # idempotent
