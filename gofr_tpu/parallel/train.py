"""Sharded training step: dp × tp × sp over one jit'd update.

No reference analog (SURVEY.md §2.8 — GoFr has no training). This is the
full-scale path the driver's ``dryrun_multichip`` validates: params are
tensor-parallel (Megatron column/row specs from sharding.py), the batch is
data-parallel, the sequence axis rides ring attention, and the optimizer
state inherits param shardings. All cross-device traffic is XLA-inserted
collectives (psum for grads over dp, all-reduce in tp blocks, ppermute in
the sp ring) riding ICI.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gofr_tpu.models import llama
from gofr_tpu.parallel.sharding import (
    llama_param_specs,
    prune_specs,
    shard_pytree,
)


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def make_train_step(cfg: llama.LlamaConfig, mesh: Mesh,
                    learning_rate: float = 3e-4,
                    use_sp: bool = False,
                    remat: bool = False):
    """Returns (init_fn, step_fn).

    init_fn(key) → TrainState with params laid out tensor-parallel on the
    mesh and optimizer moments inheriting the same shardings.
    step_fn(state, tokens, targets) → (state, loss); donates state.
    ``remat`` wraps the loss in jax.checkpoint — rematerialise activations
    to trade FLOPs for HBM (the standard TPU memory lever).
    """
    optimizer = optax.adamw(learning_rate)
    param_specs = prune_specs(llama_param_specs(), mesh)
    has_sp = use_sp and "sp" in mesh.shape
    batch_sharding = NamedSharding(
        mesh, P("dp", "sp") if has_sp else P("dp"))

    # jit so moment tensors are created directly with param shardings;
    # hoisted out of init_fn so repeated inits reuse one compiled program
    jit_opt_init = jax.jit(optimizer.init)

    def init_fn(key: jax.Array) -> TrainState:
        params = shard_pytree(llama.init(cfg, key), mesh, param_specs)
        opt_state = jit_opt_init(params)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    loss = lambda p, t, y: llama.loss_fn(p, cfg, t, y,
                                         mesh=mesh if has_sp else None)
    if remat:
        loss = jax.checkpoint(loss)

    def step_fn(state: TrainState, tokens: jnp.ndarray,
                targets: jnp.ndarray) -> Tuple[TrainState, jnp.ndarray]:
        tokens = jax.lax.with_sharding_constraint(tokens, batch_sharding)
        targets = jax.lax.with_sharding_constraint(targets, batch_sharding)
        loss_val, grads = jax.value_and_grad(loss)(state.params, tokens,
                                                   targets)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss_val

    return init_fn, jax.jit(step_fn, donate_argnums=(0,))


def make_eval_step(cfg: llama.LlamaConfig, mesh: Mesh):
    """Data/tensor-parallel forward returning mean loss."""
    batch_sharding = NamedSharding(mesh, P("dp"))

    def eval_fn(params, tokens, targets):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_sharding)
        return llama.loss_fn(params, cfg, tokens, targets)

    return jax.jit(eval_fn)
