"""GT004 positive fixture: side effects and tracer branches in traced
bodies.

Parsed by graftcheck in tests, never imported (``logger`` / ``metrics``
are deliberately undefined).
"""

import functools

import jax


@jax.jit
def noisy(x):
    print("tracing", x)
    return x * 2


@functools.partial(jax.jit, static_argnums=(1,))
def branchy(x, flag):
    if x > 0:
        return x
    return -x


def _logged_step(x):
    logger.info("step %s", x)  # noqa: F821 — parse-only fixture
    return x


logged_step = jax.jit(_logged_step)


def _metered_step(x):
    metrics.increment_counter("app_fixture_steps_total")  # noqa: F821
    return x


metered_step = jax.jit(_metered_step)


@jax.jit
def scanned(xs):
    def one(carry, x):
        # nested scan-step param carries a tracer from the outer trace
        if x:
            carry = carry + x
        return carry, x
    return jax.lax.scan(one, 0, xs)
