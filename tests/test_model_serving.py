"""End-to-end model serving: HTTP handler → ctx.predict → dynamic batcher
→ executor → compiled XLA — the full north-star path (BASELINE.json) on
the CPU backend."""

import asyncio
import json

import jax.numpy as jnp
import numpy as np

from tests.util import http_request, make_app, run, serving


def _register_tiny_classifier(app):
    """A 'model': logits = x @ W, W fixed."""
    weights = {"w": jnp.eye(4, 3)}

    def fn(params, x):
        return x @ params["w"]

    app.add_model("clf", fn, params=weights, buckets=(1, 2, 4, 8))
    return app


def test_http_classify_through_batcher():
    async def main():
        app = make_app({"TPU_ENABLED": "true"})
        _register_tiny_classifier(app)

        async def classify(ctx):
            data = ctx.bind()
            example = np.asarray(data["x"], np.float32)
            logits = await ctx.predict("clf", example)
            return {"label": int(np.argmax(logits)),
                    "logits": [float(v) for v in logits]}

        app.post("/classify", classify)
        async with serving(app) as port:
            results = await asyncio.gather(*[
                http_request(
                    port, "POST", "/classify",
                    body=json.dumps(
                        {"x": [0, 0, 0, 0][:i] + [1.0]
                         + [0] * (3 - i)}).encode(),
                    headers={"Content-Type": "application/json"})
                for i in range(3)])
            labels = [r.json()["data"]["label"] for r in results]
            assert labels == [0, 1, 2]
            # batch-size histogram was recorded (coalescing happened)
            snapshot = app.container.metrics.snapshot()
            assert "app_tpu_batch_size" in snapshot
    run(main())


def test_ctx_predict_without_batcher(mock_container):
    """CLI/cron contexts: direct executor fallback."""
    from gofr_tpu.context import Context
    from gofr_tpu.tpu import Executor
    executor = Executor(mock_container.logger, mock_container.metrics)
    executor.register("double", lambda p, x: x * 2.0, {}, buckets=(1,))
    mock_container.tpu = executor
    ctx = Context(None, mock_container)
    out = asyncio.run(ctx.predict("double", np.ones((3,), np.float32)))
    np.testing.assert_allclose(out, [2.0, 2.0, 2.0])


def test_tpu_health_in_wellknown():
    async def main():
        app = make_app({"TPU_ENABLED": "true"})
        _register_tiny_classifier(app)
        async with serving(app) as port:
            health = await http_request(port, "GET", "/.well-known/health")
            body = health.json()
            assert body["tpu"]["status"] == "UP"
            assert "devices" in body["tpu"]
    run(main())
