"""HTTP Basic auth middleware.

Capability parity with ``pkg/gofr/http/middleware/basic_auth.go``
(static user map or validation callbacks, incl. container-aware validators
14-77; ``/.well-known`` bypass, validate.go:5-7).
"""

from __future__ import annotations

import base64
import hmac
import json
from typing import Callable, Dict, Optional

from gofr_tpu.http.router import Middleware, WireHandler


def _is_well_known(path: str) -> bool:
    return path.startswith("/.well-known/")


def _unauthorized():
    body = json.dumps({"error": {"message": "Unauthorized"}}).encode()
    return 401, {"Content-Type": "application/json",
                 "WWW-Authenticate": 'Basic realm="gofr-tpu"'}, body


def basic_auth_middleware(
    users: Optional[Dict[str, str]] = None,
    validate: Optional[Callable[..., bool]] = None,
    container=None,
) -> Middleware:
    """``users`` is a username→password map; ``validate`` is a callback
    ``(user, password) -> bool`` or, when a container is supplied,
    ``(container, user, password) -> bool`` (basic_auth.go:25-43)."""

    def middleware(next_handler: WireHandler) -> WireHandler:
        async def handle(request):
            if _is_well_known(request.path):
                return await next_handler(request)
            header = request.headers.get("authorization", "")
            if not header.startswith("Basic "):
                return _unauthorized()
            try:
                decoded = base64.b64decode(header[6:]).decode("utf-8")
                user, _, password = decoded.partition(":")
            except Exception:
                return _unauthorized()
            ok = False
            if validate is not None:
                ok = validate(container, user, password) if container is not None \
                    else validate(user, password)
            elif users is not None:
                expected = users.get(user)
                ok = expected is not None and hmac.compare_digest(expected, password)
            if not ok:
                return _unauthorized()
            request.context_values["auth_user"] = user
            return await next_handler(request)
        return handle
    return middleware
