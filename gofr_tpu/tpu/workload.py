"""Workload capture & replay plane (ISSUE 17).

The ROADMAP's SLO-driven auto-tuning is gated on "bench.py replaying
recorded traffic shapes as the eval harness" — which needs a traffic
recorder first. This module is that substrate, in three pieces:

- :class:`TrafficRecorder` — a bounded, **shape-only** ring of admitted
  requests. Per request it keeps: inter-arrival delta, SLO class, model
  name, prompt/output token *lengths*, the relative deadline budget, the
  cached-prefix length, and the finish reason. It never stores token
  ids, prompt strings, or request bodies — batch-geometry/latency
  tradeoffs are a function of the workload's *shape* (PAPERS.md: arxiv
  1812.11731), and shape is all a tuning harness needs. graftcheck
  GT012 (``workload-content-leak``) enforces the invariant statically.
- a versioned compact JSON **trace** (:meth:`TrafficRecorder.
  export_trace` / :func:`load_trace`): a header with legends plus one
  fixed-width numeric row per event, so a day of traffic exports to a
  few hundred KB and survives being checked into a bench artifact.
- :func:`replay_trace` — replays a trace through a live engine on a
  virtual clock: admissions happen in recorded order with scaled
  inter-arrival sleeps, every request gets a deterministic per-index
  seed and a synthesized prompt of the recorded length, and
  ``eos_id=None`` pins each completion to its recorded token count.
  Two replays of the same trace therefore produce identical
  admitted-token counts and per-class tallies (the ``digest`` field) —
  the A/B harness for any knob change.

Hook points: the engine's ``generate``/``generate_stream`` admission
(via :meth:`admit`, which parks the event on the flight-recorder
``RequestRecord``) and the dynamic batcher's enqueue (via
:meth:`note_enqueue`). The finish reason arrives for free through
``FlightRecorder.finish`` — the single funnel every terminal status
already passes through.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

TRACE_VERSION = 1
TRACE_KIND = "gofr-workload-trace"

# snapshot histogram edges: inter-arrival (seconds) and token lengths
_DT_EDGES_S = (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0)
_LEN_EDGES = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
# label-cardinality bounds for the open-keyed mixes (models arrive from
# config, classes/finishes are closed sets — the gates make the bound
# structural rather than assumed)
_MAX_KEYS = 64


class TraceVersionError(ValueError):
    """Raised by :func:`load_trace` on schema skew: a trace produced by
    a different recorder version must be rejected loudly, not replayed
    into silently-wrong tallies."""


class TrafficEvent:
    """One admitted request, shape only. ``dt_s`` is the inter-arrival
    delta against the previous admission (0 for the first). Numbers and
    short enum labels exclusively — never token content."""

    __slots__ = ("dt_s", "cls", "model", "prompt_len", "budget",
                 "output_len", "deadline_ms", "cached_prefix_len",
                 "finish")

    def __init__(self, dt_s: float = 0.0, cls: str = "standard",
                 model: str = "generate", prompt_len: int = 0,
                 budget: int = 0, output_len: int = 0,
                 deadline_ms: Optional[float] = None,
                 cached_prefix_len: int = 0,
                 finish: Optional[str] = None):
        self.dt_s = dt_s
        self.cls = cls
        self.model = model
        self.prompt_len = prompt_len
        self.budget = budget
        self.output_len = output_len
        self.deadline_ms = deadline_ms
        self.cached_prefix_len = cached_prefix_len
        self.finish = finish


def _bump(mix: Dict[str, int], key: str) -> None:
    """Cardinality-gated counter bump: an unbounded label space (a bug
    upstream) saturates into ``"_other"`` instead of growing the dict."""
    if key not in mix and len(mix) >= _MAX_KEYS:
        key = "_other"
    mix[key] = mix.get(key, 0) + 1


def _histogram(values: List[float], edges) -> Dict[str, int]:
    counts = [0] * (len(edges) + 1)
    for value in values:
        for i, edge in enumerate(edges):
            if value <= edge:
                counts[i] += 1
                break
        else:
            counts[len(edges)] += 1
    out = {f"le_{edge}": counts[i] for i, edge in enumerate(edges)}
    out["inf"] = counts[len(edges)]
    return out


class TrafficRecorder:
    """Bounded shape-only ring of admitted requests plus the batcher's
    enqueue pulse. All host bookkeeping: O(1) per admission, snapshot
    work bounded by the ring capacity. Thread-safe — admissions come
    from the serving loop, ``note_enqueue`` from the batcher, snapshots
    and exports from admin endpoints."""

    def __init__(self, capacity: int = 2048, metrics: Any = None):
        self.capacity = max(1, int(capacity))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._ring: "deque[TrafficEvent]" = deque(maxlen=self.capacity)
        self._last_arrival: Optional[float] = None
        self._admitted_total = 0
        self._finished_total = 0
        self._class_mix: Dict[str, int] = {}
        self._finish_mix: Dict[str, int] = {}
        # batcher plane: per-model enqueue counts + inter-arrival digest
        self._enqueues_total = 0
        self._enqueue_models: Dict[str, int] = {}
        self._enqueue_last: Optional[float] = None
        self._enqueue_dt: "deque[float]" = deque(maxlen=self.capacity)

    # -- engine admission hook ----------------------------------------------
    def admit(self, record: Any, cls: str,
              deadline: Optional[float] = None,
              now: Optional[float] = None) -> TrafficEvent:
        """One admitted request. ``record`` is the flight-recorder
        ``RequestRecord`` (the shape fields — model, prompt_len, budget —
        are read from it, never the content); the event is parked on
        ``record.wevent`` so ``FlightRecorder.finish`` can close it with
        the output length and terminal status."""
        now = time.monotonic() if now is None else now
        deadline_ms = None
        if deadline is not None:
            deadline_ms = max(0.0, (deadline - now) * 1000.0)
        with self._lock:
            dt = (0.0 if self._last_arrival is None
                  else max(0.0, now - self._last_arrival))
            self._last_arrival = now
            event = TrafficEvent(
                dt_s=dt, cls=cls, model=record.model,
                prompt_len=int(record.prompt_len),
                budget=int(record.budget), deadline_ms=deadline_ms)
            self._ring.append(event)
            self._admitted_total += 1
            _bump(self._class_mix, cls)
        record.wevent = event
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_tpu_workload_events_total",
                model=record.model, cls=cls)
        return event

    def finish(self, record: Any) -> None:
        """Close the admission event with the record's terminal shape:
        output length, realized cached-prefix length, finish reason.
        Called by ``FlightRecorder.finish`` — every terminal path
        (done/cancelled/error/expired) already funnels through it."""
        event = getattr(record, "wevent", None)
        if event is None:
            return
        record.wevent = None   # one-shot: replays of finish are no-ops
        with self._lock:
            event.output_len = int(record.tokens)
            event.cached_prefix_len = int(record.cached_prefix_len)
            event.finish = record.status
            self._finished_total += 1
            _bump(self._finish_mix, record.status)

    # -- batcher enqueue hook -----------------------------------------------
    def note_enqueue(self, model: str, now: Optional[float] = None) -> None:
        """One example entering the dynamic batcher — the classify-plane
        arrival pulse (model mix + inter-arrival), no per-example shape
        beyond the model name."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._enqueues_total += 1
            _bump(self._enqueue_models, model)
            if self._enqueue_last is not None:
                self._enqueue_dt.append(max(0.0, now - self._enqueue_last))
            self._enqueue_last = now

    # -- derived views -------------------------------------------------------
    def prompt_length_distribution(
            self, model: Optional[str] = None) -> Dict[int, int]:
        """Observed prompt-length counts over the ring window — the
        workload-aware weighting the xlaz suggested-ladder DP consumes
        (recent traffic shape, not lifetime bucket hits)."""
        with self._lock:
            events = list(self._ring)
        out: Dict[int, int] = {}
        for event in events:
            if model is not None and event.model != model:
                continue
            out[event.prompt_len] = out.get(event.prompt_len, 0) + 1
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/workloadz`` payload: inter-arrival and length
        histograms over the ring window, class/finish mixes, and the
        prefix-reuse rate. Work bounded by the ring capacity."""
        with self._lock:
            events = list(self._ring)
            class_mix = dict(self._class_mix)
            finish_mix = dict(self._finish_mix)
            admitted = self._admitted_total
            finished = self._finished_total
            enq_total = self._enqueues_total
            enq_models = dict(self._enqueue_models)
            enq_dt = list(self._enqueue_dt)
        prompt_lens = [e.prompt_len for e in events]
        finished_events = [e for e in events if e.finish is not None]
        output_lens = [e.output_len for e in finished_events]
        dts = [e.dt_s for e in events[1:]]
        reused = [e for e in finished_events if e.cached_prefix_len > 0]
        prompt_len_sum = sum(e.prompt_len for e in finished_events)
        cached_len_sum = sum(e.cached_prefix_len for e in finished_events)
        return {
            "capacity": self.capacity,
            "window_events": len(events),
            "admitted_total": admitted,
            "finished_total": finished,
            "class_mix": class_mix,
            "finish_mix": finish_mix,
            "interarrival_s": {
                "histogram": _histogram(dts, _DT_EDGES_S),
                "mean": (round(sum(dts) / len(dts), 6) if dts else None),
            },
            "prompt_len": {
                "histogram": _histogram(prompt_lens, _LEN_EDGES),
                "mean": (round(sum(prompt_lens) / len(prompt_lens), 2)
                         if prompt_lens else None),
            },
            "output_len": {
                "histogram": _histogram(output_lens, _LEN_EDGES),
                "mean": (round(sum(output_lens) / len(output_lens), 2)
                         if output_lens else None),
            },
            "prefix_reuse": {
                "requests_with_reuse": len(reused),
                "request_rate": (round(len(reused) / len(finished_events), 4)
                                 if finished_events else None),
                "token_rate": (round(cached_len_sum / prompt_len_sum, 4)
                               if prompt_len_sum else None),
            },
            "batcher": {
                "enqueues_total": enq_total,
                "models": enq_models,
                "interarrival_s": {
                    "histogram": _histogram(enq_dt, _DT_EDGES_S),
                    "mean": (round(sum(enq_dt) / len(enq_dt), 6)
                             if enq_dt else None),
                },
            },
        }

    # -- trace export --------------------------------------------------------
    def export_trace(self) -> Dict[str, Any]:
        """Versioned compact trace: legends in the header, one numeric
        row per event — ``[dt_s, model_idx, cls_idx, prompt_len, budget,
        output_len, deadline_ms(-1=None), cached_prefix_len,
        finish_idx(-1=in flight)]``."""
        with self._lock:
            events = list(self._ring)
        models: List[str] = []
        classes: List[str] = []
        finishes: List[str] = []

        def index(legend: List[str], value: str) -> int:
            try:
                return legend.index(value)
            except ValueError:
                legend.append(value)
                return len(legend) - 1

        rows = []
        for e in events:
            rows.append([
                round(e.dt_s, 6),
                index(models, e.model),
                index(classes, e.cls),
                e.prompt_len,
                e.budget,
                e.output_len,
                (-1 if e.deadline_ms is None
                 else round(e.deadline_ms, 3)),
                e.cached_prefix_len,
                (-1 if e.finish is None else index(finishes, e.finish)),
            ])
        return {
            "kind": TRACE_KIND,
            "version": TRACE_VERSION,
            "created_unix": time.time(),
            "models": models,
            "classes": classes,
            "finishes": finishes,
            "events": rows,
        }


class WorkloadTrace:
    """A loaded trace: validated header + decoded events."""

    __slots__ = ("version", "events")

    def __init__(self, version: int, events: List[TrafficEvent]):
        self.version = version
        self.events = events


def load_trace(data: Any) -> WorkloadTrace:
    """Decode an exported trace dict (or JSON string). Raises
    :class:`TraceVersionError` on kind/version skew — a trace from a
    different schema must never replay into plausible-looking numbers."""
    if isinstance(data, (str, bytes)):
        data = json.loads(data)
    if not isinstance(data, dict) or data.get("kind") != TRACE_KIND:
        raise TraceVersionError(
            f"not a {TRACE_KIND} payload: kind={data.get('kind')!r}"
            if isinstance(data, dict) else "trace payload is not a dict")
    version = data.get("version")
    if version != TRACE_VERSION:
        raise TraceVersionError(
            f"trace version {version!r} != supported {TRACE_VERSION}")
    models = list(data.get("models") or [])
    classes = list(data.get("classes") or [])
    finishes = list(data.get("finishes") or [])

    def legend(items: List[str], idx: int, default: str) -> Optional[str]:
        if idx < 0:
            return None
        return items[idx] if idx < len(items) else default

    events: List[TrafficEvent] = []
    for row in data.get("events") or []:
        (dt_s, model_i, cls_i, prompt_len, budget, output_len,
         deadline_ms, cached, finish_i) = row
        events.append(TrafficEvent(
            dt_s=float(dt_s),
            model=legend(models, int(model_i), "generate") or "generate",
            cls=legend(classes, int(cls_i), "standard") or "standard",
            prompt_len=int(prompt_len),
            budget=int(budget),
            output_len=int(output_len),
            deadline_ms=(None if deadline_ms is None or deadline_ms < 0
                         else float(deadline_ms)),
            cached_prefix_len=int(cached),
            finish=legend(finishes, int(finish_i), "done"),
        ))
    return WorkloadTrace(version=int(version), events=events)


# -- replay ------------------------------------------------------------------
def _synth_prompt(index: int, length: int, vocab: int, seed: int) -> List[int]:
    """Deterministic content-free prompt of the recorded length: a
    per-(seed, index) affine walk over the vocab, avoiding id 0 so a
    pad-id convention cannot collide. Same trace + seed → bit-identical
    prompts on every replay."""
    span = max(1, vocab - 1)
    base = (seed * 2654435761 + index * 1000003) & 0x7FFFFFFF
    return [(base + j * 97) % span + 1 for j in range(max(1, length))]


def _request_seed(index: int, seed: int) -> int:
    return (seed ^ (index * 0x9E3779B9)) & 0x7FFFFFFF


async def replay_trace(engine, trace: WorkloadTrace,
                       time_scale: float = 1.0,
                       seed: int = 0x5EED,
                       honor_deadlines: bool = False) -> Dict[str, Any]:
    """Replay ``trace`` through a live engine on a virtual clock.

    Admissions happen strictly in recorded order; ``time_scale`` scales
    the recorded inter-arrival deltas (1.0 = arrival-faithful, 0.0 = as
    fast as the loop admits, still ordered). Each request synthesizes a
    prompt of the recorded length, carries a deterministic per-index
    ``Sampling`` seed, decodes with ``eos_id=None``, and targets its
    recorded output length (falling back to the recorded budget for
    events that never finished) — so the admitted-token count per
    request is pinned by the trace, not by model content.

    ``honor_deadlines=False`` (default) admits every request without a
    deadline: outcomes cannot depend on host timing, which is what makes
    two replays bit-identical (the acceptance bar). Flip it on to
    reproduce deadline-class scheduling pressure at the cost of
    timing-dependent shed/expire outcomes. Per-class tallies always key
    on the *recorded* class.

    Returns ``{requests, admitted_tokens, errors, per_class, digest}``
    where ``digest`` hashes the canonical tally — two replays of the
    same trace compare equal iff their digests do."""
    from gofr_tpu.slo import set_request_deadline
    from gofr_tpu.tpu.generate import Sampling

    vocab = int(getattr(getattr(engine, "cfg", None), "vocab_size", 0)) \
        or 32000
    per_class: Dict[str, Dict[str, Any]] = {}
    totals = {"requests": 0, "admitted_tokens": 0, "errors": 0}

    def tally(cls: str) -> Dict[str, Any]:
        entry = per_class.get(cls)
        if entry is None:
            entry = per_class[cls] = {"requests": 0, "tokens": 0,
                                      "outcomes": {}}
        return entry

    async def one(index: int, event: TrafficEvent) -> None:
        prompt = _synth_prompt(index, event.prompt_len, vocab, seed)
        budget = event.output_len if event.output_len > 0 else event.budget
        budget = max(1, budget)
        if honor_deadlines and event.deadline_ms:
            set_request_deadline(event.deadline_ms)
        else:
            set_request_deadline(None)
        entry = tally(event.cls)
        entry["requests"] += 1
        totals["requests"] += 1
        try:
            tokens = await engine.generate(
                prompt, max_new_tokens=budget, eos_id=None,
                sampling=Sampling(seed=_request_seed(index, seed)))
        except Exception as exc:
            totals["errors"] += 1
            outcome = type(exc).__name__
            entry["outcomes"][outcome] = \
                entry["outcomes"].get(outcome, 0) + 1
            return
        entry["tokens"] += len(tokens)
        entry["outcomes"]["ok"] = entry["outcomes"].get("ok", 0) + 1
        totals["admitted_tokens"] += len(tokens)

    from gofr_tpu.aio import spawn_logged
    tasks = []
    for index, event in enumerate(trace.events):
        if time_scale > 0 and event.dt_s > 0 and index > 0:
            await asyncio.sleep(event.dt_s * time_scale)
        tasks.append(spawn_logged(one(index, event),
                                  name=f"replay-{index}"))
    if tasks:
        await asyncio.gather(*tasks)

    result = {
        "requests": totals["requests"],
        "admitted_tokens": totals["admitted_tokens"],
        "errors": totals["errors"],
        "per_class": {cls: per_class[cls] for cls in sorted(per_class)},
    }
    result["digest"] = hashlib.sha256(
        json.dumps(result, sort_keys=True).encode()).hexdigest()[:16]
    return result


def new_traffic_recorder(config, metrics: Any = None) \
        -> Optional[TrafficRecorder]:
    """Composition-root factory (``App.start``): ``TRAFFIC_REC_ENABLED``
    (default on) and ``TRAFFIC_REC_CAPACITY`` (ring size, default 2048;
    <= 0 disables). Returns None when disabled — every hook site treats
    a None recorder as zero-cost."""
    enabled = str((config.get("TRAFFIC_REC_ENABLED") if config else None)
                  or "true").strip().lower()
    if enabled in ("0", "false", "off", "no"):
        return None
    capacity = (config.get_int("TRAFFIC_REC_CAPACITY", 2048)
                if config else 2048)
    if capacity <= 0:
        return None
    return TrafficRecorder(capacity=capacity, metrics=metrics)
