"""CRUD scaffolding example — parity with reference
examples/using-add-rest-handlers/main.go: one dataclass registers five
REST routes (POST/GET-all/GET/PUT/DELETE /user) against the configured
SQL datasource; the table is created by a migration at boot.
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import new_app
from gofr_tpu.migration import Migration


@dataclasses.dataclass
class User:
    id: int = 0
    name: str = ""
    age: int = 0
    is_employed: bool = False


def create_table(ds):
    ds.sql.execute(
        "CREATE TABLE IF NOT EXISTS user ("
        "id INTEGER PRIMARY KEY, name TEXT, age INTEGER, "
        "is_employed BOOLEAN)")


def build_app():
    app = new_app()
    app.migrate({1: Migration(up=create_table)})
    app.add_rest_handlers(User)
    return app


if __name__ == "__main__":
    build_app().run()
