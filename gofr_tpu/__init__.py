"""gofr_tpu — a TPU-native microservice framework.

Brand-new framework with the capability surface of GoFr (the Go microservice
framework surveyed in SURVEY.md): one ``App`` wires HTTP, gRPC, CLI, cron,
websocket, and pub/sub entry points around a dependency-injection ``Container``
that owns datasources and observability — plus the TPU as a first-class
container datasource: handlers call ``ctx.tpu.predict(...)`` which dispatches
through an in-process JAX/XLA executor holding AOT-compiled models resident in
TPU HBM, with dynamic batching in front and mesh-sharded (ICI) execution for
multi-chip slices.

Reference capability map: /root/reference/pkg/gofr (gofr.go:34-52 ``App``,
context.go:12-27 ``Context``). This package is an original TPU-first design,
not a port.
"""

from gofr_tpu.app import App, new_app, new_cmd
from gofr_tpu.context import Context
from gofr_tpu.version import FRAMEWORK_VERSION

__all__ = ["App", "Context", "new_app", "new_cmd", "FRAMEWORK_VERSION"]
__version__ = FRAMEWORK_VERSION
