"""GT003 recompile hazard: jit call-site discipline, checked ahead of
deploy.

PR 3's compile ledger can *count* serve-time recompiles after they
already stalled traffic; this rule catches the classic causes at review
time. Ahead-of-time shape/staticness discipline is what makes TPU
compilation viable at all (PAPERS.md: Julia→TPU full compilation; TPU
exploration survey).

Checks:

- **jit-per-call** (``hazard=fresh-jit``): ``jax.jit(f)(x)`` inside a
  function body builds a *new* wrapper — and a new compile cache entry —
  on every invocation. Cache the jitted callable (module level, a
  factory-held dict like ``GenerationEngine._decode_fns``, or a closure
  built once).
- **unhashable static** (``hazard=unhashable-static``): a list/dict/set
  literal passed at a ``static_argnums`` position of a known-jitted
  callable raises at call time or, with tuple-coercing wrappers,
  recompiles per call.
- **shape-derived argument** (``hazard=shape-arg``): ``len(x)`` /
  ``x.shape[i]`` passed to a known-jitted callable at a *non-static*
  position. As a traced value it cannot affect shapes (so it is almost
  always intended static), and once declared static every distinct
  length compiles a fresh executable — round it to a declared bucket
  rung first (the ladder idiom in ``gofr_tpu/tpu/executor.py``).
- **raw-len device shape** (``hazard=raw-shape``): ``jnp.zeros``-family
  constructors whose shape contains a bare ``len(...)`` — an unbucketed
  dimension mints one executable per distinct request size.
- **live-count slice width** (``hazard=page-width``): a device upload
  (``jnp.asarray``/``jnp.array``/``jax.device_put``) or a known-jitted
  call whose argument is sliced to a ``len(...)``/``.shape``-derived
  bound (``table[:, :len(pages)]``). The slice width becomes an array
  dimension, so a *live count* — pages held, slots active — mints one
  executable per distinct value. Slice to a declared ladder rung
  instead (the page-gather-width idiom in
  ``GenerationEngine._table_dev``).

Known-jitted callables are resolved module-locally: names bound to
``jax.jit(...)`` and functions decorated with ``@jax.jit`` /
``@partial(jax.jit, ...)``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "arange"}


def _is_jit(module: ModuleInfo, node: ast.AST) -> Optional[ast.Call]:
    """Return the ``jax.jit(...)`` Call if ``node`` is one (including
    ``partial(jax.jit, ...)``), else None."""
    if not isinstance(node, ast.Call):
        return None
    dotted = module.dotted(node.func)
    if dotted in ("jax.jit", "jax.api.jit"):
        return node
    if dotted in ("functools.partial", "partial") and node.args:
        inner = module.dotted(node.args[0])
        if inner in ("jax.jit", "jax.api.jit"):
            return node
    return None


def _static_positions(jit_call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    nums.add(el.value)
        elif kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.add(el.value)
    return nums, names


def _shape_derived(node: ast.AST) -> Optional[str]:
    """'len(...)' / '.shape[...]' expressions, including simple arithmetic
    on them (``len(x) + 1``)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return "len(...)"
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                           "size"):
            return f".{sub.attr}"
    return None


def _sliced_by_len(node: ast.AST) -> Optional[str]:
    """A Subscript anywhere in ``node`` whose slice *bounds* are
    len()/.shape-derived — ``x[:, :len(pages)]`` — i.e. a live count
    becoming an array dimension."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Subscript):
            continue
        parts = sub.slice.elts if isinstance(sub.slice, ast.Tuple) \
            else [sub.slice]
        for part in parts:
            if not isinstance(part, ast.Slice):
                continue
            for bound in (part.lower, part.upper, part.step):
                if bound is None:
                    continue
                src = _shape_derived(bound)
                if src is not None:
                    return src
    return None


class RecompileHazardRule(Rule):
    rule_id = "GT003"
    title = "recompile-hazard"
    severity = "error"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        jitted: Dict[str, Tuple[Set[int], Set[str]]] = {}

        # pass 1: collect known-jitted names (module level and class body)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                jit_call = _is_jit(module, node.value)
                if jit_call is not None and \
                        module.enclosing_function(node) is None:
                    jitted[node.targets[0].id] = _static_positions(jit_call)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    dotted = module.dotted(deco)
                    if dotted in ("jax.jit", "jax.api.jit"):
                        jitted[node.name] = (set(), set())
                    else:
                        jit_call = _is_jit(module, deco)
                        if jit_call is not None:
                            jitted[node.name] = _static_positions(jit_call)

        # pass 2: call-site checks
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            findings.extend(self._fresh_jit(module, node))
            findings.extend(self._jitted_call(module, node, jitted))
            findings.extend(self._raw_shape(module, node))
            findings.extend(self._page_width(module, node))
        return findings

    def _fresh_jit(self, module: ModuleInfo,
                   call: ast.Call) -> Iterable[Finding]:
        """jax.jit(f)(x): the outer call's func is itself a jit call."""
        jit_call = _is_jit(module, call.func)
        if jit_call is None:
            return ()
        fn = module.enclosing_function(call)
        if fn is None:
            return ()  # module-scope immediate invoke runs once at import
        return (Finding(
            rule=self.rule_id,
            path=module.relpath,
            line=call.lineno,
            message=(
                f"recompile hazard [fresh-jit]: jax.jit(...)(...) inside "
                f"'{fn.name}' builds a new wrapper (and compile-cache "
                f"entry) every call — jit once and cache the callable"),
            severity=self.severity,
            key=f"fresh-jit in {fn.name}",
        ),)

    def _jitted_call(self, module: ModuleInfo, call: ast.Call,
                     jitted: Dict[str, Tuple[Set[int], Set[str]]]
                     ) -> Iterable[Finding]:
        if not isinstance(call.func, ast.Name) or \
                call.func.id not in jitted:
            return ()
        name = call.func.id
        static_nums, static_names = jitted[name]
        findings: List[Finding] = []
        for index, arg in enumerate(call.args):
            is_static = index in static_nums
            if is_static and isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=arg.lineno,
                    message=(
                        f"recompile hazard [unhashable-static]: argument "
                        f"{index} of jitted '{name}' is declared static "
                        f"but passed an unhashable "
                        f"{type(arg).__name__.lower()} literal — static "
                        f"args must hash (use a tuple)"),
                    severity=self.severity,
                    key=f"unhashable-static arg{index} of {name}",
                ))
            width_src = None if is_static else _sliced_by_len(arg)
            if width_src is not None:
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=arg.lineno,
                    message=(
                        f"recompile hazard [page-width]: argument {index} "
                        f"of jitted '{name}' is sliced to a "
                        f"{width_src}-derived width — the live count "
                        f"becomes an array dimension, one executable per "
                        f"distinct value; slice to a declared ladder "
                        f"rung instead"),
                    severity=self.severity,
                    key=f"page-width arg{index} of {name}",
                ))
                continue   # the precise finding; skip the generic one
            shape_src = None if is_static else _shape_derived(arg)
            if shape_src is not None:
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=arg.lineno,
                    message=(
                        f"recompile hazard [shape-arg]: {shape_src} flows "
                        f"into non-static argument {index} of jitted "
                        f"'{name}' — declare it in static_argnums and "
                        f"round to a bucket rung, or it silently becomes "
                        f"a traced scalar that cannot shape anything"),
                    severity="warning",
                    key=f"shape-arg arg{index} of {name}",
                ))
        for kw in call.keywords:
            if kw.arg in static_names and \
                    isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=kw.value.lineno,
                    message=(
                        f"recompile hazard [unhashable-static]: static "
                        f"argname '{kw.arg}' of jitted '{name}' is passed "
                        f"an unhashable literal"),
                    severity=self.severity,
                    key=f"unhashable-static {kw.arg} of {name}",
                ))
        return findings

    def _raw_shape(self, module: ModuleInfo,
                   call: ast.Call) -> Iterable[Finding]:
        dotted = module.dotted(call.func)
        if dotted is None:
            return ()
        root, _, ctor = dotted.rpartition(".")
        if ctor not in _ARRAY_CTORS or root not in (
                "jax.numpy", "jnp", "numpy.jnp"):
            return ()
        if not call.args:
            return ()
        shape = call.args[0]
        elements = shape.elts if isinstance(shape, (ast.Tuple, ast.List)) \
            else [shape]
        for element in elements:
            if isinstance(element, ast.Call) and \
                    isinstance(element.func, ast.Name) and \
                    element.func.id == "len":
                fn = module.enclosing_function(call)
                where = fn.name if fn is not None else "<module>"
                return (Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=call.lineno,
                    message=(
                        f"recompile hazard [raw-shape]: device buffer in "
                        f"'{where}' is shaped by a bare len(...) — every "
                        f"distinct length mints one executable; round up "
                        f"to a declared bucket rung first"),
                    severity=self.severity,
                    key=f"raw-shape in {where}",
                ),)
        return ()

    def _page_width(self, module: ModuleInfo,
                    call: ast.Call) -> Iterable[Finding]:
        """Device uploads sliced to a live-count width: the host->device
        copy's shape tracks ``len(pages)``-style state, so every distinct
        count both re-uploads and re-specializes whatever consumes it."""
        dotted = module.dotted(call.func)
        if dotted not in ("jnp.asarray", "jax.numpy.asarray", "jnp.array",
                          "jax.numpy.array", "jax.device_put"):
            return ()
        for arg in call.args:
            src = _sliced_by_len(arg)
            if src is None:
                continue
            fn = module.enclosing_function(call)
            where = fn.name if fn is not None else "<module>"
            return (Finding(
                rule=self.rule_id,
                path=module.relpath,
                line=call.lineno,
                message=(
                    f"recompile hazard [page-width]: device upload in "
                    f"'{where}' is sliced to a {src}-derived width — a "
                    f"live page/item count becomes an array dimension, "
                    f"minting one executable per distinct value; slice "
                    f"to a declared ladder rung instead"),
                severity=self.severity,
                key=f"page-width in {where}",
            ),)
        return ()
