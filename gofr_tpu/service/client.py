"""Outbound HTTP service client: the inter-service call path.

Capability parity with ``pkg/gofr/service`` (new.go:18-64 ``httpService`` +
``HTTP`` interface Get/Post/Put/Patch/Delete ×(plain, WithHeaders);
createAndSendRequest new.go:135-195: span per call, W3C inject,
``app_http_service_response`` histogram, structured request log;
health.go:18-20 ``HealthCheck`` via /.well-known/alive;
health_config.go:1-23 endpoint override).

Sync core on stdlib urllib (handlers run in a worker thread, so blocking IO
is isolated from the event loop — see handler.py); every verb also has an
``a``-prefixed async variant that offloads to the default executor.
"""

from __future__ import annotations

import asyncio
import json as jsonlib
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from gofr_tpu.trace.tracer import format_traceparent


class ServiceResponse:
    def __init__(self, status_code: int, headers: Dict[str, str],
                 body: bytes):
        self.status_code = status_code
        self.headers = headers
        self.body = body

    def json(self) -> Any:
        return jsonlib.loads(self.body.decode() or "null")

    @property
    def ok(self) -> bool:
        return 200 <= self.status_code < 300


class ServiceError(Exception):
    """Transport-level failure (connection refused, DNS, timeout)."""


class HTTPService:
    """Plain client; decorators (auth, circuit breaker, headers) wrap it —
    the reference's Options pattern (service/options.go:3-5)."""

    def __init__(self, base_url: str, logger=None, metrics=None,
                 tracer=None, timeout: float = 30.0,
                 service_name: str = ""):
        self.base_url = base_url.rstrip("/")
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self.timeout = timeout
        self.service_name = service_name or self.base_url

    # -- verb surface (new.go:26-64) ----------------------------------------
    def get(self, path: str, params: Optional[Dict] = None,
            headers: Optional[Dict] = None) -> ServiceResponse:
        return self.request("GET", path, params=params, headers=headers)

    def post(self, path: str, params: Optional[Dict] = None,
             body: Any = None, headers: Optional[Dict] = None):
        return self.request("POST", path, params=params, body=body,
                            headers=headers)

    def put(self, path: str, params: Optional[Dict] = None, body: Any = None,
            headers: Optional[Dict] = None):
        return self.request("PUT", path, params=params, body=body,
                            headers=headers)

    def patch(self, path: str, params: Optional[Dict] = None,
              body: Any = None, headers: Optional[Dict] = None):
        return self.request("PATCH", path, params=params, body=body,
                            headers=headers)

    def delete(self, path: str, body: Any = None,
               headers: Optional[Dict] = None):
        return self.request("DELETE", path, body=body, headers=headers)

    # async variants (offloaded; event loop never blocks)
    async def aget(self, path: str, params=None, headers=None):
        return await self._offload(self.get, path, params, headers)

    async def apost(self, path: str, params=None, body=None, headers=None):
        return await self._offload(self.post, path, params, body, headers)

    async def aput(self, path: str, params=None, body=None, headers=None):
        return await self._offload(self.put, path, params, body, headers)

    async def apatch(self, path: str, params=None, body=None, headers=None):
        return await self._offload(self.patch, path, params, body, headers)

    async def adelete(self, path: str, body=None, headers=None):
        return await self._offload(self.delete, path, body, headers)

    async def _offload(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    # -- the single send path (new.go:135-195) ------------------------------
    def request(self, method: str, path: str, params: Optional[Dict] = None,
                body: Any = None,
                headers: Optional[Dict] = None) -> ServiceResponse:
        url = f"{self.base_url}/{path.lstrip('/')}" if path else self.base_url
        if params:
            url += ("&" if "?" in url else "?") + urllib.parse.urlencode(
                params, doseq=True)
        send_headers = dict(headers or {})
        data: Optional[bytes] = None
        if body is not None:
            if isinstance(body, (bytes, bytearray)):
                data = bytes(body)
            else:
                data = jsonlib.dumps(body).encode()
                send_headers.setdefault("Content-Type", "application/json")

        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(
                f"http-service {method} {self.service_name}")
            span.set_attribute("http.url", url)
            send_headers.setdefault("traceparent", format_traceparent(span))

        start = time.perf_counter()
        try:
            request = urllib.request.Request(url, data=data, method=method,
                                             headers=send_headers)
            try:
                with urllib.request.urlopen(request,
                                            timeout=self.timeout) as resp:
                    response = ServiceResponse(resp.status,
                                               dict(resp.headers),
                                               resp.read())
            except urllib.error.HTTPError as exc:  # non-2xx still a response
                response = ServiceResponse(exc.code, dict(exc.headers or {}),
                                           exc.read())
        except Exception as exc:
            elapsed = time.perf_counter() - start
            self._observe(method, url, None, elapsed)
            if span is not None:
                span.set_status("ERROR")
                span.finish()
            raise ServiceError(f"{method} {url}: {exc}") from exc

        elapsed = time.perf_counter() - start
        self._observe(method, url, response.status_code, elapsed)
        if span is not None:
            span.set_attribute("http.status_code", response.status_code)
            span.finish()
        return response

    def _observe(self, method: str, url: str, status: Optional[int],
                 elapsed: float) -> None:
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_http_service_response", elapsed, service=self.service_name,
                method=method, status=str(status or "error"))
        if self.logger is not None:
            log = self.logger.error if (status is None or status >= 500) \
                else self.logger.info
            log("HTTP %s %s -> %s in %.1fms", method, url,
                status if status is not None else "ERR", elapsed * 1e3,
                service=self.service_name)

    # -- health (service/health.go, health_config.go) -----------------------
    health_endpoint = ".well-known/alive"

    def health_check(self) -> Dict[str, Any]:
        try:
            response = self.get(self.health_endpoint)
            status = "UP" if response.ok else "DOWN"
            return {"status": status,
                    "details": {"host": self.base_url,
                                "code": response.status_code}}
        except Exception as exc:
            return {"status": "DOWN",
                    "details": {"host": self.base_url, "error": repr(exc)}}
