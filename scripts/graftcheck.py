#!/usr/bin/env python3
"""graftcheck launcher — identical to ``python -m gofr_tpu.analysis``,
for environments where the package is not on sys.path. All flags pass
through; see docs/references/static-analysis.md for the rule catalog."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from gofr_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
