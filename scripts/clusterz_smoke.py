#!/usr/bin/env python
"""Tier-1 fleet-observability smoke: clusterz rollup + trace stitching.

Builds the same two-role in-proc cluster as disagg_smoke.py (dense
prefill replica, paged decode replica) plus one replica behind an open
circuit, drives one request through the DisaggRouter, then asserts the
ISSUE 10 observability surfaces built on top of it:

1. ``DisaggRouter.trace`` (the ``/debug/tracez/{trace_id}`` builder)
   returns ONE stitched timeline whose phases — prefill, kv_transfer,
   handoff_gap, decode — sum to within 10% of the observed end-to-end
   latency, with the handoff gap appearing exactly once.
2. ``build_clusterz`` reports both live replicas with role rollups and
   marks the circuit-open replica stale instead of failing the page.
3. ``build_hbmz`` attributes device memory with an unattributed
   residual below 10% of bytes-in-use (when the backend reports memory
   stats at all; host CPU may not).

Prints ``clusterz smoke: OK`` and exits 0, or raises with the failing
property. Budget: a few seconds on 8 host CPU devices.
"""

import asyncio
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


class _OpenCircuit:
    """A replica whose circuit breaker is open: clusterz must mark it
    stale WITHOUT probing (observe() here raising is the proof)."""

    kind = "http"

    def available(self):
        return False

    async def observe(self):
        raise AssertionError("clusterz probed a circuit-open replica")


def main() -> None:
    import jax

    from gofr_tpu.clusterz import build_clusterz
    from gofr_tpu.container import new_mock_container
    from gofr_tpu.hbmz import build_hbmz
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.cluster import (ClusterRegistry, DisaggRouter,
                                      InProcTransport)
    from gofr_tpu.tpu.generate import GenerationEngine

    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    container = new_mock_container()

    def build(paged):
        kwargs = dict(paged_kv=True) if paged else {}
        return GenerationEngine(cfg, params, max_slots=2, max_len=32,
                                prompt_buckets=(8,), kv_page=4,
                                logger=container.logger,
                                metrics=container.metrics, **kwargs)

    async def run() -> None:
        prefill_eng, decode_eng = build(False), build(True)
        container.tpu = decode_eng
        cluster = ClusterRegistry(logger=container.logger,
                                  metrics=container.metrics)
        cluster.register("p0", "prefill", InProcTransport(prefill_eng))
        cluster.register("d0", "decode", InProcTransport(decode_eng))
        cluster.register("z9", "decode", _OpenCircuit())
        router = DisaggRouter(cluster, metrics=container.metrics)
        await decode_eng.start()
        try:
            stream = await router.generate_stream(
                [1, 2, 3, 4, 5], max_new_tokens=6)
            tokens = []
            async for token in stream:
                tokens.append(token)
            assert tokens, "disagg request produced no tokens"
            trace_id = stream.trace_id
            assert trace_id, "relay stream carries no trace_id"

            # 1. stitched timeline --------------------------------------
            timeline = await router.trace(trace_id)
            assert timeline is not None, f"no stitch for {trace_id}"
            assert timeline["stitched"], timeline
            names = [p["name"] for p in timeline["phases"]]
            assert names.count("handoff_gap") == 1, names
            for want in ("prefill", "kv_transfer", "decode"):
                assert names.count(want) == 1, names
            e2e = timeline["e2e_s"]
            total = sum(p["duration_s"] for p in timeline["phases"])
            assert e2e > 0, timeline
            assert abs(total - e2e) <= 0.10 * e2e, \
                f"phases sum {total:.6f}s vs e2e {e2e:.6f}s (>10% apart)"

            # 2. clusterz rollup ----------------------------------------
            page = await build_clusterz(cluster, router=router)
            reps = page["replicas"]
            assert set(reps) == {"p0", "d0", "z9"}, sorted(reps)
            assert not reps["p0"]["stale"], reps["p0"]
            assert not reps["d0"]["stale"], reps["d0"]
            assert reps["z9"]["stale"], reps["z9"]
            assert "circuit" in reps["z9"]["stale_reason"], reps["z9"]
            roles = page["roles"]
            assert roles["prefill"]["replicas"] == ["p0"], roles
            assert roles["decode"]["replicas"] == ["d0", "z9"], roles
            assert roles["decode"]["stale"] == ["z9"], roles
            assert page["router"]["requests"] == 1, page["router"]
            assert page["router"]["stitched_traces"] >= 1, page["router"]

            # 3. hbmz attribution ---------------------------------------
            report = build_hbmz(container)
            assert report["attributed_bytes"] > 0, report
            in_use = report.get("device_bytes_in_use")
            if in_use:
                residual = report["unattributed_bytes"]
                assert residual < 0.10 * in_use, \
                    f"unattributed {residual} >= 10% of in-use {in_use}"
        finally:
            await decode_eng.stop()

    asyncio.run(run())
    print("clusterz smoke: OK")


if __name__ == "__main__":
    main()
