import json
from dataclasses import dataclass

import pytest

from gofr_tpu.http.errors import EntityNotFound, InvalidParam, MissingParam
from gofr_tpu.http.request import Request
from gofr_tpu.http.responder import Responder
from gofr_tpu.http.response import FileResponse, Raw, Redirect, Response
from gofr_tpu.http.router import Router


async def _h(_req):
    return 200, {}, b"ok"


def test_router_exact_and_params():
    router = Router()
    router.add("GET", "/users/{id}/posts/{pid}", _h)
    router.add("GET", "/health", _h)
    handler, params, _, _ = router.lookup("GET", "/users/7/posts/9")
    assert handler is not None
    assert params == {"id": "7", "pid": "9"}
    handler, params, _, _ = router.lookup("GET", "/health")
    assert handler is not None and params == {}
    handler, _, other, _ = router.lookup("POST", "/health")
    assert handler is None and other is True
    handler, _, other, _ = router.lookup("GET", "/nope")
    assert handler is None and other is False


def test_router_methods_for():
    router = Router()
    router.add("GET", "/x", _h)
    router.add("POST", "/x", _h)
    assert router.methods_for("/x") == ["GET", "POST"]


def test_request_query_params():
    req = Request(query="a=1&a=2&b=x&empty=")
    assert req.param("a") == "1"
    assert req.params("a") == ["1", "2"]
    assert req.param("b") == "x"
    assert req.param("missing") == ""


def test_request_bind_json_dataclass():
    @dataclass
    class Person:
        name: str = ""
        age: int = 0

    req = Request(method="POST", body=json.dumps({"name": "ada", "age": 3}).encode(),
                  headers={"content-type": "application/json"})
    person = req.bind(Person)
    assert person.name == "ada" and person.age == 3
    raw = req.bind()
    assert raw == {"name": "ada", "age": 3}


def test_request_bind_bad_json():
    req = Request(body=b"{nope", headers={"content-type": "application/json"})
    with pytest.raises(InvalidParam):
        req.bind()


def test_request_bind_form():
    req = Request(body=b"a=1&b=hello+world",
                  headers={"content-type": "application/x-www-form-urlencoded"})
    assert req.bind() == {"a": "1", "b": "hello world"}


def test_request_bind_multipart():
    boundary = "XXBOUND"
    body = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="field1"\r\n\r\n'
        "value1\r\n"
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="file1"; filename="a.txt"\r\n'
        "Content-Type: text/plain\r\n\r\n"
        "file-bytes\r\n"
        f"--{boundary}--\r\n"
    ).encode()
    req = Request(body=body, headers={
        "content-type": f"multipart/form-data; boundary={boundary}"})
    data = req.bind()
    assert data["field1"] == "value1"
    assert data["file1"].filename == "a.txt"
    assert data["file1"].content == b"file-bytes"


def test_host_name_forwarded_proto():
    req = Request(headers={"host": "api.example.com", "x-forwarded-proto": "https"})
    assert req.host_name() == "https://api.example.com"


def test_responder_envelope_and_status():
    responder = Responder()
    status, headers, body = responder.respond({"k": "v"}, None, "GET")
    assert status == 200
    assert json.loads(body) == {"data": {"k": "v"}}
    status, _, _ = responder.respond({"k": "v"}, None, "POST")
    assert status == 201
    status, _, body = responder.respond(None, None, "DELETE")
    assert status == 204 and body == b""


def test_responder_error_mapping():
    responder = Responder()
    status, _, body = responder.respond(None, EntityNotFound("id", "7"), "GET")
    assert status == 404
    assert "No entity found" in json.loads(body)["error"]["message"]
    status, _, _ = responder.respond(None, MissingParam(["x"]), "GET")
    assert status == 400
    status, _, _ = responder.respond(None, RuntimeError("boom"), "GET")
    assert status == 500


def test_responder_raw_file_redirect_response():
    responder = Responder()
    status, _, body = responder.respond(Raw([1, 2]), None, "GET")
    assert status == 200 and json.loads(body) == [1, 2]
    status, headers, body = responder.respond(
        FileResponse(b"PNG", "image/png"), None, "GET")
    assert headers["Content-Type"] == "image/png" and body == b"PNG"
    status, headers, _ = responder.respond(Redirect("/there"), None, "GET")
    assert status == 302 and headers["Location"] == "/there"
    status, headers, body = responder.respond(
        Response(data={"a": 1}, status_code=418, headers={"X-Tea": "pot"}),
        None, "GET")
    assert status == 418 and headers["X-Tea"] == "pot"


def test_static_files(tmp_path):
    (tmp_path / "index.html").write_text("<html>hi</html>")
    (tmp_path / "secret.txt").write_text("s")
    router = Router()
    router.add_static_files("/static", str(tmp_path))
    handler, _, _, _ = router.lookup("GET", "/static/index.html")
    assert handler is not None
    handler, _, _, _ = router.lookup("GET", "/static/../secret.txt")
    # traversal outside the dir is refused (resolves within tmp_path here,
    # so check a genuinely outside path)
    handler_out, _, _, _ = router.lookup("GET", "/static/../../etc/passwd")
    assert handler_out is None
