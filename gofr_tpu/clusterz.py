"""Fleet-wide rollup + cross-replica trace stitching: ``/debug/clusterz``
and ``/debug/tracez/{trace_id}``.

ISSUE 10's tentpole view. ``statusz``/``varz`` describe ONE replica; a
disaggregated fleet (ISSUE 8) needs one page that answers "is the fleet
healthy and where is the hot replica" and one endpoint that reassembles a
request whose flight records live split across a prefill and a decode
replica.

- :func:`build_clusterz` fans out over the :class:`ClusterRegistry`'s
  replicas through their existing transports (InProc probes are plain
  snapshots; HTTP probes ride the circuit-breaker-wrapped service
  client). A replica whose circuit is open is never probed — it is
  marked ``stale`` with the reason, and the page still renders. Probe
  failures likewise degrade to stale entries instead of failing the
  whole page: a half-blind fleet view beats a 500.
- :func:`build_tracez` asks the :class:`DisaggRouter` to stitch the
  end-to-end timeline for one ``trace_id`` (prefill → kv_transfer →
  handoff_gap → decode); when the router has no stitch entry (or
  ``?local=1``) it falls back to this process's own flight records, so
  a replica can always answer for its local half.

Both builders are app-independent — ``bench.py``, the smoke scripts, and
tests call them without an App; ``enable_clusterz``/``enable_tracez``
are the thin HTTP bindings.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from gofr_tpu.tpu.registry import STATE_DRAINING

__all__ = ["build_clusterz", "build_tracez", "enable_clusterz",
           "enable_tracez"]


def _extract_view(observation: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize one replica's probe result into the rollup fields. An
    InProc probe carries ``stats``/``slo`` directly; an HTTP probe
    carries the peer's whole statusz page."""
    view: Dict[str, Any] = {"goodput_tokens_per_s": None,
                            "pool_occupancy": None,
                            "active_slots": None,
                            "queue_depth": None,
                            "watchdog": None,
                            "device_seconds": None,
                            "max_burn_rate": None,
                            "min_budget_remaining": None,
                            "burning": None}
    statusz = observation.get("statusz") or {}
    slo = observation.get("slo") or statusz.get("slo") or {}
    window = slo.get("60s") or {}
    if "goodput_tokens_per_s" in window:
        view["goodput_tokens_per_s"] = window["goodput_tokens_per_s"]
    stats = observation.get("stats") or {}
    engine = statusz.get("engine") or {}
    kv_pool = stats.get("kv_pool") or engine.get("kv_cache") or {}
    if "occupancy" in kv_pool:
        view["pool_occupancy"] = kv_pool["occupancy"]
    for key in ("active_slots", "queue_depth"):
        if key in stats:
            view[key] = stats[key]
        elif key in engine:
            view[key] = engine[key]
    if statusz.get("watchdog"):
        view["watchdog"] = {
            "state": statusz["watchdog"].get("state"),
            "reason": statusz["watchdog"].get("reason"),
        }
    if stats.get("device_seconds"):
        view["device_seconds"] = stats["device_seconds"]
    # error-budget burn rollup (ISSUE 18): the replica's statusz already
    # carries its /debug/sloz evaluation — lift the worst burn, the
    # tightest remaining budget, and any burning verdicts into the fleet
    # view so the hot replica is findable without N per-replica fetches
    budget = (observation.get("slo_budget")
              or statusz.get("slo_budget") or {})
    entries = budget.get("budgets") or []
    burns = [b for entry in entries
             for b in (entry.get("burn") or {}).values() if b is not None]
    if burns:
        view["max_burn_rate"] = round(max(burns), 3)
    remaining = [entry["budget_remaining"] for entry in entries
                 if entry.get("budget_remaining") is not None]
    if remaining:
        view["min_budget_remaining"] = min(remaining)
    if budget.get("burning"):
        view["burning"] = list(budget["burning"])
    return view


async def build_clusterz(cluster, router=None,
                         watchdog=None) -> Dict[str, Any]:
    """One fleet snapshot: per-replica health/rollup fields, per-role
    aggregates, and the router's KV-transfer quantiles. Never raises on
    an unreachable replica — it renders ``stale`` instead."""
    replicas: Dict[str, Any] = {}
    for name in cluster.replicas():
        replica = cluster._replicas[name]
        info: Dict[str, Any] = {
            "role": replica.role,
            "state": replica.state,
            "inflight": replica.inflight,
            "requests": replica.requests,
            "transport": getattr(replica.transport, "kind", "?"),
            "stale": False,
        }
        if replica.state == STATE_DRAINING:
            info["drain"] = {"inflight": replica.inflight,
                             "drained": replica.inflight == 0}
        if not replica.transport.available():
            info["stale"] = True
            info["stale_reason"] = "circuit open"
            replicas[name] = info
            continue
        observe = getattr(replica.transport, "observe", None)
        if observe is None:
            info["stale"] = True
            info["stale_reason"] = "transport has no observe()"
            replicas[name] = info
            continue
        try:
            observation = await observe()
        except Exception as exc:
            info["stale"] = True
            info["stale_reason"] = repr(exc)
            replicas[name] = info
            continue
        info["health"] = observation.get("health", "UNKNOWN")
        info.update(_extract_view(observation))
        replicas[name] = info

    roles: Dict[str, Any] = {}
    for role, names in cluster.roles().items():
        fresh = [replicas[n] for n in names if not replicas[n]["stale"]]
        goodput = [r["goodput_tokens_per_s"] for r in fresh
                   if r.get("goodput_tokens_per_s") is not None]
        occupancy = [r["pool_occupancy"] for r in fresh
                     if r.get("pool_occupancy") is not None]
        burn = [r["max_burn_rate"] for r in fresh
                if r.get("max_burn_rate") is not None]
        burning = [n for n in names
                   if not replicas[n]["stale"] and replicas[n].get("burning")]
        roles[role] = {
            "replicas": names,
            "stale": [n for n in names if replicas[n]["stale"]],
            "draining": [n for n in names
                         if replicas[n]["state"] == STATE_DRAINING],
            "goodput_tokens_per_s": (round(sum(goodput), 3)
                                     if goodput else None),
            "max_pool_occupancy": (max(occupancy) if occupancy else None),
            # worst burn across the role's fresh replicas + which
            # replicas have a burning budget pair right now (ISSUE 18)
            "max_burn_rate": (max(burn) if burn else None),
            "burning": burning,
        }

    out: Dict[str, Any] = {
        "at": time.time(),
        "replicas": replicas,
        "roles": roles,
    }
    if router is not None:
        out["router"] = {
            "requests": router._requests,
            "bytes_shipped": router._bytes_shipped,
            "kv_transfer_quantiles": router.transfer_quantiles(),
            "stitched_traces": len(router._stitches),
        }
        # fleet router (tpu/fleet.py): routing split, migrations, and
        # prefix-index coverage ride the same rollup page
        fleet_stats = getattr(router, "fleet_stats", None)
        if fleet_stats is not None:
            out["fleet"] = fleet_stats()
            autoscaler = getattr(router, "autoscaler", None)
            if autoscaler is not None:
                out["fleet"]["autoscaler"] = autoscaler.status()
            # fleet series rollup (ISSUE 16): the cursor-pulled window
            # means the autoscaler acts on, next to the decision log
            rollup = getattr(router, "rollup", None)
            if rollup is not None:
                out["fleet"]["telemetry"] = rollup.statusz()
    if watchdog is not None:
        out["watchdog"] = watchdog.statusz()
    return out


def _local_records(container, trace_id: str) -> List[Dict[str, Any]]:
    """This process's flight records for ``trace_id`` — engine or
    registry-of-engines, whichever the container wired."""
    tpu = getattr(container, "tpu", None)
    if tpu is None:
        return []
    recorder = getattr(tpu, "recorder", None)
    if recorder is not None:
        return recorder.find(trace_id)
    entries = getattr(tpu, "_entries", None)   # ModelRegistry
    if entries is None:
        return []
    records: List[Dict[str, Any]] = []
    for entry in entries.values():
        recorder = getattr(entry.engine, "recorder", None)
        if recorder is not None:
            records.extend(recorder.find(trace_id))
    return records


async def build_tracez(container, trace_id: str,
                       local_only: bool = False) -> Dict[str, Any]:
    """The stitched timeline when the router has one, the local flight
    records otherwise. ``local_only`` is what a peer's transport asks
    for — it must NOT recurse through the router."""
    router = getattr(container, "cluster_router", None)
    if router is not None and not local_only:
        stitched = await router.trace(trace_id)
        if stitched is not None:
            return stitched
    return {"trace_id": trace_id, "stitched": False,
            "records": _local_records(container, trace_id)}


def enable_clusterz(app, prefix: str = "/debug/clusterz") -> None:
    async def clusterz(ctx):
        container = app.container
        cluster = getattr(container, "cluster", None)
        if cluster is None:
            return {"error": "no cluster registered", "replicas": {}}
        return await build_clusterz(
            cluster,
            router=getattr(container, "cluster_router", None),
            watchdog=getattr(container, "watchdog", None))

    app.get(prefix, clusterz)


def enable_tracez(app, prefix: str = "/debug/tracez") -> None:
    async def tracez(ctx):
        trace_id = ctx.path_param("trace_id")
        local_only = (ctx.param("local") or "") not in ("", "0", "false")
        return await build_tracez(app.container, trace_id,
                                  local_only=local_only)

    app.get(f"{prefix}/{{trace_id}}", tracez)
