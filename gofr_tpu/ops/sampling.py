"""On-device token sampling: temperature / top-k / top-p, per sequence.

The serving engine (gofr_tpu.tpu.generate) carries one row of sampling
state per KV-cache slot, so every request can run its own temperature,
top-k, top-p and PRNG stream while sharing the batched decode executable
with everyone else. The Go reference has no sampling surface at all
(SURVEY.md §2.7 — not an ML system); the design constraints here are
XLA's, not the reference's:

- **Static shapes**: per-row top-k values are data, not shape — the mask
  is built by ranking a full descending sort of the logits, so one
  compiled executable serves every (temperature, top_k, top_p) mix.
- **Greedy rows stay greedy**: rows with ``temperature == 0`` resolve to
  ``argmax`` inside the same program (`jnp.where` on the final choice),
  so a batch may freely mix greedy and sampled requests.
- **Per-row PRNG**: each row owns a key; callers carry the advanced keys
  forward (split-once-per-sample discipline — a consumed key is never
  reused, matching jax.random's contract).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# Rows with temperature <= 0 are greedy; this floor only guards the
# division for rows whose sampled branch is discarded anyway.
_TEMP_FLOOR = 1e-6


def sample_logits(logits: jnp.ndarray, temperature: jnp.ndarray,
                  top_k: jnp.ndarray, top_p: jnp.ndarray,
                  key: jax.Array) -> jnp.ndarray:
    """Sample one token id from a single row of logits.

    ``temperature`` scalar f32 (<=0 → greedy argmax); ``top_k`` scalar
    int32 (0 → disabled); ``top_p`` scalar f32 (>=1 → disabled); ``key``
    a PRNG key consumed by this call.
    """
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    order = jnp.argsort(-logits)                    # descending
    sorted_logits = jnp.take(logits, order)
    temp = jnp.maximum(temperature, _TEMP_FLOOR)
    scaled = sorted_logits.astype(jnp.float32) / temp

    ranks = jnp.arange(vocab, dtype=jnp.int32)
    k_eff = jnp.where(top_k > 0, top_k, vocab)
    keep_k = ranks < k_eff

    probs = jax.nn.softmax(scaled, axis=-1)
    # nucleus rule: keep the smallest prefix whose mass reaches top_p —
    # a token stays if the mass *before* it is still below the threshold,
    # so the argmax token always survives even when top_p is tiny.
    mass_before = jnp.cumsum(probs) - probs
    keep_p = mass_before < top_p

    masked = jnp.where(keep_k & keep_p, scaled, -jnp.inf)
    choice = jax.random.categorical(key, masked, axis=-1)
    sampled = jnp.take(order, choice).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def sample_batch(logits: jnp.ndarray, temperature: jnp.ndarray,
                 top_k: jnp.ndarray, top_p: jnp.ndarray,
                 keys: jax.Array) -> Tuple[jnp.ndarray, jax.Array]:
    """Sample one token per row; returns ``(tokens (B,), advanced keys)``.

    ``logits`` (B, V); per-row ``temperature``/``top_p`` f32 and ``top_k``
    int32 of shape (B,); ``keys`` (B, 2) uint32 per-row PRNG keys. Each
    row's key is split exactly once: one half is consumed by this sample,
    the other is returned for the next step, so a slot's token stream is
    a pure function of its seed regardless of how ticks are batched.
    """
    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)   # (B, 2, 2)
    use, carry = split[:, 0], split[:, 1]
    tokens = jax.vmap(sample_logits)(logits, temperature, top_k, top_p, use)
    return tokens, carry
