"""GT006 positive fixture: KV pool leaves materialized on the loop.

Parsed by graftcheck in tests, never imported.
"""

import jax
import numpy as np

from gofr_tpu.tpu import kv_wire


async def export_handler(pool):
    # sync device->host copy of a whole prompt's KV pages on the loop
    return np.asarray(pool.leaves["k"])


def _stage(engine):
    return jax.device_get(engine._pool.leaves["v"])


async def transitive(engine):
    # blocks through a plain-call hop: transitive -> _stage -> device sync
    return _stage(engine)


async def pack_inline(payload):
    # kv_wire.pack walks every leaf buffer on the calling thread
    return kv_wire.pack(payload)


async def adopt_inline(blob):
    return kv_wire.unpack(blob)


async def serialize(pool):
    # the serialization copy itself, without np.asarray
    return pool.leaves["k"].tobytes()
