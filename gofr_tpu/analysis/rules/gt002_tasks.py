"""GT002 fire-and-forget tasks: spawned coroutines whose crash vanishes.

``asyncio.ensure_future`` / ``create_task`` detaches a coroutine from the
caller; if nobody awaits the task or attaches a done-callback, an escaped
exception is only whispered to the loop's exception handler at GC time —
a dead subscriber loop or cron job looks exactly like a quiet one.

The fix shipped with this rule is :func:`gofr_tpu.aio.spawn_logged`,
which attaches a done-callback that logs the exception and increments
``app_async_task_failures_total{task=...}``.

Detection — for each ``ensure_future``/``create_task`` call site:

- result discarded (expression statement) → finding;
- result passed straight into another call (``list.append(...)``) →
  finding (stored, but still nothing observes the exception);
- result assigned to ``X`` → exempt only if the *same function* also has
  ``X.add_done_callback(...)`` or ``await X``; ``X.cancel()`` alone does
  not observe an exception raised before the cancel;
- ``await create_task(...)`` or ``return create_task(...)`` → exempt
  (the awaiter/caller observes the result).

The function-scope requirement is deliberate: "stop() awaits it later"
still loses every exception raised between start and stop, which for a
serve loop is the entire process lifetime.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

SPAWNERS = {"ensure_future", "create_task"}


def _spawn_label(module: ModuleInfo, call: ast.Call) -> Optional[str]:
    func = call.func
    dotted = module.dotted(func)
    if dotted in ("asyncio.ensure_future", "asyncio.create_task"):
        return dotted
    if isinstance(func, ast.Attribute) and func.attr in SPAWNERS:
        return func.attr
    return None


def _callee_name(call: ast.Call) -> str:
    if call.args and isinstance(call.args[0], ast.Call):
        inner = call.args[0].func
        if isinstance(inner, ast.Attribute):
            return inner.attr
        if isinstance(inner, ast.Name):
            return inner.id
    return "<coroutine>"


class FireAndForgetRule(Rule):
    rule_id = "GT002"
    title = "fire-and-forget-task"
    severity = "error"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            label = _spawn_label(module, node)
            if label is None:
                continue
            verdict = self._verdict(module, node)
            if verdict is None:
                continue
            fn = module.enclosing_function(node)
            where = fn.name if fn is not None else "<module>"
            findings.append(Finding(
                rule=self.rule_id,
                path=module.relpath,
                line=node.lineno,
                message=(
                    f"fire-and-forget task: {label}({_callee_name(node)}"
                    f"(...)) {verdict} — an escaped exception disappears "
                    f"silently; spawn with gofr_tpu.aio.spawn_logged(...) "
                    f"or add_done_callback"),
                severity=self.severity,
                key=f"{label}({_callee_name(node)}) in {where}",
            ))
        return findings

    def _verdict(self, module: ModuleInfo,
                 call: ast.Call) -> Optional[str]:
        """None = exempt; else a short description of the leak."""
        parent = module.parents.get(call)
        if isinstance(parent, (ast.Await, ast.Return)):
            return None
        if isinstance(parent, ast.Expr):
            return "drops its result"
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = parent.targets[0]
            if self._observed(module, call, target):
                return None
            return (f"is assigned to "
                    f"'{ast.unparse(target)}' but never awaited and given "
                    f"no done-callback in this function")
        if isinstance(parent, ast.Call):
            return "is passed along with no exception-handling callback"
        # starred/tuple/comprehension targets: be conservative, flag
        return "has no exception-handling done-callback"

    def _observed(self, module: ModuleInfo, call: ast.Call,
                  target: ast.AST) -> bool:
        """True if the enclosing function awaits the target or attaches a
        done-callback to it."""
        fn = module.enclosing_function(call)
        scope = fn if fn is not None else module.tree
        target_src = ast.unparse(target)
        for node in ast.walk(scope):
            if isinstance(node, ast.Await) and \
                    ast.unparse(node.value) == target_src:
                return True
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "add_done_callback" and \
                    ast.unparse(node.func.value) == target_src:
                return True
        return False
