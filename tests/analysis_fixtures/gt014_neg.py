"""GT014 negative fixture: serving-knob changes that stay inside the
guarded funnel — the apply paths themselves, self-writes inside the
owning class, constructors wiring the seed point, and callers routing
through apply_operating_point()."""


class MiniEngine:
    def __init__(self, steps_per_tick=1):
        # constructors wire the seed operating point
        self.steps_per_tick = steps_per_tick
        self.prompt_buckets = (16, 64)
        self.slots_cap = None
        self.class_weights = {"batch": 1.0}

    def apply_operating_point(self, point):
        # the sanctioned apply path validates then swaps its own state
        self.steps_per_tick = point.steps_per_tick
        self.prompt_buckets = point.prompt_buckets
        self.slots_cap = point.slots_cap
        return self.steps_per_tick

    def _retune(self, k):
        # self-writes inside the owning class are the implementation,
        # not a bypass
        self.steps_per_tick = max(1, int(k))


class MiniQueues:
    def __init__(self):
        self.class_weights = {"batch": 1.0}

    def set_weights(self, weights):
        # the admission-weights apply path
        self.class_weights = dict(weights)


def tuned_caller(engine, point):
    # callers route through the guarded path; reads stay free
    observed = engine.steps_per_tick
    engine.apply_operating_point(point)
    return observed


def unrelated_attrs(thing):
    # attribute names outside the knob set are not serving knobs
    thing.max_retries = 3
    thing.steps_total = 9
