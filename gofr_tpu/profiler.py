"""On-demand XLA profiling over HTTP.

The reference exposes no profiler (SURVEY.md §5 "no pprof endpoints");
for a TPU serving process a trace is the first diagnostic, so the
framework wires jax.profiler behind two admin routes:

  POST /debug/profiler/start {"dir": "/tmp/trace", "duration_s": 10}
  POST /debug/profiler/stop                          → stops, returns dir

The captured directory is TensorBoard/XProf-compatible. Routes are only
registered via ``app.enable_profiler()`` — never on by default.

Hardened for serving use (ISSUE 10):

- **Duration cap.** Every capture auto-stops. ``duration_s`` defaults to
  ``DEFAULT_DURATION_S`` and is clamped to ``MAX_DURATION_S`` — a
  forgotten ``stop`` on a production replica must not trace forever
  (jax.profiler buffers grow with the trace and a capture left running
  degrades serving).
- **Single flight.** One capture at a time per App; a concurrent start
  answers 200 with ``"already profiling"`` plus the running capture's
  dir and remaining budget, never a second ``start_trace`` (jax.profiler
  is process-global and double-starts raise).
- **Statusz surface.** The per-App state dict is stored as
  ``app._profiler_state``; ``profiler_status`` renders it (running /
  started_at / deadline / captures taken / last artifact dir) and
  ``statusz.build_status`` embeds it, so "is someone tracing right now,
  and where did the last trace land" is answerable without grepping
  logs.

State is per-``enable_profiler`` call (i.e. per App), not module-global:
two App instances in one process (tests, embedded apps) must not see each
other's profiling session through a shared dict. jax.profiler itself is
process-wide, so concurrent *starts* from two apps still race at the JAX
layer — but one app stopping can no longer clobber another's bookkeeping.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

DEFAULT_DURATION_S = 15.0
MAX_DURATION_S = 120.0


def profiler_status(state: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Render one App's profiler state for statusz. Safe on None (the
    app never called ``enable_profiler``)."""
    if not state:
        return {"enabled": False}
    out: Dict[str, Any] = {
        "enabled": True,
        "running": state["dir"] is not None,
        "captures": state["captures"],
        "last_artifact_dir": state["last_dir"],
    }
    if state["dir"] is not None:
        out["dir"] = state["dir"]
        out["started_at"] = state["started_at"]
        if state["deadline"] is not None:
            out["remaining_s"] = round(
                max(0.0, state["deadline"] - time.monotonic()), 3)
    return out


def enable_profiler(app, prefix: str = "/debug/profiler") -> None:
    state: Dict[str, Any] = {
        "dir": None,          # capture in progress → its artifact dir
        "started_at": None,   # wall clock, for the statusz surface
        "deadline": None,     # monotonic auto-stop point
        "timer": None,        # the auto-stop timer, cancelled on stop
        "captures": 0,
        "last_dir": None,     # most recent finished capture's artifacts
    }
    lock = threading.Lock()
    app._profiler_state = state

    def _stop_locked() -> Optional[str]:
        """Stop the running capture. Caller holds ``lock``."""
        import jax
        if state["dir"] is None:
            return None
        timer = state["timer"]
        if timer is not None:
            timer.cancel()
        trace_dir = state["dir"]
        state["dir"] = None
        state["started_at"] = None
        state["deadline"] = None
        state["timer"] = None
        state["captures"] += 1
        state["last_dir"] = trace_dir
        jax.profiler.stop_trace()
        return trace_dir

    def _auto_stop(trace_dir: str) -> None:
        # runs on the timer thread — take the same lock as start/stop so
        # a racing manual stop and the deadline can't both stop_trace
        with lock:
            if state["dir"] != trace_dir:
                return   # already stopped manually
            _stop_locked()

    def start(ctx):
        import jax
        body = ctx.bind() or {}
        trace_dir = body.get("dir") or "/tmp/gofr_tpu_trace"
        try:
            duration_s = float(body.get("duration_s") or DEFAULT_DURATION_S)
        except (TypeError, ValueError):
            duration_s = DEFAULT_DURATION_S
        duration_s = max(0.1, min(duration_s, MAX_DURATION_S))
        with lock:
            if state["dir"] is not None:
                return {"status": "already profiling",
                        "dir": state["dir"],
                        "remaining_s": round(
                            max(0.0, (state["deadline"] or 0.0)
                                - time.monotonic()), 3)}
            jax.profiler.start_trace(trace_dir)
            state["dir"] = trace_dir
            state["started_at"] = time.time()
            state["deadline"] = time.monotonic() + duration_s
            timer = threading.Timer(duration_s, _auto_stop, (trace_dir,))
            timer.daemon = True
            state["timer"] = timer
            timer.start()
        ctx.logger.info("profiler started -> %s (auto-stop in %.1fs)",
                        trace_dir, duration_s)
        return {"status": "started", "dir": trace_dir,
                "duration_s": duration_s}

    def stop(ctx):
        with lock:
            trace_dir = _stop_locked()
        if trace_dir is None:
            return {"status": "not profiling",
                    "last_artifact_dir": state["last_dir"]}
        ctx.logger.info("profiler stopped, trace in %s", trace_dir)
        return {"status": "stopped", "dir": trace_dir}

    app.post(f"{prefix}/start", start)
    app.post(f"{prefix}/stop", stop)
