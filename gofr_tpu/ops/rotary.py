"""Rotary position embeddings (RoPE) for the Llama serving path.

TPU-first details: the cos/sin tables are precomputed once per max length
(static shape, lives in HBM alongside weights) and gathered with a static
slice or integer positions — no dynamic shapes under jit. Rotation is done
in fp32 then cast back so bf16 Q/K keep precision at long context.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_table(max_len: int, head_dim: int, theta: float = 10000.0):
    """Precompute (max_len, head_dim/2) cos/sin tables in fp32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    positions = jnp.arange(max_len, dtype=jnp.float32)
    angles = jnp.outer(positions, inv_freq)          # (max_len, head_dim/2)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` of shape (batch, seq, heads, head_dim).

    ``positions`` is (batch, seq) int32 — absolute positions, so the same
    function serves prefill (0..S-1) and single-token decode (cache_len).
    """
    dtype = x.dtype
    cos_g = cos[positions][:, :, None, :]            # (B, S, 1, D/2)
    sin_g = sin[positions][:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos_g - x2 * sin_g, x2 * cos_g + x1 * sin_g], axis=-1)
    return rotated.astype(dtype)
