"""Redis datasource (parity: pkg/gofr/datasource/redis, SURVEY.md §2.4)."""

from gofr_tpu.datasource.redisx.client import (
    InMemoryRedis,
    RedisClient,
    RedisError,
    new_redis,
)

__all__ = ["InMemoryRedis", "RedisClient", "RedisError", "new_redis"]
