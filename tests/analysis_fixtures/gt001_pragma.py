"""Pragma fixture: a real GT001 violation, deliberately suppressed.

Parsed by graftcheck in tests, never imported.
"""

import time


async def handler():
    # graftcheck: ignore[GT001] — fixture: deliberate suppression with a
    # justification comment, the required form for host-side exceptions
    time.sleep(0.1)


async def inline_pragma():
    time.sleep(0.2)  # graftcheck: ignore[GT001] — fixture: same-line form
