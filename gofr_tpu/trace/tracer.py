"""Distributed tracing: spans, W3C tracecontext propagation, exporters.

Capability parity with the reference's OTel integration: per-request spans
(http/middleware/tracer.go:15-32), user spans via ``ctx.trace(name)``
(context.go:45-55), spans around cron jobs / pub-sub / SQL / outbound calls,
W3C ``traceparent`` inject on outbound requests (service/new.go:158), and a
batching span exporter (exporter.go:22-124).

Original design: a dependency-free tracer on ``contextvars`` (so spans follow
both asyncio tasks and threads), 128-bit trace ids, and pluggable exporters —
``none`` (default), ``console``, and ``zipkin`` (JSON v2 over HTTP, flushed by
a background thread). No OTel SDK in the hot path.
"""

from __future__ import annotations

import contextvars
import json
import queue
import random
import re
import threading
import time
import urllib.request
from typing import Dict, List, Optional

_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "gofr_tpu_span", default=None
)

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

# sentinel telling the export worker to flush its batch and exit
_STOP = object()


def _rand_hex(nbits: int) -> str:
    return f"{random.getrandbits(nbits):0{nbits // 4}x}"


class Span:
    """A single span; use as a context manager.

    ``with tracer.start_span("name"):`` parents subsequent spans in the same
    task/thread automatically (reference analog: otel context propagation).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "attributes", "links", "events", "_tracer", "_token",
                 "status")

    # span events are bounded so a chaos storm (one event per injected
    # fault) can never grow a span without limit
    MAX_EVENTS = 64

    def __init__(self, tracer: Optional["Tracer"], name: str,
                 trace_id: Optional[str] = None, parent_id: Optional[str] = None):
        self.name = name
        self.trace_id = trace_id or _rand_hex(128)
        self.span_id = _rand_hex(64)
        self.parent_id = parent_id
        self.start = time.time()
        self.end: Optional[float] = None
        self.attributes: Dict[str, str] = {}
        self.links: List[Dict[str, str]] = []
        self.events: List[Dict[str, object]] = []
        self.status: str = "OK"
        self._tracer = tracer
        self._token: Optional[contextvars.Token] = None

    def set_attribute(self, key: str, value) -> None:
        self.attributes[str(key)] = str(value)

    def add_link(self, other: "Span") -> None:
        """Link another span (many-to-one causality, e.g. one batched engine
        step serving several requests — OTel span-links analog)."""
        if len(self.links) < self.MAX_EVENTS:
            self.links.append({"trace_id": other.trace_id,
                               "span_id": other.span_id})

    def add_event(self, name: str, **attrs) -> None:
        """Timestamped point annotation inside the span (OTel span-events
        analog) — why a phase stalled, not just that it did. The chaos
        plane stamps fault injections here, the brownout ladder its
        level transitions; past ``MAX_EVENTS`` further events drop
        silently rather than growing the span."""
        if len(self.events) < self.MAX_EVENTS:
            self.events.append({
                "name": str(name),
                "t": time.time(),
                "attributes": {str(k): str(v) for k, v in attrs.items()},
            })

    def find_events(self, name: str) -> List[Dict[str, object]]:
        return [e for e in self.events if e["name"] == name]

    def set_status(self, status: str) -> None:
        self.status = status

    def finish(self) -> None:
        if self.end is not None:
            return
        self.end = time.time()
        if self._token is not None:
            try:
                _current.reset(self._token)
            except ValueError:
                _current.set(None)
            self._token = None
        if self._tracer is not None:
            self._tracer._export(self)

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.status = "ERROR"
            self.set_attribute("error", repr(exc))
        self.finish()


def current_span() -> Optional[Span]:
    return _current.get()


def extract_traceparent(header: Optional[str]) -> Optional[Dict[str, str]]:
    """Parse a W3C ``traceparent`` header → {trace_id, span_id} or None."""
    if not header:
        return None
    match = _TRACEPARENT_RE.match(header.strip().lower())
    if not match:
        return None
    _, trace_id, span_id, _ = match.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return {"trace_id": trace_id, "span_id": span_id}


def format_traceparent(span: Span) -> str:
    return f"00-{span.trace_id}-{span.span_id}-01"


class _Exporter:
    def export(self, spans: List[Span]) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class ListExporter(_Exporter):
    """Collects exported spans in memory — test double / flight-recorder
    introspection (``Tracer(exporter=ListExporter())``)."""

    def __init__(self):
        self.spans: List[Span] = []

    def export(self, spans: List[Span]) -> None:
        self.spans.extend(spans)

    def find(self, name: str) -> List[Span]:
        return [s for s in self.spans if s.name == name]


class _ConsoleExporter(_Exporter):
    def export(self, spans: List[Span]) -> None:
        for span in spans:
            dur_us = int(((span.end or span.start) - span.start) * 1e6)
            print(f"[trace] {span.trace_id} {span.name} {dur_us}us "
                  f"{span.status} {span.attributes}")


class _ZipkinExporter(_Exporter):
    """POST Zipkin v2 JSON spans (reference analog: exporter.go:22-91 posts
    Zipkin-ish JSON to the hosted tracer endpoint)."""

    def __init__(self, url: str, service_name: str):
        self.url = url
        self.service_name = service_name

    def export(self, spans: List[Span]) -> None:
        body = json.dumps([
            {
                "id": span.span_id,
                "traceId": span.trace_id,
                "parentId": span.parent_id,
                "name": span.name,
                "timestamp": int(span.start * 1e6),
                "duration": int(((span.end or span.start) - span.start) * 1e6),
                "localEndpoint": {"serviceName": self.service_name},
                # span events map onto Zipkin v2's first-class
                # annotations (timestamped point values)
                "annotations": [
                    {"timestamp": int(e["t"] * 1e6),
                     "value": "%s %s" % (e["name"], e["attributes"])
                     if e["attributes"] else e["name"]}
                    for e in span.events
                ],
                # Zipkin v2 has no first-class span links; encode them as a
                # tag so the linked trace ids survive into the UI
                "tags": dict(
                    span.attributes, status=span.status,
                    **({"links": ",".join(
                        f"{l['trace_id']}:{l['span_id']}"
                        for l in span.links)} if span.links else {})),
            }
            for span in spans
        ]).encode()
        req = urllib.request.Request(
            self.url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            urllib.request.urlopen(req, timeout=5).close()
        except Exception:
            pass  # tracing must never take the app down


class Tracer:
    """Span factory + batching export pipeline.

    Exporter selection mirrors the reference's ``initTracer``
    (gofr.go:277-327): TRACE_EXPORTER = none|console|zipkin, with
    TRACER_URL for zipkin.
    """

    def __init__(self, service_name: str = "gofr-tpu",
                 exporter: Optional[_Exporter] = None):
        self.service_name = service_name
        self._exporter = exporter
        self._queue: "queue.Queue[Optional[Span]]" = queue.Queue(maxsize=4096)
        self._worker: Optional[threading.Thread] = None
        if exporter is not None:
            self._worker = threading.Thread(
                target=self._run_worker, name="trace-export", daemon=True
            )
            self._worker.start()

    def start_span(self, name: str,
                   remote_parent: Optional[Dict[str, str]] = None,
                   parent: Optional[Span] = None) -> Span:
        """Start a span. Parent resolution: ``remote_parent`` (a parsed
        ``traceparent``) wins, then an explicit ``parent`` span, then the
        context-local current span. An explicit ``parent`` is how background
        tasks (batcher flushes, engine ticks) attach child spans to a
        request whose contextvar scope they never run under."""
        if remote_parent is not None:
            return Span(self, name, trace_id=remote_parent["trace_id"],
                        parent_id=remote_parent["span_id"])
        if parent is None:
            parent = current_span()
        if parent is not None:
            return Span(self, name, trace_id=parent.trace_id,
                        parent_id=parent.span_id)
        return Span(self, name)

    def _export(self, span: Span) -> None:
        if self._exporter is None:
            return
        try:
            self._queue.put_nowait(span)
        except queue.Full:
            pass

    def _run_worker(self) -> None:
        batch: List[Span] = []
        while True:
            try:
                item = self._queue.get(timeout=1.0)
            except queue.Empty:
                item = None
            stopping = item is _STOP
            span = None if stopping else item
            if span is not None:
                batch.append(span)
            if batch and (span is None or len(batch) >= 128):
                try:
                    self._exporter.export(batch)  # type: ignore[union-attr]
                # graftcheck: ignore[GT010] — a flaky exporter must not
                # kill the span worker; iterations are paced by the 1s
                # queue.get timeout above, so this cannot spin hot
                except Exception:
                    pass
                batch = []
            if stopping:
                return

    def shutdown(self, timeout: float = 5.0) -> None:
        """Drain queued spans and export the final batch before closing the
        exporter — spans finished just before shutdown must not be lost."""
        if self._exporter is None:
            return
        if self._worker is not None and self._worker.is_alive():
            try:
                self._queue.put_nowait(_STOP)
            except queue.Full:
                pass  # drained inline below
            self._worker.join(timeout=timeout)
            self._worker = None
        # anything still queued (full queue above, dead worker, or spans
        # finished while the worker was stopping) exports inline
        batch: List[Span] = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                batch.append(item)
        if batch:
            try:
                self._exporter.export(batch)
            except Exception:
                pass
        self._exporter.shutdown()


def new_tracer(config, logger=None) -> Tracer:
    """Build a tracer from config (reference: gofr.go:277-327 initTracer)."""
    name = config.get_or_default("APP_NAME", "gofr-tpu-app")
    kind = config.get_or_default("TRACE_EXPORTER", "none").lower()
    exporter: Optional[_Exporter] = None
    if kind == "console":
        exporter = _ConsoleExporter()
    elif kind in ("zipkin", "gofr"):
        url = config.get_or_default(
            "TRACER_URL", "http://localhost:9411/api/v2/spans"
        )
        exporter = _ZipkinExporter(url, name)
        if logger is not None:
            logger.info("tracing exporter %s -> %s", kind, url)
    return Tracer(service_name=name, exporter=exporter)
