"""Fused ragged paged attention (ISSUE 13): one Pallas kernel over
variable-length page tables.

The load-bearing contracts, in order:

1. TOKEN IDENTITY — the kernel's output is bit-equal to the gather
   formulation (the correctness oracle) for every fill pattern, bf16
   and int8, decode and the γ+1 verify variant, eager AND jitted. The
   oracle itself is made jit-stable by explicit ``lax.reduce_precision``
   rounding points (ops/attention._snap), which the kernel reproduces.
2. SENTINEL SKIP — sentinel / dead-tail table entries are never
   dereferenced: NaN-poisoning every unreferenced page must not perturb
   the output (the gather path merely masks *scores*, so it cannot make
   this guarantee — ``0 * NaN`` poisons V; the kernel's ``pl.when``
   block skip can, and this test pins it).
3. LADDER RETIREMENT — with ragged active the engine compiles ONE
   decode executable per (steps, sampled) family: no per-width entries
   in the ledger, gather_widths collapses to the full table width.
4. FALLBACK — unsupported geometry falls back to the gather
   formulation at call time and stays bit-identical by construction.

All tests run the kernel in Pallas interpret mode on CPU (tier-1).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.ops.attention import (check_sentinel_masked,
                                    paged_decode_attention,
                                    paged_verify_attention)
from gofr_tpu.ops.pallas import (ragged_paged_decode_attention,
                                 ragged_paged_verify_attention,
                                 ragged_supported)
from gofr_tpu.tpu.generate import GenerationEngine, Sampling
from gofr_tpu.tpu.page_pool import PagePool

NUM_PAGES, PAGE, HKV, HQ, D, P = 12, 16, 2, 4, 16, 4
SENTINEL = NUM_PAGES


def _scenario(cache_lens, g_len=1, int8=False, head_dim=D, seed=0):
    """Pool leaves + a page table covering each slot's cache_len (pages
    allocated bottom-up, page NUM_PAGES-1 deliberately never used — it
    is the kernel's clamp target for sentinel entries)."""
    keys = jax.random.split(jax.random.PRNGKey(seed), 8)
    B = len(cache_lens)
    shape = (NUM_PAGES, PAGE, HKV, head_dim)
    if int8:
        k_pages = jax.random.randint(keys[0], shape, -127, 128, jnp.int8)
        v_pages = jax.random.randint(keys[1], shape, -127, 128, jnp.int8)
        scales = dict(
            k_scale_pages=jax.random.uniform(
                keys[5], shape[:-1], jnp.float32, 0.01, 0.03),
            v_scale_pages=jax.random.uniform(
                keys[6], shape[:-1], jnp.float32, 0.01, 0.03))
    else:
        k_pages = jax.random.normal(keys[0], shape, jnp.float32) \
            .astype(jnp.bfloat16)
        v_pages = jax.random.normal(keys[1], shape, jnp.float32) \
            .astype(jnp.bfloat16)
        scales = {}
    q = jax.random.normal(keys[2], (B, g_len, HQ, head_dim),
                          jnp.float32).astype(jnp.bfloat16)
    k_new = jax.random.normal(keys[3], (B, g_len, HKV, head_dim),
                              jnp.float32).astype(jnp.bfloat16)
    v_new = jax.random.normal(keys[4], (B, g_len, HKV, head_dim),
                              jnp.float32).astype(jnp.bfloat16)
    if g_len == 1:
        k_new, v_new = k_new[:, 0], v_new[:, 0]
    table = np.full((B, P), SENTINEL, np.int32)
    nxt = 0
    for b, n in enumerate(cache_lens):
        for col in range(-(-n // PAGE)):
            table[b, col] = nxt
            nxt += 1
    assert nxt < NUM_PAGES - 1          # keep the clamp target unused
    return (q, k_pages, v_pages, jnp.asarray(table), k_new, v_new,
            jnp.asarray(cache_lens, jnp.int32)), scales, table


FILLS = [0, 5, P * PAGE, 17]            # empty / one partial / max / mixed


# -- tentpole: bit-identity with the gather oracle ---------------------------

def test_decode_identity_vs_gather_fill_patterns():
    args, _, _ = _scenario(FILLS)
    oracle = paged_decode_attention(*args)
    out = ragged_paged_decode_attention(*args)
    assert out.dtype == oracle.dtype
    assert bool((out == oracle).all())


def test_decode_identity_under_jit():
    """The oracle's rounding points are explicit (reduce_precision), so
    jit cannot fold them away: eager == jit == kernel, all four ways."""
    args, _, _ = _scenario(FILLS)
    eager = paged_decode_attention(*args)
    jitted = jax.jit(paged_decode_attention)(*args)
    ragged = jax.jit(ragged_paged_decode_attention)(*args)
    assert bool((eager == jitted).all())
    assert bool((jitted == ragged).all())


def test_decode_identity_int8_fused_dequant():
    args, scales, _ = _scenario([5, 33, 64], int8=True)
    oracle = paged_decode_attention(*args, **scales)
    out = ragged_paged_decode_attention(*args, **scales)
    assert bool((out == oracle).all())


def test_verify_identity_gamma_plus_one():
    """γ+1-token verify variant: causal among the new tokens, same
    rounding schedule — bit-equal to paged_verify_attention."""
    args, _, _ = _scenario([0, 7, 40], g_len=3)
    oracle = paged_verify_attention(*args)
    out = ragged_paged_verify_attention(*args)
    assert bool((out == oracle).all())


def test_verify_identity_int8():
    args, scales, _ = _scenario([9, 21], g_len=2, int8=True)
    oracle = paged_verify_attention(*args, **scales)
    out = ragged_paged_verify_attention(*args, **scales)
    assert bool((out == oracle).all())


# -- sentinel skip guarantee -------------------------------------------------

def test_sentinel_pages_never_dereferenced():
    """NaN-poison every page no table row references (including the
    clamp target NUM_PAGES-1): the kernel's output must not move. The
    gather oracle cannot pass this — its clamp gathers the poisoned
    page and ``0 * NaN`` rides through the V einsum — which is exactly
    why the kernel's ``pl.when`` skip is the stronger contract."""
    args, _, table = _scenario([5, 0, 37])
    clean = ragged_paged_decode_attention(*args)
    q, k_pages, v_pages, table_dev, k_new, v_new, cache_len = args
    live = set(table[table != SENTINEL].tolist())
    dead = [p for p in range(NUM_PAGES) if p not in live]
    assert NUM_PAGES - 1 in dead
    poison = np.asarray(k_pages, np.float32)
    poison[dead] = np.nan
    k_poison = jnp.asarray(poison).astype(k_pages.dtype)
    poison = np.asarray(v_pages, np.float32)
    poison[dead] = np.nan
    v_poison = jnp.asarray(poison).astype(v_pages.dtype)
    out = ragged_paged_decode_attention(
        q, k_poison, v_poison, table_dev, k_new, v_new, cache_len)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())
    assert bool((out == clean).all())


def test_check_sentinel_masked_contract():
    """The gather path's safety assertion: sentinel entries inside the
    covered prefix (live tokens + the new token) are a table-corruption
    bug, sentinel tails are fine."""
    table = np.full((2, P), SENTINEL, np.int32)
    table[0, :2] = [0, 1]
    table[1, :1] = [2]
    check_sentinel_masked(table, np.array([17, 3]), PAGE, SENTINEL)
    bad = table.copy()
    bad[0, 1] = SENTINEL                # covered by cache_len=17
    with pytest.raises(AssertionError):
        check_sentinel_masked(bad, np.array([17, 3]), PAGE, SENTINEL)


def test_pad_table_tiles_with_sentinel():
    table = np.arange(6, dtype=np.int32).reshape(2, 3)
    padded = PagePool.pad_table(table, 4, SENTINEL)
    assert padded.shape == (2, 4)
    assert (padded[:, 3] == SENTINEL).all()
    assert PagePool.pad_table(padded, 4, SENTINEL) is padded


# -- fallback ----------------------------------------------------------------

def test_fallback_on_misaligned_head_dim():
    """head_dim=12 misses the interpret-mode tiling (not a multiple of
    8): the ragged entry point must fall back to the gather formulation
    and stay bit-identical by construction."""
    assert not ragged_supported(12, HQ, HKV, PAGE, interpret=True)
    args, _, _ = _scenario([5, 33], head_dim=12)
    oracle = paged_decode_attention(*args)
    out = ragged_paged_decode_attention(*args)
    assert bool((out == oracle).all())


def test_ragged_supported_predicate():
    assert ragged_supported(16, 4, 2, 16, interpret=True)
    assert ragged_supported(128, 8, 2, 16, interpret=False)
    assert not ragged_supported(64, 8, 2, 16, interpret=False)   # hd % 128
    assert not ragged_supported(16, 5, 2, 16, interpret=True)    # hq % hkv


# -- engine integration ------------------------------------------------------

@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _make_engine(cfg, params, **kwargs):
    container = new_mock_container()
    kwargs.setdefault("max_slots", 4)
    kwargs.setdefault("max_len", 64)
    kwargs.setdefault("prompt_buckets", (8, 16))
    engine = GenerationEngine(cfg, params, logger=container.logger,
                              metrics=container.metrics, **kwargs)
    return engine, container


async def _serve(engine, prompts, budget=6, sampling=None):
    await engine.start()
    try:
        outs = []
        for prompt in prompts:
            outs.append(await asyncio.wait_for(
                engine.generate(prompt, max_new_tokens=budget,
                                sampling=sampling), 60.0))
        return outs
    finally:
        await engine.stop()


def test_engine_greedy_identity_and_ladder_retirement(setup):
    """The acceptance criterion: identical greedy streams dense vs
    gather vs ragged — and with ragged active the per-width decode
    executable class is gone (one (steps, sampled) family, gather
    width pinned at the full table width)."""
    cfg, params = setup
    prompts = [[1, 2, 3, 4, 5], list(range(1, 11)), [9, 8, 7]]

    dense = asyncio.run(_serve(_make_engine(cfg, params)[0], prompts))
    g_eng, _ = _make_engine(cfg, params, paged_kv=True, kv_page=4,
                            ragged_attn="off")
    gather = asyncio.run(_serve(g_eng, prompts))
    r_eng, container = _make_engine(cfg, params, paged_kv=True, kv_page=4,
                                    ragged_attn="on")
    ragged = asyncio.run(_serve(r_eng, prompts))
    assert gather == dense
    assert ragged == dense

    assert g_eng.attn_path == "gather" and r_eng.attn_path == "ragged"
    widths = r_eng.xlaz()["paged_kv"]["gather_widths"]
    assert widths == [r_eng.pages_per_slot]          # ladder collapsed
    assert len(g_eng.xlaz()["paged_kv"]["gather_widths"]) >= 1
    # no per-width decode executables: every key carries the same
    # (full-table) gather width
    keys = r_eng.xlaz()["paged_kv"]["decode_executables"]
    assert keys and len({k.rstrip(")").split(", ")[-1] for k in keys}) == 1
    served = container.metrics.value("app_tpu_attn_kernel_total",
                                     model=r_eng.model_name, path="ragged")
    assert served and served > 0


def test_engine_seeded_sampling_identity(setup):
    cfg, params = setup
    prompts = [[1, 2, 3, 4, 5], [7, 7, 7]]
    sampling = Sampling(temperature=0.8, top_k=20, seed=7)
    gather = asyncio.run(_serve(
        _make_engine(cfg, params, paged_kv=True, kv_page=4,
                     ragged_attn="off")[0], prompts, sampling=sampling))
    ragged = asyncio.run(_serve(
        _make_engine(cfg, params, paged_kv=True, kv_page=4,
                     ragged_attn="on")[0], prompts, sampling=sampling))
    assert ragged == gather


def test_engine_prefix_hit_and_miss_identity(setup):
    """Prefix-cache hits admit via table entries (zero-copy); decode
    over adopted pages must still match the dense reference stream."""
    cfg, params = setup
    shared = list(range(1, 9))
    prompts = [shared + [50 + i] for i in range(2)]
    prompts = prompts + prompts          # second wave hits
    ref = asyncio.run(_serve(_make_engine(cfg, params)[0], prompts))
    engine, _ = _make_engine(cfg, params, paged_kv=True, kv_page=4,
                             prefix_cache=True, ragged_attn="on")
    out = asyncio.run(_serve(engine, prompts))
    assert out == ref
    lookups = engine.stats()["prefix_cache"]["lookups"]
    assert lookups["hit"] + lookups["partial"] >= 2


def test_engine_int8_identity(setup):
    import dataclasses
    cfg, _ = setup
    cfg8 = dataclasses.replace(cfg, kv_int8=True)
    params = llama.init(cfg8, jax.random.PRNGKey(0))
    prompts = [[1, 2, 3, 4, 5], [4, 4, 8, 1]]
    gather = asyncio.run(_serve(
        _make_engine(cfg8, params, paged_kv=True, kv_page=4,
                     ragged_attn="off")[0], prompts, budget=4))
    ragged = asyncio.run(_serve(
        _make_engine(cfg8, params, paged_kv=True, kv_page=4,
                     ragged_attn="on")[0], prompts, budget=4))
    assert ragged == gather


def test_ragged_attn_knob_validation(setup):
    cfg, params = setup
    with pytest.raises(ValueError):
        _make_engine(cfg, params, ragged_attn="on")      # needs paged_kv
    with pytest.raises(ValueError):
        _make_engine(cfg, params, paged_kv=True, kv_page=4,
                     ragged_attn="sometimes")
    # auto off-TPU resolves to the gather path (interpret mode is for
    # tests that opt in with "on", not production auto-selection)
    engine, _ = _make_engine(cfg, params, paged_kv=True, kv_page=4,
                             ragged_attn="auto")
    assert engine.attn_path == "gather"
