"""Request flight recorder: bounded in-memory timeline of recent inference
requests.

Observability gap this closes (ISSUE 1): a request's trace used to end at
the HTTP middleware while its latency lived inside the continuous-batching
engine — queue wait, prefill, per-token decode, and which batches the
request rode in were invisible. The recorder keeps one compact
:class:`RequestRecord` per request (in-flight + a bounded ring of completed
ones) that ``/debug/statusz`` renders live; the batcher/engine additionally
emit real child spans (``queue.wait`` / ``prefill`` / ``decode``) and
per-step spans with links, so the same timeline is visible in a trace UI.

Everything here is plain host bookkeeping — no device syncs, O(1) per
event, bounded memory — so it is always on.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class RequestRecord:
    """Timeline of one request through the serving stack. Timestamps are
    ``time.monotonic`` (durations); ``wall_enqueued_at`` is ``time.time``
    for display. Batch participation is kept as bounded aggregates (count /
    min / max / sum), not a per-tick list — a long generation must not grow
    the record."""

    __slots__ = ("trace_id", "span_id", "model", "prompt_len", "budget",
                 "wall_enqueued_at", "enqueued_at", "admitted_at",
                 "first_token_at", "finished_at", "tokens", "status",
                 "ticks", "batch_min", "batch_max", "batch_sum",
                 "cached_prefix_len", "pages_held", "kv_transfer_s",
                 "kv_transfer_bytes", "wevent")

    def __init__(self, model: str = "generate", prompt_len: int = 0,
                 budget: int = 0, trace_id: Optional[str] = None,
                 span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.model = model
        self.prompt_len = prompt_len
        self.budget = budget
        self.wall_enqueued_at = time.time()
        self.enqueued_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.tokens = 0
        self.status = "queued"   # queued|running|done|cancelled|error
        self.ticks = 0
        self.batch_min = 0
        self.batch_max = 0
        self.batch_sum = 0
        self.cached_prefix_len = 0   # prompt tokens served from prefix KV
        self.pages_held = 0          # KV pool pages mapped (paged engine)
        # disaggregated handoff (ISSUE 8): wire cost of a migrated
        # request's KV transfer — zero for locally prefilled requests
        self.kv_transfer_s = 0.0
        self.kv_transfer_bytes = 0
        # workload capture (ISSUE 17): the TrafficRecorder admission
        # event this request belongs to, closed at finish — shape only
        self.wevent: Optional[Any] = None

    # -- event hooks (engine/batcher call these) ---------------------------
    def admitted(self) -> None:
        self.admitted_at = time.monotonic()
        self.status = "running"

    def rode_batch(self, size: int) -> None:
        self.ticks += 1
        self.batch_sum += size
        self.batch_min = size if self.ticks == 1 else min(self.batch_min, size)
        self.batch_max = max(self.batch_max, size)

    def first_token(self) -> None:
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()

    def finish(self, status: str = "done") -> None:
        if self.finished_at is None:
            self.finished_at = time.monotonic()
            self.status = status

    # -- derived metrics ----------------------------------------------------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admitted_at is None:
            return None
        return self.admitted_at - self.enqueued_at

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.enqueued_at

    @property
    def tokens_per_s(self) -> Optional[float]:
        if self.admitted_at is None or self.tokens == 0:
            return None
        end = self.finished_at or time.monotonic()
        elapsed = end - self.admitted_at
        return self.tokens / elapsed if elapsed > 0 else None

    def to_dict(self) -> Dict[str, Any]:
        def _round(value: Optional[float]) -> Optional[float]:
            return None if value is None else round(value, 6)
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "model": self.model,
            "status": self.status,
            "prompt_len": self.prompt_len,
            "cached_prefix_len": self.cached_prefix_len,
            "pages_held": self.pages_held,
            "budget": self.budget,
            "enqueued_at": self.wall_enqueued_at,
            "queue_wait_s": _round(self.queue_wait_s),
            "ttft_s": _round(self.ttft_s),
            "kv_transfer_s": (_round(self.kv_transfer_s)
                              if self.kv_transfer_bytes else None),
            "kv_transfer_bytes": self.kv_transfer_bytes or None,
            "tokens": self.tokens,
            "tokens_per_s": _round(self.tokens_per_s),
            "batch_sizes": {
                "ticks": self.ticks,
                "min": self.batch_min,
                "max": self.batch_max,
                "mean": (round(self.batch_sum / self.ticks, 2)
                         if self.ticks else None),
            },
        }


class FlightRecorder:
    """Bounded ring buffer of completed :class:`RequestRecord` plus the
    live in-flight set. Lock-guarded: events come from the serving loop,
    snapshots from the admin endpoint, and batcher fetches from worker
    threads."""

    def __init__(self, capacity: int = 256, step_capacity: int = 128):
        self.capacity = capacity
        # workload capture (ISSUE 17): finish() is the single funnel every
        # terminal status passes through, so a TrafficRecorder attached
        # here sees the finish reason for free
        self.workload: Optional[Any] = None
        # root-cause diagnosis (ISSUE 18): a WorstOffenders ring attached
        # here sees every terminal record and keeps the top-K slowest per
        # window with their diagnosis computed at finish time
        self.offenders: Optional[Any] = None
        self._lock = threading.Lock()
        self._inflight: Dict[int, RequestRecord] = {}
        self._completed: "deque[RequestRecord]" = deque(maxlen=capacity)
        # step-phase anatomy ring (ISSUE 3): one entry per device step
        # with its host_prep/enqueue/device_wait split — the per-step twin
        # of the per-request timeline above
        self._steps: "deque[Dict[str, Any]]" = deque(maxlen=step_capacity)
        self._total = 0
        self._total_steps = 0

    def start(self, record: RequestRecord) -> RequestRecord:
        with self._lock:
            self._total += 1
            self._inflight[id(record)] = record
        return record

    def finish(self, record: RequestRecord, status: str = "done") -> None:
        record.finish(status)
        with self._lock:
            if self._inflight.pop(id(record), None) is not None:
                self._completed.append(record)
        workload = self.workload
        if workload is not None:
            workload.finish(record)
        offenders = self.offenders
        if offenders is not None:
            offenders.offer(record)

    def record_step(self, model: str, bucket: int, batch: int,
                    phases: Dict[str, float]) -> None:
        """One executed device step with its phase split (seconds). Called
        by the executor's fetch — possibly on a worker thread."""
        entry = {
            "at": time.time(),
            "model": model,
            "bucket": bucket,
            "batch": batch,
            "fill": round(batch / bucket, 4) if bucket else None,
            "phases": {name: round(seconds, 6)
                       for name, seconds in phases.items()},
        }
        with self._lock:
            self._total_steps += 1
            self._steps.append(entry)

    def find(self, trace_id: str) -> List[Dict[str, Any]]:
        """All records (in-flight + completed) tagged with ``trace_id``,
        oldest first. Each dict is :meth:`RequestRecord.to_dict` plus a
        ``timing`` block of raw monotonic timestamps so a cross-replica
        stitcher can do gap math on same-clock records (ISSUE 10)."""
        with self._lock:
            records = [r for r in self._inflight.values()
                       if r.trace_id == trace_id]
            records += [r for r in self._completed if r.trace_id == trace_id]
        records.sort(key=lambda r: r.enqueued_at)
        out = []
        for r in records:
            d = r.to_dict()
            end = r.finished_at if r.finished_at is not None else time.monotonic()
            d["timing"] = {
                "enqueued_at": r.enqueued_at,
                "admitted_at": r.admitted_at,
                "first_token_at": r.first_token_at,
                "finished_at": r.finished_at,
                "duration_s": round(end - r.enqueued_at, 6),
            }
            out.append(d)
        return out

    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            inflight = [r.to_dict() for r in self._inflight.values()]
            recent = [r.to_dict() for r in self._completed]
            steps = list(self._steps)
            total_steps = self._total_steps
        if limit is not None:
            recent = recent[-limit:]
            steps = steps[-limit:]
        recent.reverse()   # newest first — the ops-facing order
        steps.reverse()
        return {"total_requests": self._total,
                "in_flight": inflight,
                "recent": recent,
                "total_steps": total_steps,
                "steps": steps}
