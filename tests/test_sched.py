"""SLO-class weighted-fair scheduling tests: deadline classification,
ClassQueues WFQ ordering, overflow shedding, queue-depth gauges."""

import asyncio

import jax
import pytest

from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.tpu.generate import GenerationEngine, Sampling
from gofr_tpu.tpu.sched import (CLASS_BATCH, CLASS_INTERACTIVE,
                                CLASS_STANDARD, DEFAULT_CLASS_WEIGHTS,
                                ClassQueues, deadline_class,
                                parse_class_weights)


# -- deadline classification -------------------------------------------------

def test_deadline_class_boundaries():
    now = 100.0
    assert deadline_class(None, now=now) == CLASS_BATCH
    assert deadline_class(now + 0.5, now=now) == CLASS_INTERACTIVE
    assert deadline_class(now + 2.0, now=now) == CLASS_INTERACTIVE
    assert deadline_class(now + 2.001, now=now) == CLASS_STANDARD
    assert deadline_class(now - 1.0, now=now) == CLASS_INTERACTIVE
    # a custom interactive budget moves the boundary
    assert deadline_class(now + 5.0, now=now,
                          interactive_budget_s=10.0) == CLASS_INTERACTIVE


def test_parse_class_weights():
    assert parse_class_weights(None) == DEFAULT_CLASS_WEIGHTS
    assert parse_class_weights("") == DEFAULT_CLASS_WEIGHTS
    weights = parse_class_weights("interactive:8,batch:0.5")
    assert weights["interactive"] == 8.0
    assert weights["batch"] == 0.5
    assert weights["standard"] == DEFAULT_CLASS_WEIGHTS["standard"]
    # malformed entries are skipped, never fatal; non-positive rejected
    weights = parse_class_weights("junk,interactive:abc,standard:-3,a:b:c")
    assert weights == DEFAULT_CLASS_WEIGHTS
    # unknown classes accepted (forward-compatible per-tenant classes)
    assert parse_class_weights("tenant-x:7")["tenant-x"] == 7.0


# -- weighted-fair queues ----------------------------------------------------

def test_wfq_drain_ratio_follows_weights():
    """With all classes backlogged, one full virtual-time round serves
    classes in proportion to their 4:2:1 weights."""
    queues = ClassQueues()
    for i in range(8):
        queues.put_nowait(("i", i), CLASS_INTERACTIVE)
        queues.put_nowait(("s", i), CLASS_STANDARD)
        queues.put_nowait(("b", i), CLASS_BATCH)
    first_round = [queues.get_nowait()[0] for _ in range(7)]
    assert sorted(first_round) == ["b", "i", "i", "i", "i", "s", "s"]
    # ...and the ratio holds until interactive's backlog of 8 drains
    more = [queues.get_nowait()[0] for _ in range(14)]
    assert more.count("i") == 4  # 8 total: weighted share until empty
    served = queues.served()
    assert served[CLASS_INTERACTIVE] == 8


def test_wfq_fifo_within_class():
    queues = ClassQueues()
    for i in range(4):
        queues.put_nowait(i, CLASS_STANDARD)
    assert [queues.get_nowait() for _ in range(4)] == [0, 1, 2, 3]


def test_wfq_idle_class_reanchors():
    """A class that went idle resumes at the current minimum virtual
    time: it neither banks credit while idle nor starts hopelessly
    behind the classes that kept running."""
    queues = ClassQueues()
    # batch runs alone for a while, building up virtual time
    for i in range(6):
        queues.put_nowait(("b", i), CLASS_BATCH)
    for _ in range(6):
        queues.get_nowait()
    # interactive arrives fresh: it must NOT get 6 weights' worth of
    # catch-up credit — but must also not be starved. With batch
    # backlogged again, interactive (re-anchored to batch's vt) wins
    # the next 4-of-5 pops by weight.
    for i in range(6):
        queues.put_nowait(("b2", i), CLASS_BATCH)
    for i in range(6):
        queues.put_nowait(("i", i), CLASS_INTERACTIVE)
    window = [queues.get_nowait()[0] for _ in range(5)]
    assert window.count("i") == 4
    assert window.count("b2") == 1


def test_wfq_empty_and_depths():
    queues = ClassQueues()
    assert queues.empty()
    with pytest.raises(IndexError):
        queues.get_nowait()
    queues.put_nowait("x", CLASS_BATCH)
    assert queues.qsize() == 1
    depths = queues.depths()
    assert depths == {CLASS_INTERACTIVE: 0, CLASS_STANDARD: 0,
                      CLASS_BATCH: 1}
    assert list(queues.drain()) == [(CLASS_BATCH, "x")]
    assert queues.empty()


# -- engine integration: shed accounting and depth gauges --------------------

@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _stub_request(engine, cls, loop):
    """A page-deferred admission entry as _admit_pending stages it."""
    flight = engine._new_flight([1, 2, 3], budget=4)
    future = loop.create_future()
    return ([1, 2, 3], 8, 4, None, Sampling(), future, None, 0.0,
            flight, cls, None)


def test_shed_overflow_strictly_within_class(setup):
    """Past the overflow cap, the deepest class sheds its own newest
    entry — other classes' entries survive untouched."""
    cfg, params = setup
    container = new_mock_container()
    engine = GenerationEngine(cfg, params, max_slots=4, max_len=64,
                              prompt_buckets=(8,), model_name="m",
                              logger=container.logger,
                              metrics=container.metrics)

    async def main():
        loop = asyncio.get_running_loop()
        engine._overflow_cap = 4
        futures = []
        # 4 batch entries (the deep class), then 1 interactive
        for _ in range(4):
            req = _stub_request(engine, CLASS_BATCH, loop)
            engine._overflow.append(req)
            futures.append((CLASS_BATCH, req[5]))
        interactive = _stub_request(engine, CLASS_INTERACTIVE, loop)
        engine._overflow.append(interactive)
        futures.append((CLASS_INTERACTIVE, interactive[5]))

        engine._shed_overflow()
        assert len(engine._overflow) == 4
        # the NEWEST batch entry was shed; interactive survived
        shed = [f for cls, f in futures if f.done()]
        assert len(shed) == 1
        assert shed[0] is futures[3][1]
        assert not interactive[5].done()
        with pytest.raises(RuntimeError, match="admission overflow"):
            shed[0].result()
        assert engine._shed_by_class == {CLASS_BATCH: 1}
        assert container.metrics.value(
            "app_tpu_sched_shed_total", model="m", cls=CLASS_BATCH) == 1.0
        # drain the remaining futures so the loop shuts down clean
        engine._fail_outstanding(RuntimeError("test teardown"))

    asyncio.run(main())


def test_queue_depth_gauges_per_class(setup):
    cfg, params = setup
    container = new_mock_container()
    engine = GenerationEngine(cfg, params, max_slots=4, max_len=64,
                              prompt_buckets=(8,), model_name="m",
                              logger=container.logger,
                              metrics=container.metrics)

    async def main():
        loop = asyncio.get_running_loop()
        engine._overflow.append(_stub_request(engine, CLASS_BATCH, loop))
        engine._pending.put_nowait(("x",), CLASS_INTERACTIVE)
        engine._set_queue_gauges()
        value = container.metrics.value
        assert value("app_tpu_admission_queue_depth",
                     model="m", cls=CLASS_INTERACTIVE) == 1.0
        assert value("app_tpu_admission_queue_depth",
                     model="m", cls=CLASS_BATCH) == 1.0
        assert value("app_tpu_admission_queue_depth",
                     model="m", cls=CLASS_STANDARD) == 0.0
        engine._fail_outstanding(RuntimeError("test teardown"))

    asyncio.run(main())


def test_engine_serves_mixed_classes_to_completion(setup):
    """Requests across classes (deadline-derived) all finish; per-class
    served counts and token accounting land in stats()."""
    from gofr_tpu.slo import set_request_deadline
    cfg, params = setup
    container = new_mock_container()
    engine = GenerationEngine(cfg, params, max_slots=2, max_len=64,
                              prompt_buckets=(8,), model_name="m",
                              logger=container.logger,
                              metrics=container.metrics)

    async def main():
        await engine.start()
        try:
            # first request compiles the executables — keep it deadline-
            # free so cold-compile time cannot expire it
            await engine.generate([9, 9], max_new_tokens=4)

            async def interactive():
                set_request_deadline(1500.0)
                try:
                    return await engine.generate([1, 2], max_new_tokens=4)
                finally:
                    set_request_deadline(None)

            outs = await asyncio.gather(
                interactive(),
                engine.generate([1, 2], max_new_tokens=4),
                engine.generate([3, 4], max_new_tokens=4))
            assert all(len(o) == 4 for o in outs)
        finally:
            await engine.stop()
        classes = engine.stats()["classes"]
        assert classes["served"].get(CLASS_INTERACTIVE, 0) >= 1
        assert classes["served"].get(CLASS_BATCH, 0) >= 3
        assert classes["weights"] == DEFAULT_CLASS_WEIGHTS
        tokens = container.metrics.value(
            "app_tpu_sched_tokens_total", model="m", cls=CLASS_BATCH)
        assert tokens is not None and tokens >= 4.0

    asyncio.run(main())
