"""gRPC transport on grpc.aio, sharing the app's event loop.

Capability parity with ``pkg/gofr/grpc`` + gofr.go:55-59 RegisterService
(newGRPCServer grpc.go:20-29 chains recovery + LoggingInterceptor; Run
31-46). Two registration styles:

- protoc: ``app.register_grpc_service(add_FooServicer_to_server, Foo())``
- dynamic JSON unary (original to this framework): no protoc needed —
  ``app.register_grpc_unary("Predict", "classify", handler)`` exposes
  ``/gofr.Predict/classify`` taking/returning JSON bytes, and the handler
  receives a normal gofr Context. This is the BERT/Llama streaming serve
  surface (BASELINE.md config 3) without codegen in the loop.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import grpc

from gofr_tpu.context import Context


class GRPCRequest:
    """Transport-agnostic Request over a JSON unary payload."""

    def __init__(self, payload: Any, service: str, method: str,
                 metadata: Dict[str, str]):
        self.payload = payload if isinstance(payload, dict) else {}
        self._raw = payload
        self.service = service
        self.method_name = method
        self.metadata = metadata

    def param(self, key: str) -> str:
        value = self.payload.get(key, "")
        return "" if value is None else str(value)

    def params(self, key: str) -> List[str]:
        value = self.payload.get(key)
        if isinstance(value, list):
            return [str(v) for v in value]
        return [str(value)] if value is not None else []

    def path_param(self, key: str) -> str:
        return self.param(key)

    def bind(self, target: Any = None) -> Any:
        if target is None:
            return self._raw
        if isinstance(self._raw, dict):
            return target(**self._raw)
        return self._raw

    def header(self, key: str) -> str:
        return self.metadata.get(key.lower(), "")

    @property
    def method(self) -> str:
        return "GRPC"

    @property
    def path(self) -> str:
        return f"/{self.service}/{self.method_name}"


class _LoggingInterceptor(grpc.aio.ServerInterceptor):
    """Per-RPC log + latency (parity: grpc/log.go:59 LoggingInterceptor)."""

    def __init__(self, logger, metrics):
        self.logger = logger
        self.metrics = metrics

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        inner = handler.unary_unary
        method = handler_call_details.method
        logger, metrics = self.logger, self.metrics

        async def wrapper(request, context):
            start = time.perf_counter()
            try:
                response = await inner(request, context)
                elapsed = time.perf_counter() - start
                logger.info("gRPC %s ok in %.2fms", method, elapsed * 1e3)
                metrics.record_histogram("app_http_service_response",
                                         elapsed, service="grpc",
                                         method=method, status="OK")
                return response
            except Exception as exc:
                logger.error("gRPC %s failed: %r", method, exc)
                raise

        return grpc.unary_unary_rpc_method_handler(
            wrapper, request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer)


class GRPCServer:
    def __init__(self, container, port: int, logger=None,
                 host: str = "0.0.0.0"):
        self.container = container
        self.port = port
        self.host = host
        self.logger = logger or container.logger
        self._dynamic: Dict[str, Dict[str, Callable]] = {}
        self._protoc: List[Tuple[Callable, Any]] = []
        self._server: Optional[grpc.aio.Server] = None
        self.bound_port: int = port

    def register(self, spec, servicer) -> None:
        if isinstance(spec, tuple) and spec and spec[0] == "dynamic":
            _, service, method = spec
            self._dynamic.setdefault(service, {})[method] = servicer
        else:
            self._protoc.append((spec, servicer))

    def _dynamic_handler(self, service: str,
                         methods: Dict[str, Callable]):
        container = self.container

        def make(method_name: str, handler: Callable):
            async def unary(request_bytes: bytes, context) -> bytes:
                try:
                    payload = json.loads(request_bytes or b"null")
                except json.JSONDecodeError:
                    await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                        "body is not valid JSON")
                metadata = {k: v for k, v in
                            (context.invocation_metadata() or [])}
                ctx = Context(GRPCRequest(payload, service, method_name,
                                          metadata), container)
                try:
                    result = handler(ctx)
                    if asyncio.iscoroutine(result):
                        result = await result
                except Exception as exc:  # panic isolation (grpc.go:23-25)
                    container.logger.error("gRPC handler panic: %r", exc)
                    await context.abort(grpc.StatusCode.INTERNAL, str(exc))
                from gofr_tpu.http.responder import _jsonable
                return json.dumps({"data": _jsonable(result)},
                                  default=str).encode()

            return grpc.unary_unary_rpc_method_handler(unary)

        handlers = {name: make(name, fn) for name, fn in methods.items()}
        return grpc.method_handlers_generic_handler(f"gofr.{service}",
                                                    handlers)

    async def start(self) -> None:
        self._server = grpc.aio.server(
            interceptors=[_LoggingInterceptor(self.logger,
                                              self.container.metrics)])
        for register_fn, servicer in self._protoc:
            register_fn(servicer, self._server)
        for service, methods in self._dynamic.items():
            self._server.add_generic_rpc_handlers(
                (self._dynamic_handler(service, methods),))
        self.bound_port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()
        self.logger.info("gRPC server listening on %s:%d", self.host,
                         self.bound_port)

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None
