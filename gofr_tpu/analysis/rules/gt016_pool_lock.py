"""GT016 shared-pool lock discipline: free-list mutation off the lock.

``PagePool`` (``gofr_tpu/tpu/page_pool.py``) is the one structure in
the serving stack that is *designed* to be touched from two threads:
the engine loop allocates/releases pages while executor threads hold
``pool.lock`` around donating dispatches that read the leaves. The
free-list and refcount tables are plain Python lists/dicts — a
mutation that races a donating dispatch corrupts page accounting
silently: double-allocated pages show up as cross-request KV bleed,
double-freed ones as HBM "leaks" the budget gauge can't explain.

Detection, project-wide:

1. **Find pool classes.** Any class whose constructor binds a
   ``threading.Lock``/``RLock`` to a ``*lock*`` attribute is a
   lock-disciplined shared structure; the attribute name is remembered
   as *the* serializing lock.
2. **Find its mutators.** Methods of that class whose body writes the
   protected tables *outside any* ``with self.<lock>:`` block: an
   assign/augassign/del through ``self.<attr>``, or a mutating method
   call (``append``/``pop``/``remove``/``clear``/…) on one, where
   ``<attr>`` names a free-list or refcount (contains ``free`` or
   ``ref``, or is ``leaves``). A *self-serializing* method — every
   protected mutation under the class's own lock, the ``PagePool``
   idiom — imposes no obligation on callers and is never a mutator.
3. **Flag unlocked mutator calls.** Every call site *outside* the pool
   class whose receiver is pool-typed (project type inference) and
   whose mutator call is not lexically inside ``with <x>.lock:`` — and
   whose enclosing function can actually be *entered* unlocked: a
   function only ever called from inside ``with pool.lock:`` blocks is
   covered by its callers (computed by a worklist over the project
   call graph, starting from functions with no callers).

The pool's own methods are exempt (the class may serialize internally
or document single-writer phases); so are call sites under any
``with *lock*:`` — the checker does not prove it is the *right* lock
(documented blind spot). Suppress a deliberate unlocked phase (e.g.
engine-loop single-writer setup before threads exist) with
``# graftcheck: ignore[GT016]`` plus a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from gofr_tpu.analysis.dataflow import dotted_path
from gofr_tpu.analysis.engine import Finding, Rule

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_MUTATING_CALLS = {
    "append", "pop", "remove", "clear", "extend", "insert", "add",
    "discard", "popitem", "setdefault", "update",
}


def _protected_attr(name: str) -> bool:
    lowered = name.lower()
    return ("free" in lowered or "ref" in lowered
            or lowered in ("leaves", "_leaves"))


def _under_lock(module, node: ast.AST) -> bool:
    """Is ``node`` lexically inside ``with <something lock-ish>:``
    (sync or async) within its own function?"""
    cursor = module.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            return False
        if isinstance(cursor, (ast.With, ast.AsyncWith)):
            for item in cursor.items:
                path = dotted_path(item.context_expr)
                if path is not None and _is_lockish(path):
                    return True
        cursor = module.parents.get(cursor)
    return False


def _is_lockish(path: str) -> bool:
    last = path.rsplit(".", 1)[-1].lower()
    return "lock" in last


class PoolLockRule(Rule):
    rule_id = "GT016"
    title = "shared-pool-lock"
    severity = "error"

    def check_project(self, project) -> Iterable[Finding]:
        pools = self._find_pools(project)
        if not pools:
            return []
        mutators = self._find_mutators(project, pools)
        unlocked = self._unlocked_reachable(project)
        findings: List[Finding] = []
        for ref in sorted(project.functions):
            findings.extend(self._check_function(
                project, ref, pools, mutators, unlocked))
        return findings

    # -- step 1: pool classes ----------------------------------------------
    def _find_pools(self, project) -> Dict[Tuple[str, str], str]:
        """ClassRef → lock attribute name."""
        pools: Dict[Tuple[str, str], str] = {}
        for cref, info in project.classes.items():
            init = info.methods.get("__init__")
            if init is None:
                continue
            fn = project.functions.get((cref[0], init))
            if fn is None:
                continue
            module = project.module_of((cref[0], init))
            for node in project.body_nodes((cref[0], init)):
                if not isinstance(node, ast.Assign) \
                        or not isinstance(node.value, ast.Call):
                    continue
                if module.dotted(node.value.func) not in _LOCK_CTORS:
                    continue
                for target in node.targets:
                    path = dotted_path(target)
                    if path and path.startswith("self.") \
                            and _is_lockish(path):
                        pools[cref] = path.split(".", 1)[1]
        return pools

    # -- step 2: mutator methods -------------------------------------------
    def _find_mutators(self, project, pools) -> Dict[
            Tuple[str, str], Set[str]]:
        """ClassRef → method names that mutate protected tables."""
        out: Dict[Tuple[str, str], Set[str]] = {}
        for cref in pools:
            info = project.classes[cref]
            for mname, mqual in info.methods.items():
                if mname == "__init__":
                    continue
                mref = (cref[0], mqual)
                if self._mutates_protected(project, mref):
                    out.setdefault(cref, set()).add(mname)
        return out

    @staticmethod
    def _mutates_protected(project, mref) -> bool:
        """True when the method mutates a protected table *outside* a
        ``with *lock*:`` block — a self-serializing method (all
        mutations internally locked) imposes nothing on callers."""
        module = project.module_of(mref)
        for node in project.body_nodes(mref):
            if _under_lock(module, node):
                continue
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = list(node.targets)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATING_CALLS:
                path = dotted_path(node.func.value)
                if path and path.startswith("self.") and any(
                        _protected_attr(part)
                        for part in path.split(".")[1:]):
                    return True
            for target in targets:
                if isinstance(target, ast.Subscript):
                    target = target.value
                path = dotted_path(target)
                if path and path.startswith("self.") and any(
                        _protected_attr(part)
                        for part in path.split(".")[1:]):
                    return True
        return False

    # -- caller-side lock coverage -----------------------------------------
    def _unlocked_reachable(self, project) -> Set:
        """FuncRefs that can be *entered* without any ``with *lock*:``
        held: entry points (no callers) plus anything called through a
        site that is not under a lock."""
        unlocked: Set = set()
        stack = [ref for ref in project.functions
                 if not project.callers(ref)]
        while stack:
            ref = stack.pop()
            if ref in unlocked:
                continue
            unlocked.add(ref)
            module = project.module_of(ref)
            for callee, site in project.calls(ref):
                if callee in unlocked:
                    continue
                if not _under_lock(module, site):
                    stack.append(callee)
        return unlocked

    # -- step 3: flag unlocked mutator calls --------------------------------
    def _check_function(self, project, ref, pools, mutators,
                        unlocked) -> Iterable[Finding]:
        rel, qualname = ref
        module = project.module_of(ref)
        own_class = project.class_of_function(ref)
        findings: List[Finding] = []
        for node in project.body_nodes(ref):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            rtype = project.type_of(ref, node.func.value)
            if rtype is None or rtype not in pools:
                continue
            if own_class is not None and own_class.ref == rtype:
                continue  # the pool's own methods serialize internally
            if node.func.attr not in mutators.get(rtype, ()):
                continue
            if _under_lock(module, node):
                continue
            if ref not in unlocked:
                continue  # every entry path already holds a lock
            pool_name = project.classes[rtype].name
            receiver = dotted_path(node.func.value) or "<pool>"
            lock_attr = pools[rtype]
            findings.append(Finding(
                rule=self.rule_id, path=module.relpath,
                line=node.lineno,
                message=(
                    f"shared-pool-lock: '{receiver}.{node.func.attr}()' "
                    f"mutates {pool_name}'s free-list/refcount tables "
                    f"without holding '{receiver}.{lock_attr}' — a "
                    f"concurrent donating dispatch in an executor "
                    f"thread races this mutation and corrupts page "
                    f"accounting; wrap the call in "
                    f"'with {receiver}.{lock_attr}:'"),
                severity=self.severity,
                key=(f"unlocked {pool_name}.{node.func.attr} "
                     f"in {qualname}"),
            ))
        return findings
