"""TPU-first neural-net ops: norms, rotary embeddings, attention.

These back the servable model zoo (gofr_tpu.models) required by the
north star (BASELINE.json); the Go reference has no compute ops at all
(SURVEY.md §2.7 "there are none").
"""

from gofr_tpu.ops.attention import (
    attention,
    causal_mask,
    decode_attention,
    decode_attention_cached,
    gather_kv_pages,
    paged_decode_attention,
    paged_verify_attention,
    prefill_attention,
    prefix_prefill_attention,
    verify_attention,
)
from gofr_tpu.ops.norms import layer_norm, rms_norm
from gofr_tpu.ops.rotary import apply_rope, rope_table

__all__ = [
    "attention", "causal_mask", "decode_attention", "prefill_attention",
    "prefix_prefill_attention", "gather_kv_pages", "paged_decode_attention",
    "verify_attention", "paged_verify_attention",
    "layer_norm", "rms_norm", "apply_rope", "rope_table",
]
