"""graftcheck CLI: ``python -m gofr_tpu.analysis [paths...]``.

Exit 0 = no unsuppressed findings beyond the committed baseline;
exit 1 = new findings (printed one per line as ``path:line: RULE msg``)
or unparseable files.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from gofr_tpu.analysis import engine
from gofr_tpu.analysis.rules import ALL_RULES, default_rules


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m gofr_tpu.analysis",
        description="graftcheck: serving-aware static analysis "
                    "(rule catalog: docs/references/static-analysis.md)")
    parser.add_argument(
        "paths", nargs="*", type=pathlib.Path,
        help="files/directories to scan (default: the gofr_tpu package)")
    parser.add_argument(
        "--baseline", type=pathlib.Path, default=engine.DEFAULT_BASELINE,
        help="grandfathered-findings file "
             "(default: scripts/graftcheck_baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every unsuppressed finding")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0")
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)")
    parser.add_argument(
        "--docs", type=pathlib.Path, default=None,
        help="metrics catalog for GT005 "
             "(default: docs/quick-start/observability.md)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    opts = parser.parse_args(argv)

    if opts.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.rule_id}  {cls.title}")
        return 0

    select = [token.strip() for token in opts.select.split(",")
              if token.strip()] or None
    options = {}
    if opts.docs is not None:
        options["docs_catalog"] = opts.docs
    rules = default_rules(select=select, **options)

    paths = opts.paths or [engine.PACKAGE]
    baseline = {} if (opts.no_baseline or opts.write_baseline) \
        else engine.load_baseline(opts.baseline)
    report = engine.run(paths=paths, rules=rules, baseline=baseline)

    if opts.write_baseline:
        engine.write_baseline(opts.baseline, report.new_findings)
        print(f"graftcheck: wrote {len(report.new_findings)} grandfathered "
              f"finding(s) to {opts.baseline}")
        return 0

    for error in report.parse_errors:
        print(error, file=sys.stderr)
    for finding in report.new_findings:
        print(finding.render(), file=sys.stderr)
    if report.stale_baseline:
        # informational: the debt shrank — tighten the pin so it can't grow
        print(f"graftcheck: note: {len(report.stale_baseline)} baseline "
              f"entr{'y is' if len(report.stale_baseline) == 1 else 'ies are'}"
              f" stale (fixed?) — regenerate with --write-baseline",
              file=sys.stderr)
    if report.exit_code:
        print(f"graftcheck: {len(report.new_findings)} new finding(s) "
              f"({report.files_scanned} files, "
              f"{len(report.baselined)} baselined, "
              f"{report.suppressed} pragma-suppressed)", file=sys.stderr)
        return 1
    print(f"graftcheck: OK ({report.files_scanned} files, "
          f"{len(report.baselined)} baselined, "
          f"{report.suppressed} pragma-suppressed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
