"""Shared fallback policy for the Pallas TPU kernels.

Every kernel in this package ships with a pure-jnp reference formulation
that stays the correctness oracle (ops/attention); the kernels fall back
to it when the shapes miss TPU tiling or the process is not running on a
TPU at all. Two rules keep that decision honest:

- The backend is re-checked at **call time**, never cached at import
  time: tests (and multi-backend processes) swap ``JAX_PLATFORMS``
  between calls, and a stale import-time decision would pin interpret
  mode — or worse, a compiled TPU kernel — across the swap.
- Tiling support is split by mode: the compiled kernel needs real
  Mosaic tiles (lane dim 128, sublane-aligned head counts), while
  interpret mode only needs shapes the emulator can reshape cleanly —
  so CPU tier-1 tests exercise the kernel's control flow on geometries
  (tiny presets) the hardware tiles would reject.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["resolve_interpret", "decode_shapes_tileable",
           "ragged_shapes_supported"]


def resolve_interpret(interpret: Optional[bool]) -> bool:
    """Resolve an ``interpret=None`` default to "not on a TPU", checked
    at call time (a test that swaps platforms mid-process must not see a
    stale decision). Explicit True/False passes through untouched."""
    if interpret is not None:
        return bool(interpret)
    import jax

    return jax.default_backend() != "tpu"


def decode_shapes_tileable(t_max: int, block_k: int, head_dim: int,
                           q_heads: int) -> bool:
    """Dense flash-decode tiling predicate (ops/pallas/decode_attention):
    the KV window must split into whole lane-aligned blocks and heads
    must fill a sublane."""
    return (t_max % block_k == 0 and head_dim % 128 == 0
            and t_max >= 128 and q_heads % 8 == 0)


def ragged_shapes_supported(head_dim: int, q_heads: int, kv_heads: int,
                            page: int, interpret: bool) -> bool:
    """Ragged-paged-attention support predicate.

    Compiled mode needs Mosaic-tileable blocks: a 128-lane head_dim, a
    sublane-filling q-head count, and a page deep enough to tile the KV
    block. Interpret mode (the CPU tier-1 path) only needs the reshapes
    inside the kernel to be exact — head_dim a whole number of 8-lanes —
    so tiny test geometries run the kernel while a genuinely misaligned
    head_dim still exercises the gather fallback on every backend.
    """
    if q_heads % kv_heads != 0 or page < 1:
        return False
    if interpret:
        return head_dim % 8 == 0
    return head_dim % 128 == 0 and q_heads % 8 == 0 and page % 16 == 0
