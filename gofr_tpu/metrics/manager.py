"""Metrics manager: typed metric store with Prometheus exposition.

Capability parity with the reference's ``pkg/gofr/metrics``
(metrics/register.go:15-25 ``Manager`` New/Increment/Delta/Record/Set;
store.go typed store w/ duplicate detection; 249-269 label validation +
cardinality warning; exporters/exporter.go Prometheus export;
handler.go:21-35 runtime-gauge refresh per scrape).

Original design: a lock-guarded in-process registry (no OTel indirection —
the exposition endpoint renders directly from the store), float64 histograms
with fixed bucket boundaries, and label cardinality warnings at 100 series
per metric.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from gofr_tpu.logging import Logger

LabelKey = Tuple[Tuple[str, str], ...]

_CARDINALITY_WARN = 100


class MetricsError(Exception):
    pass


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    def __init__(self, name: str, kind: str, desc: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.kind = kind  # counter | updown | histogram | gauge
        self.desc = desc
        self.buckets = list(buckets) if buckets else []
        # series: labelkey -> value (float) or histogram state dict
        self.series: Dict[LabelKey, object] = {}


class Manager:
    """Create-then-use metrics API (reference: metrics/register.go:15-25).

    Metrics must be registered (``new_counter`` etc.) before use; using an
    unregistered or wrong-typed name logs an error instead of raising, so a
    metrics bug never takes down a request path (matching the reference's
    error-log-and-continue behaviour, metrics/metrics.go).
    """

    def __init__(self, logger: Optional[Logger] = None):
        self._logger = logger
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -- registration -------------------------------------------------------
    def _register(self, name: str, kind: str, desc: str,
                  buckets: Optional[Sequence[float]] = None) -> None:
        with self._lock:
            if name in self._metrics:
                self._err(f"metric {name!r} already registered")
                return
            self._metrics[name] = _Metric(name, kind, desc, buckets)

    def new_counter(self, name: str, desc: str = "") -> None:
        self._register(name, "counter", desc)

    def new_updown_counter(self, name: str, desc: str = "") -> None:
        self._register(name, "updown", desc)

    def new_histogram(self, name: str, desc: str = "",
                      buckets: Sequence[float] = ()) -> None:
        if not buckets:
            buckets = (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10, 30)
        self._register(name, "histogram", desc, buckets)

    def new_gauge(self, name: str, desc: str = "") -> None:
        self._register(name, "gauge", desc)

    # -- writes -------------------------------------------------------------
    def _get(self, name: str, kind: str) -> Optional[_Metric]:
        metric = self._metrics.get(name)
        if metric is None:
            self._err(f"metric {name!r} not registered")
            return None
        if metric.kind != kind:
            self._err(f"metric {name!r} is a {metric.kind}, not a {kind}")
            return None
        return metric

    def increment_counter(self, name: str, /, **labels: str) -> None:
        metric = self._get(name, "counter")
        if metric is None:
            return
        key = _label_key(labels)
        with self._lock:
            self._check_cardinality(metric)
            metric.series[key] = float(metric.series.get(key, 0.0)) + 1.0  # type: ignore[arg-type]

    def delta_updown_counter(self, name: str, value: float, /, **labels: str) -> None:
        metric = self._get(name, "updown")
        if metric is None:
            return
        key = _label_key(labels)
        with self._lock:
            self._check_cardinality(metric)
            metric.series[key] = float(metric.series.get(key, 0.0)) + value  # type: ignore[arg-type]

    def record_histogram(self, name: str, value: float, /,
                         exemplar: Optional[Dict[str, str]] = None,
                         **labels: str) -> None:
        """Record one observation. ``exemplar`` is an optional small label
        dict (typically ``{"trace_id": ...}``) attached to the bucket the
        value falls into and rendered as an OpenMetrics exemplar — the
        bridge from an aggregate latency histogram back to one concrete
        traced request."""
        metric = self._get(name, "histogram")
        if metric is None:
            return
        key = _label_key(labels)
        with self._lock:
            self._check_cardinality(metric)
            state = metric.series.get(key)
            if state is None:
                state = {"count": 0, "sum": 0.0,
                         "buckets": [0] * len(metric.buckets),
                         "exemplars": {}}
                metric.series[key] = state
            state["count"] += 1          # type: ignore[index]
            state["sum"] += value        # type: ignore[index]
            # per-bucket counts; exposition cumulates (prometheus `le` form)
            bucket_idx = len(metric.buckets)   # +Inf bucket
            for i, bound in enumerate(metric.buckets):
                if value <= bound:
                    state["buckets"][i] += 1  # type: ignore[index]
                    bucket_idx = i
                    break
            if exemplar:
                # last observation wins per bucket (OpenMetrics allows at
                # most one exemplar per bucket line)
                state.setdefault("exemplars", {})[bucket_idx] = (  # type: ignore[union-attr]
                    {str(k): str(v) for k, v in exemplar.items()},
                    float(value), time.time())

    def set_gauge(self, name: str, value: float, /, **labels: str) -> None:
        metric = self._get(name, "gauge")
        if metric is None:
            return
        key = _label_key(labels)
        with self._lock:
            self._check_cardinality(metric)
            metric.series[key] = float(value)

    # -- reads (for exposition and tests) -----------------------------------
    def snapshot(self) -> Dict[str, _Metric]:
        with self._lock:
            return dict(self._metrics)

    def value(self, name: str, /, **labels: str) -> Optional[float]:
        metric = self._metrics.get(name)
        if metric is None:
            return None
        state = metric.series.get(_label_key(labels))
        if isinstance(state, dict):
            return float(state["count"])
        return float(state) if state is not None else None

    # -- internals ----------------------------------------------------------
    def _check_cardinality(self, metric: _Metric) -> None:
        if len(metric.series) == _CARDINALITY_WARN:
            self._err(
                f"metric {metric.name!r} exceeded {_CARDINALITY_WARN} label "
                "combinations; high-cardinality labels degrade scrapes"
            )

    def _err(self, message: str) -> None:
        if self._logger is not None:
            self._logger.error(message)


def new_manager(logger: Optional[Logger] = None) -> Manager:
    return Manager(logger=logger)


def current_rss_bytes() -> Optional[float]:
    """Current (not peak) resident set size from ``/proc/self/statm``;
    None where procfs is unavailable (macOS, restricted containers)."""
    try:
        # graftcheck: ignore[GT001] — /proc/self/statm is a procfs read
        # (kernel memory, microseconds, never blocks on storage); an
        # executor hop per metrics refresh would cost more than the read
        with open("/proc/self/statm") as fh:
            resident_pages = int(fh.read().split()[1])
        import os
        return float(resident_pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, IndexError, ValueError):
        return None


def system_metrics_refresh(manager: Manager, app_name: str, app_version: str) -> None:
    """Refresh runtime gauges; called on each scrape (reference:
    metrics/handler.go:21-35 and container/container.go:158-166 app_info /
    go_routines / memory gauges)."""
    import gc
    import resource

    manager.set_gauge("app_info", 1.0, name=app_name, version=app_version)
    manager.set_gauge("threads_total", float(threading.active_count()))
    # ru_maxrss is the PEAK rss — a gauge built from it can never go down
    # and overstates steady-state memory; prefer the live value from procfs
    rss = current_rss_bytes()
    usage = resource.getrusage(resource.RUSAGE_SELF)
    if rss is None:
        rss = float(usage.ru_maxrss) * 1024.0
    manager.set_gauge("memory_rss_bytes", rss)
    manager.set_gauge("gc_objects", float(gc.get_count()[0]))
    manager.set_gauge("uptime_seconds", time.monotonic() - _START)


_START = time.monotonic()
