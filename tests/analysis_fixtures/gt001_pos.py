"""GT001 positive fixture: blocking calls reachable from async defs.

Parsed by graftcheck in tests, never imported.
"""

import time

import numpy as np


async def handler(values):
    time.sleep(0.1)
    return np.asarray(values)


def _helper(result):
    return result.block_until_ready()


async def transitive(x):
    # blocks through a plain-call hop: handler -> _helper -> device sync
    return _helper(x)


async def lock_wait(lock):
    lock.acquire()
    try:
        return 1
    finally:
        lock.release()


async def reads(path):
    with open(path) as fh:
        return fh.read()


async def scheduler(loop, x):
    # loop-scheduled callbacks run on the loop: edge to _helper
    loop.call_soon(_helper, x)
