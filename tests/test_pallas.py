"""Pallas flash-attention kernel tests (interpret mode on CPU — the kernel
itself, not just the fallback)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.models import llama
from gofr_tpu.ops import attention, prefill_attention
from gofr_tpu.ops.pallas import flash_attention


def _qkv(seq, q_heads=4, kv_heads=2, dim=128, batch=2):
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (batch, seq, q_heads, dim))
    k = jax.random.normal(keys[1], (batch, seq, kv_heads, dim))
    v = jax.random.normal(keys[2], (batch, seq, kv_heads, dim))
    return q, k, v


def test_flash_matches_dense_causal():
    q, k, v = _qkv(256)
    ref = prefill_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_matches_dense_noncausal():
    q, k, v = _qkv(256)
    ref = attention(q, k, v)
    out = flash_attention(q, k, v, causal=False, interpret=True,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_uneven_blocks():
    """block_q != block_k exercises the causal block-skip boundary."""
    q, k, v = _qkv(512)
    ref = prefill_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True, block_q=128, block_k=256)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    out = flash_attention(q, k, v, interpret=True, block_q=256, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_mha_no_gqa():
    q, k, v = _qkv(128, q_heads=2, kv_heads=2)
    ref = prefill_attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_fallback_small_shapes():
    """head_dim 32 / seq 16 can't tile — must silently use the dense path."""
    q, k, v = _qkv(16, dim=32)
    ref = prefill_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_flash_fallback_warns_at_long_context():
    """A silent dense fallback at long S turns a shape mistake into an
    opaque 16 GB OOM (r5, measured on v5e) — it must warn at trace time.
    Short sequences stay silent."""
    import warnings

    import pytest

    q, k, v = _qkv(8192, q_heads=8, dim=64)  # head_dim 64: untileable
    # B=2 x H=8 x 8192^2 x f32 = 4.3 GB score tensor -> must warn
    with pytest.warns(UserWarning, match="GB score tensor"):
        jax.eval_shape(lambda q, k, v: flash_attention(q, k, v), q, k, v)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        q2, k2, v2 = _qkv(16, dim=32)
        flash_attention(q2, k2, v2)      # short fallback: stays silent
    assert not [w for w in caught if "DENSE attention" in str(w.message)]


def test_llama_use_flash_config():
    """tiny preset (head_dim 16) routes through the fallback — forward must
    be identical with the flag on."""
    cfg = llama.config("tiny")
    cfg_flash = llama.config("tiny", use_flash=True)
    params = llama.init(cfg, jax.random.PRNGKey(0))
    tokens = jnp.ones((1, 8), jnp.int32)
    ref = llama.forward(params, cfg, tokens)
    out = llama.forward(params, cfg_flash, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
