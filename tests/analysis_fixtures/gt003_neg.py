"""GT003 negative fixture: disciplined jit call sites.

Parsed by graftcheck in tests, never imported.
"""

import jax
import jax.numpy as jnp

_BUCKETS = (8, 16, 32)


def _forward(params, tokens):
    return params, tokens


static_jitted = jax.jit(_forward, static_argnums=(1,))
plain_jitted = jax.jit(_forward)


def cached_factory(cache, key):
    # the repo's jit-factory idiom: build once, reuse from a dict —
    # the jit call is not immediately invoked, so no fresh-jit hazard
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(_forward)
        cache[key] = fn
    return fn


def bucketed(params, tokens):
    # static arg is a hashable rung, and the device shape is a rung too
    rung = next(b for b in _BUCKETS if b >= len(tokens))
    padded = jnp.zeros((rung, 4))
    return static_jitted(params, rung), padded


def tuple_static(params):
    return static_jitted(params, (1, 2, 3))


_PAGE_WIDTHS = (4, 8, 16)


def ladder_width_upload(table, pages):
    # disciplined: the slice bound is a ladder rung covering the live
    # count, so the executable set is bounded by the ladder
    pw = next(w for w in _PAGE_WIDTHS if w >= len(pages))
    return jnp.asarray(table[:, :pw])


def scalar_prefetch_table_upload(table, lens):
    # ragged-attention idiom: the scalar-prefetch operands are the FULL
    # fixed-width page table and the per-slot lengths — no live-count
    # slice bound anywhere, so the executable set is one per geometry.
    # The kernel skips dead entries via its in-kernel length guard
    # instead of the host shrinking the upload.
    return jnp.asarray(table), jnp.asarray(lens)
