"""Llama-family decoder for the /generate serving path.

North star (BASELINE.json): "Llama-2-7B /generate endpoint, tensor-parallel
across v5e-8, KV-cache in HBM". The Go reference has no models
(SURVEY.md §2.7); this is an original TPU-first design:

- **Stacked layers + lax.scan**: all per-layer weights are stacked on a
  leading (L, ...) axis and the decoder is one ``lax.scan`` — one traced
  layer body regardless of depth, so Llama-2-7B (32 layers) compiles as
  fast as the tiny test preset.
- **bf16 weights/activations** (MXU native), fp32 for norms/softmax/logits.
- **Static-shape KV cache** (B, Tmax, Hkv, Dh) per layer with a fill-length
  mask — one compiled decode executable serves every fill level, the
  prerequisite for continuous batching.
- **Tensor parallelism by sharding annotation only**: the model code is
  SPMD-agnostic; gofr_tpu.parallel.tensor_parallel assigns PartitionSpecs
  to these param names and XLA inserts the all-reduces over ICI
  (scaling-book recipe), instead of hand-written collective calls.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.ops import (
    apply_rope,
    decode_attention,
    decode_attention_cached,
    gather_kv_pages,
    prefill_attention,
    prefix_prefill_attention,
    rms_norm,
    rope_table,
    verify_attention,
)
from gofr_tpu.ops.quant import qmm, quantize_kv, quantize_tree


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # pallas flash-attention prefill (ops/pallas): O(S) memory, causal-block
    # skipping — required beyond ~8K context on one core; falls back to the
    # dense einsum when shapes don't meet TPU tiling constraints
    use_flash: bool = False
    # pallas decode attention (ops/pallas/decode_attention): numerics
    # verified, but MEASURED ~5x SLOWER at 7B geometry — a
    # pallas_call per layer inside the decode scan breaks XLA's weight
    # prefetch pipeline. Default off; kept as the starting point for a
    # fused whole-step kernel (see that module's post-mortem).
    use_flash_decode: bool = False
    # int8 KV cache (ops/quant.quantize_kv): per-(token, head) scales,
    # halving the cache's HBM *footprint* — the capacity lever for longer
    # contexts / more slots per chip. MEASURED (v5e, 7B geometry,
    # 2026-07-30): decode is ~12% SLOWER than bf16 through plain XLA —
    # the int8→bf16 convert does not stay fused into the attention dots,
    # so the "saved" bytes come back as a materialized converted copy
    # (bf16 full-window 300 tok/s vs int8 265; window-bounded 366 vs 260
    # standalone-tick numbers). Default off: use it when the cache must
    # fit, not to go faster; a Pallas fused dequant-attention kernel is
    # the known fix (same conclusion as ops/pallas/decode_attention).
    # Mutually exclusive with use_flash_decode (the flash kernel reads a
    # bf16 cache) — enforced in __post_init__.
    kv_int8: bool = False

    def __post_init__(self):
        if self.kv_int8 and self.use_flash_decode:
            raise ValueError(
                "kv_int8 and use_flash_decode are mutually exclusive: the "
                "pallas decode kernel reads a bf16 cache")

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


PRESETS: Dict[str, LlamaConfig] = {
    # tiny: unit tests + driver dryrun (shapes divisible by tp=4, sp=2)
    "tiny": LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, ffn_dim=128, max_seq_len=128),
    # small: single-chip bench model
    "small": LlamaConfig(vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
                         n_kv_heads=16, ffn_dim=2816, max_seq_len=2048),
    "7b": LlamaConfig(),  # Llama-2-7B geometry
    # Llama-3-8B geometry: GQA 32:8 (the engine's decode attention and
    # cache specs handle grouped KV heads natively), 128K-token-family
    # vocab, rope theta 500k
    "llama3-8b": LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                             n_heads=32, n_kv_heads=8, ffn_dim=14336,
                             max_seq_len=8192, rope_theta=500000.0),
}


def config(preset: str = "tiny", **overrides) -> LlamaConfig:
    return dataclasses.replace(PRESETS[preset], **overrides)


def init(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Random params (serving benches run on random weights; real weights
    arrive via gofr_tpu checkpoint loading — same pytree layout)."""
    keys = jax.random.split(key, 10)
    dt = cfg.dtype
    d, f, l_count = cfg.dim, cfg.ffn_dim, cfg.n_layers
    qd = cfg.n_heads * cfg.head_dim
    kvd = cfg.n_kv_heads * cfg.head_dim

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dt)

    return {
        "tok_emb": dense(keys[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((l_count, d), dt),
            "wq": dense(keys[1], (l_count, d, qd), d),
            "wk": dense(keys[2], (l_count, d, kvd), d),
            "wv": dense(keys[3], (l_count, d, kvd), d),
            "wo": dense(keys[4], (l_count, qd, d), qd),
            "ffn_norm": jnp.ones((l_count, d), dt),
            "w_gate": dense(keys[5], (l_count, d, f), d),
            "w_up": dense(keys[6], (l_count, d, f), d),
            "w_down": dense(keys[7], (l_count, f, d), f),
        },
        "out_norm": jnp.ones((d,), dt),
        "lm_head": dense(keys[8], (d, cfg.vocab_size), d),
    }


def init_cache(cfg: LlamaConfig, batch: int,
               max_len: Optional[int] = None) -> Dict[str, jnp.ndarray]:
    """Static-shape per-layer KV cache resident in HBM. With
    ``cfg.kv_int8`` the k/v arrays are int8 plus per-vector scale planes
    ``ks``/``vs`` (L, B, T, Hkv) — half the bytes, same layout."""
    t_max = max_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, t_max, cfg.n_kv_heads, cfg.head_dim)
    if cfg.kv_int8:
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "ks": jnp.ones(shape[:-1], jnp.float32),
                "vs": jnp.ones(shape[:-1], jnp.float32)}
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _qkv(layer, x, cfg, cos, sin, positions):
    # qmm: weights may be int8-quantized (ops/quant) — transparent here
    b, s, _ = x.shape
    q = qmm(x, layer["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = qmm(x, layer["wk"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = qmm(x, layer["wv"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    return q, k, v


def _ffn(layer, x):
    gate = jax.nn.silu(qmm(x, layer["w_gate"]).astype(jnp.float32))
    up = qmm(x, layer["w_up"]).astype(jnp.float32)
    return qmm((gate * up).astype(x.dtype), layer["w_down"])


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """int8 weight-only quantization of every matmul weight (attention,
    FFN, lm_head); norms and tok_emb stay bf16. Halves decode HBM traffic
    and fits 7B geometry on one ~16 GB chip (ops/quant rationale)."""
    return quantize_tree(params)


def forward(params: Dict[str, Any], cfg: LlamaConfig, tokens: jnp.ndarray,
            mesh=None, sp_axis: str = "sp", dp_axis: str = "dp",
            tp_axis: str = "tp") -> jnp.ndarray:
    """Full causal forward → logits (B, S, V) in fp32. Training/eval path.

    With ``mesh`` given (long-context sequence parallelism), attention runs
    as ring attention over the ``sp_axis`` ring — K/V blocks rotate via
    ppermute over ICI, composing with dp (batch) and tp (heads) sharding.
    """
    b, s = tokens.shape
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    x = params["tok_emb"][tokens]

    if mesh is not None:
        from gofr_tpu.parallel.ring_attention import ring_attention

        def attend(q, k, v):
            return ring_attention(q, k, v, mesh, axis_name=sp_axis,
                                  batch_axis=dp_axis, head_axis=tp_axis)
    elif cfg.use_flash:
        from gofr_tpu.ops.pallas import flash_attention
        attend = flash_attention
    else:
        attend = prefill_attention

    def body(x, layer):
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(layer, h, cfg, cos, sin, positions)
        attn = attend(q, k, v).reshape(b, s, -1)
        x = x + qmm(attn, layer["wo"])
        h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        x = x + _ffn(layer, h)
        return x, None

    x, _ = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    return qmm(x, params["lm_head"]).astype(jnp.float32)


def prefill(params: Dict[str, Any], cfg: LlamaConfig, tokens: jnp.ndarray,
            cache: Dict[str, jnp.ndarray],
            lengths: Optional[jnp.ndarray] = None,
            prefix: Optional[Dict[str, jnp.ndarray]] = None,
            prefix_len: int = 0
            ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], jnp.ndarray]:
    """Run the prompt, fill the cache. Returns (last-token logits (B, V),
    cache, cache_len (B,)).

    ``lengths`` (B,) supports right-padded prompts (the bucketed serving
    path): logits are taken at position lengths-1 per sequence and
    cache_len = lengths, so junk positions past a prompt's real end are
    never attended to in decode.

    ``prefix``/``prefix_len`` is the suffix-only prefill path (prefix KV
    reuse, tpu/prefix_cache): ``prefix`` holds pre-computed KV for the
    prompt's first ``prefix_len`` tokens (same leaves as ``cache``,
    shapes (L, B, prefix_len, ...)), ``tokens`` carries only the suffix.
    RoPE positions offset by the *static* ``prefix_len`` and attention
    for each suffix token spans cached-prefix + suffix
    (ops.prefix_prefill_attention); the returned ``cache`` still holds
    only the suffix KV (the caller owns prefix placement) while
    ``cache_len`` counts prefix + suffix. With ``cfg.kv_int8`` the prefix
    arrives quantized and is dequantized to the compute dtype here —
    decode reads quantized KV either way, but suffix-prefill logits see
    quantization-level drift vs a full prefill (documented contract:
    exact token-identity holds for bf16 caches).
    """
    b, s = tokens.shape
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    positions = jnp.broadcast_to(
        prefix_len + jnp.arange(s, dtype=jnp.int32), (b, s))
    x = params["tok_emb"][tokens]
    if cfg.use_flash and prefix is None:
        # the flash kernel is strictly causal — the prefix path needs the
        # rectangular prefix block, so it uses the dense mask form
        from gofr_tpu.ops.pallas import flash_attention as attend
    else:
        attend = prefill_attention

    xs: Dict[str, Any] = {"layer": params["layers"], "cache": cache}
    if prefix is not None:
        xs["prefix"] = prefix

    def body(x, xs):
        layer = xs["layer"]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(layer, h, cfg, cos, sin, positions)
        if prefix is None:
            attn = attend(q, k, v).reshape(b, s, -1)
        else:
            pk, pv = xs["prefix"]["k"], xs["prefix"]["v"]
            if cfg.kv_int8:
                pk = pk.astype(cfg.dtype) * \
                    xs["prefix"]["ks"][..., None].astype(cfg.dtype)
                pv = pv.astype(cfg.dtype) * \
                    xs["prefix"]["vs"][..., None].astype(cfg.dtype)
            k_all = jnp.concatenate([pk, k], axis=1)
            v_all = jnp.concatenate([pv, v], axis=1)
            attn = prefix_prefill_attention(
                q, k_all, v_all, prefix_len).reshape(b, s, -1)
        x = x + qmm(attn, layer["wo"])
        h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        x = x + _ffn(layer, h)
        if cfg.kv_int8:
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            new_cache = {
                "k": lax.dynamic_update_slice_in_dim(
                    xs["cache"]["k"], kq, 0, axis=1),
                "v": lax.dynamic_update_slice_in_dim(
                    xs["cache"]["v"], vq, 0, axis=1),
                "ks": lax.dynamic_update_slice_in_dim(
                    xs["cache"]["ks"], ks, 0, axis=1),
                "vs": lax.dynamic_update_slice_in_dim(
                    xs["cache"]["vs"], vs, 0, axis=1)}
        else:
            new_cache = {
                "k": lax.dynamic_update_slice_in_dim(
                    xs["cache"]["k"], k, 0, axis=1),
                "v": lax.dynamic_update_slice_in_dim(
                    xs["cache"]["v"], v, 0, axis=1)}
        return x, new_cache

    x, new_cache = lax.scan(body, x, xs)
    if lengths is None:
        last = x[:, -1]
        cache_len = jnp.full((b,), prefix_len + s, jnp.int32)
    else:
        last = x[jnp.arange(b), lengths - 1]
        cache_len = prefix_len + lengths.astype(jnp.int32)
    last = rms_norm(last, params["out_norm"], cfg.norm_eps)
    logits = qmm(last, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache, cache_len


def decode_step(params: Dict[str, Any], cfg: LlamaConfig,
                token: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                cache_len: jnp.ndarray, window: Optional[int] = None
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], jnp.ndarray]:
    """One decode step. token (B,) int32; returns (logits (B,V), cache,
    cache_len+1). Static shapes: scatters into the cache at cache_len.

    The full stacked cache is a scan CARRY, not an xs→ys pair: scanning
    the cache as xs makes XLA materialize a fresh stacked ys every step —
    a full-cache rewrite that measured ~40% of 7B decode tick time. As a
    carry the while-loop state buffer is updated in place and the scatter
    writes only the B new (H, D) rows per layer (measured 1.6× faster
    end-to-end at 7B geometry, within 6% of a no-scatter ceiling). The
    attention still runs over (old cache + current K/V) via
    decode_attention_cached with the scatter off its critical path.

    ``window`` (static) bounds the attention read to the cache's first
    ``window`` positions — fill-bounded decode: the caller guarantees
    every *active* row's cache_len < window, picks the executable from a
    small window ladder, and the dead tail of the static cache is never
    streamed from HBM (it dominates early-fill decode traffic). The
    scatter still targets the full cache, so growing past a window rung
    just switches executables, never moves data. With ``cfg.kv_int8`` the
    cache is int8 + scale planes; the new row quantizes before scatter.
    """
    b = token.shape[0]
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    positions = cache_len[:, None]                       # (B, 1)
    x = params["tok_emb"][token][:, None, :]             # (B, 1, D)
    batch_idx = jnp.arange(b)
    int8 = cfg.kv_int8
    carry_keys = ("k", "v", "ks", "vs") if int8 else ("k", "v")

    def body(carry, layer_and_idx):
        x = carry[0]
        caches = carry[1:]
        layer, idx = layer_and_idx
        views = [lax.dynamic_index_in_dim(c, idx, 0, keepdims=False)
                 for c in caches]
        if window is not None:
            views = [v[:, :window] for v in views]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(layer, h, cfg, cos, sin, positions)
        if cfg.use_flash_decode and not int8:
            from gofr_tpu.ops.pallas import flash_decode_attention
            attn = flash_decode_attention(q, views[0], views[1], k[:, 0],
                                          v[:, 0], cache_len)
        else:
            k_scale = views[2] if int8 else None
            v_scale = views[3] if int8 else None
            attn = decode_attention_cached(q, views[0], views[1], k[:, 0],
                                           v[:, 0], cache_len,
                                           k_scale=k_scale, v_scale=v_scale)
        x = x + qmm(attn.reshape(b, 1, -1), layer["wo"])
        h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        x = x + _ffn(layer, h)
        # in-place scatter of the B new rows at [layer idx, b, cache_len[b]]
        if int8:
            kq, ks = quantize_kv(k[:, 0])
            vq, vs = quantize_kv(v[:, 0])
            new_rows = (kq, vq, ks, vs)
        else:
            new_rows = (k[:, 0], v[:, 0])
        caches = tuple(
            c.at[idx, batch_idx, cache_len].set(row)
            for c, row in zip(caches, new_rows))
        return (x,) + caches, None

    carry, _ = lax.scan(
        body, (x,) + tuple(cache[key] for key in carry_keys),
        (params["layers"], jnp.arange(cfg.n_layers)))
    x = carry[0]
    new_cache = dict(zip(carry_keys, carry[1:]))
    x = rms_norm(x[:, 0], params["out_norm"], cfg.norm_eps)
    logits = qmm(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache, cache_len + 1


def decode_step_paged(params: Dict[str, Any], cfg: LlamaConfig,
                      token: jnp.ndarray, pool: Dict[str, jnp.ndarray],
                      page_table: jnp.ndarray, cache_len: jnp.ndarray,
                      active: jnp.ndarray, ragged: bool = False
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray],
                                 jnp.ndarray]:
    """One decode step over the unified paged KV pool (ISSUE 6).

    token (B,) int32; ``pool`` holds the shared page-pool leaves
    (L, num_pages, page, Hkv, Dh) (+ int8 scale planes); ``page_table``
    (B, P) int32 maps each slot's sequence pages to pool rows, with
    ``num_pages`` as the unallocated sentinel — P is a *static* ladder
    rung, so one executable serves every fill level just like the dense
    cache, and P plays the attention-window role (only the table's pages
    are gathered/streamed, not a max_len tail). ``active`` (B,) bool
    gates the append: the pool is shared, so an inactive slot must not
    scatter — its row could have been freed and reallocated to another
    stream while a pipelined tick was in flight — hence its destination
    is routed to the sentinel page and dropped (the dense path could
    ignore this: each slot owned its cache row forever).

    Per layer this gathers the table's pages into the dense-cache-shaped
    (B, P*page, Hkv, Dh) view and runs exactly the dense decode-step
    attention over it (ops.paged_decode_attention formulation), then
    appends the new K/V row at page ``cache_len // page``, offset
    ``cache_len % page``. Pool leaves ride the scan carry for the same
    reason the dense cache does (no stacked-ys rewrite). Returns
    (logits (B, V), pool, cache_len + 1) — the caller freezes inactive
    rows' cache_len, as on the dense path.

    ``ragged=True`` (static) swaps the gather-then-attend formulation
    for the fused Pallas ragged kernel
    (ops.pallas.ragged_paged_decode_attention): no (B, P*page) view is
    materialized — the kernel walks the slot's actual pages via scalar
    prefetch — so ``page_table`` may carry the slot's *full* table (no
    ladder rung slicing) and int8 dequant happens in-kernel from the
    scale planes. Takes priority over ``cfg.use_flash_decode`` and,
    unlike it, supports int8. Token-identical to the gather path (that
    formulation remains the correctness oracle and the fallback on
    unsupported shapes / off-TPU).
    """
    b = token.shape[0]
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    positions = cache_len[:, None]                       # (B, 1)
    x = params["tok_emb"][token][:, None, :]             # (B, 1, D)
    int8 = cfg.kv_int8
    carry_keys = ("k", "v", "ks", "vs") if int8 else ("k", "v")
    num_pages = pool["k"].shape[1]
    page = pool["k"].shape[2]
    # the append destination is the same for every layer: hoist it
    page_col = cache_len // page                         # (B,)
    page_row = jnp.take_along_axis(page_table, page_col[:, None],
                                   axis=1, mode="clip")[:, 0]
    dest_row = jnp.where(active, page_row, num_pages)    # sentinel-drop
    offset = cache_len % page

    def body(carry, layer_and_idx):
        x = carry[0]
        pools = carry[1:]
        layer, idx = layer_and_idx
        planes = [lax.dynamic_index_in_dim(c, idx, 0, keepdims=False)
                  for c in pools]                        # (N, page, ...)
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(layer, h, cfg, cos, sin, positions)
        if ragged:
            from gofr_tpu.ops.pallas import ragged_paged_decode_attention
            attn = ragged_paged_decode_attention(
                q, planes[0], planes[1], page_table, k[:, 0], v[:, 0],
                cache_len,
                k_scale_pages=planes[2] if int8 else None,
                v_scale_pages=planes[3] if int8 else None)
        elif cfg.use_flash_decode and not int8:
            from gofr_tpu.ops.pallas import flash_decode_attention
            views = [gather_kv_pages(p, page_table) for p in planes]
            attn = flash_decode_attention(q, views[0], views[1], k[:, 0],
                                          v[:, 0], cache_len)
        else:
            views = [gather_kv_pages(p, page_table) for p in planes]
            k_scale = views[2] if int8 else None
            v_scale = views[3] if int8 else None
            attn = decode_attention_cached(q, views[0], views[1], k[:, 0],
                                           v[:, 0], cache_len,
                                           k_scale=k_scale, v_scale=v_scale)
        x = x + qmm(attn.reshape(b, 1, -1), layer["wo"])
        h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        x = x + _ffn(layer, h)
        if int8:
            kq, ks = quantize_kv(k[:, 0])
            vq, vs = quantize_kv(v[:, 0])
            new_rows = (kq, vq, ks, vs)
        else:
            new_rows = (k[:, 0], v[:, 0])
        pools = tuple(
            c.at[idx, dest_row, offset].set(row, mode="drop")
            for c, row in zip(pools, new_rows))
        return (x,) + pools, None

    carry, _ = lax.scan(
        body, (x,) + tuple(pool[key] for key in carry_keys),
        (params["layers"], jnp.arange(cfg.n_layers)))
    x = carry[0]
    new_pool = dict(zip(carry_keys, carry[1:]))
    x = rms_norm(x[:, 0], params["out_norm"], cfg.norm_eps)
    logits = qmm(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_pool, cache_len + 1


def verify_step(params: Dict[str, Any], cfg: LlamaConfig,
                tokens: jnp.ndarray, cache: Dict[str, jnp.ndarray],
                cache_len: jnp.ndarray, window: Optional[int] = None
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Speculative verify forward: score G tokens per row in ONE step.

    ``tokens`` (B, G) sit at absolute positions ``cache_len + g``; the
    target model computes logits for every position (judging draft token
    g+1 at position g, plus the bonus position) while writing the G new
    KV rows into the cache — exactly G sequential :func:`decode_step`
    calls fused into one forward, which is the whole speculative-decode
    bargain: decode is HBM-bandwidth-bound streaming weights + cache, so
    verifying G tokens costs roughly one step's traffic. G is a *static*
    ladder rung (the engine's γ family), so shapes stay compile-stable.

    Returns (logits (B, G, V) fp32, cache). ``cache_len`` is NOT
    advanced here — the caller commits ``a + 1`` of the G+1 candidate
    tokens after acceptance and advances cache_len itself; rows written
    past the committed point sit beyond cache_len, are never attended,
    and are overwritten by the next tick (the same masking argument that
    lets inactive dense rows scatter garbage). Scatters use
    ``mode="drop"`` so a near-full row cannot clamp-corrupt its tail.
    """
    b, g_len = tokens.shape
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    positions = cache_len[:, None] + jnp.arange(g_len,
                                                dtype=jnp.int32)[None, :]
    x = params["tok_emb"][tokens]                        # (B, G, D)
    batch_idx = jnp.arange(b)
    int8 = cfg.kv_int8
    carry_keys = ("k", "v", "ks", "vs") if int8 else ("k", "v")

    def body(carry, layer_and_idx):
        x = carry[0]
        caches = carry[1:]
        layer, idx = layer_and_idx
        views = [lax.dynamic_index_in_dim(c, idx, 0, keepdims=False)
                 for c in caches]
        if window is not None:
            views = [v[:, :window] for v in views]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(layer, h, cfg, cos, sin, positions)
        k_scale = views[2] if int8 else None
        v_scale = views[3] if int8 else None
        attn = verify_attention(q, views[0], views[1], k, v, cache_len,
                                k_scale=k_scale, v_scale=v_scale)
        x = x + qmm(attn.reshape(b, g_len, -1), layer["wo"])
        h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        x = x + _ffn(layer, h)
        if int8:
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            new_rows = (kq, vq, ks, vs)
        else:
            new_rows = (k, v)
        caches = tuple(
            c.at[idx, batch_idx[:, None], positions].set(row, mode="drop")
            for c, row in zip(caches, new_rows))
        return (x,) + caches, None

    carry, _ = lax.scan(
        body, (x,) + tuple(cache[key] for key in carry_keys),
        (params["layers"], jnp.arange(cfg.n_layers)))
    x = carry[0]
    new_cache = dict(zip(carry_keys, carry[1:]))
    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    logits = qmm(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def verify_step_paged(params: Dict[str, Any], cfg: LlamaConfig,
                      tokens: jnp.ndarray, pool: Dict[str, jnp.ndarray],
                      page_table: jnp.ndarray, cache_len: jnp.ndarray,
                      active: jnp.ndarray, ragged: bool = False
                      ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Paged-pool variant of :func:`verify_step` (unified page pool).

    Same contract; the G new KV rows land at pool positions
    ``cache_len + g`` through the slot's page-table row. ``active`` (B,)
    bool routes inactive rows' appends to the sentinel page (dropped) —
    mandatory here because pool pages are shared and may have been
    reallocated, exactly as in :func:`decode_step_paged`. The engine
    guarantees an active row's allocated pages cover
    ``cache_len + G`` before dispatching a γ=G verify rung.
    ``ragged=True`` runs the fused Pallas kernel's γ+1-query variant
    over the pool pages directly (no gathered view), same semantics as
    on :func:`decode_step_paged`.
    """
    b, g_len = tokens.shape
    cos, sin = rope_table(cfg.max_seq_len, cfg.head_dim, cfg.rope_theta)
    positions = cache_len[:, None] + jnp.arange(g_len,
                                                dtype=jnp.int32)[None, :]
    x = params["tok_emb"][tokens]                        # (B, G, D)
    int8 = cfg.kv_int8
    carry_keys = ("k", "v", "ks", "vs") if int8 else ("k", "v")
    num_pages = pool["k"].shape[1]
    page = pool["k"].shape[2]
    # per-position append destinations, hoisted out of the layer scan
    page_col = positions // page                         # (B, G)
    page_row = jnp.take_along_axis(page_table, page_col, axis=1,
                                   mode="clip")
    dest_row = jnp.where(active[:, None], page_row, num_pages)
    offset = positions % page

    def body(carry, layer_and_idx):
        x = carry[0]
        pools = carry[1:]
        layer, idx = layer_and_idx
        planes = [lax.dynamic_index_in_dim(c, idx, 0, keepdims=False)
                  for c in pools]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(layer, h, cfg, cos, sin, positions)
        if ragged:
            from gofr_tpu.ops.pallas import ragged_paged_verify_attention
            attn = ragged_paged_verify_attention(
                q, planes[0], planes[1], page_table, k, v, cache_len,
                k_scale_pages=planes[2] if int8 else None,
                v_scale_pages=planes[3] if int8 else None)
        else:
            views = [gather_kv_pages(p, page_table) for p in planes]
            k_scale = views[2] if int8 else None
            v_scale = views[3] if int8 else None
            attn = verify_attention(q, views[0], views[1], k, v, cache_len,
                                    k_scale=k_scale, v_scale=v_scale)
        x = x + qmm(attn.reshape(b, g_len, -1), layer["wo"])
        h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        x = x + _ffn(layer, h)
        if int8:
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            new_rows = (kq, vq, ks, vs)
        else:
            new_rows = (k, v)
        pools = tuple(
            c.at[idx, dest_row, offset].set(row, mode="drop")
            for c, row in zip(pools, new_rows))
        return (x,) + pools, None

    carry, _ = lax.scan(
        body, (x,) + tuple(pool[key] for key in carry_keys),
        (params["layers"], jnp.arange(cfg.n_layers)))
    x = carry[0]
    new_pool = dict(zip(carry_keys, carry[1:]))
    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    logits = qmm(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_pool


def generate(params: Dict[str, Any], cfg: LlamaConfig, tokens: jnp.ndarray,
             max_new_tokens: int, temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Greedy (or temperature) generation, fully jittable: prefill then a
    ``lax.scan`` of decode steps (static trip count → one executable)."""
    b, s = tokens.shape
    cache = init_cache(cfg, b, max_len=min(cfg.max_seq_len,
                                           s + max_new_tokens))
    logits, cache, cache_len = prefill(params, cfg, tokens, cache)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def sample(logits, key):
        if temperature > 0.0:
            return jax.random.categorical(key, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    # Split once up front: one key for the prefill sample, distinct fresh
    # keys for the max_new_tokens-1 decode steps (never reuse a consumed key).
    all_keys = jax.random.split(rng, max_new_tokens)
    first = sample(logits, all_keys[0]).astype(jnp.int32)

    def body(carry, key):
        token, cache, cache_len = carry
        logits, cache, cache_len = decode_step(params, cfg, token, cache,
                                               cache_len)
        next_token = sample(logits, key).astype(jnp.int32)
        return (next_token, cache, cache_len), token

    keys = all_keys[1:]
    (last, _, _), out = lax.scan(body, (first, cache, cache_len),
                                 keys[:max_new_tokens - 1] if max_new_tokens > 1
                                 else keys[:0])
    out_tokens = jnp.concatenate(
        [out.T, last[:, None]], axis=1) if max_new_tokens > 1 else last[:, None]
    return out_tokens


def loss_fn(params: Dict[str, Any], cfg: LlamaConfig, tokens: jnp.ndarray,
            targets: jnp.ndarray, mesh=None) -> jnp.ndarray:
    """Next-token cross-entropy — the training-step objective used by
    gofr_tpu.parallel.train and the driver's dryrun_multichip."""
    logits = forward(params, cfg, tokens, mesh=mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()
