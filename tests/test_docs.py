"""Every ``python`` code block in docs/ must actually execute.

The reference treats its docs tree as a first-class product surface
(/root/reference/docs — 27 pages); this repo goes one further and CI-runs
the snippets.  Convention:

- ```` ```python ````        → executed, top to bottom, per page (blocks on
                               one page share a namespace so later blocks
                               can build on earlier ones).
- ```` ```python noexec ```` → shown but not executed (needs a live broker,
                               a real TPU slice, multiple processes, ...).
- any other fence (bash, text, json, yaml) → never executed.

``App.run`` is patched to a no-op so pages can end with the real entry
point without blocking the suite; everything before it runs for real
(sqlite ``:memory:``, in-process redis, the INMEM broker, JAX on the
virtual CPU mesh from conftest.py).
"""

import ast
import os
import pathlib
import re

import pytest

DOCS = pathlib.Path(__file__).resolve().parent.parent / "docs"

_FENCE = re.compile(r"^```(\w+)?([^\n`]*)$")


def _python_blocks(text: str):
    """Yield (first_line_number, source, executable) for each python fence
    (noexec blocks come back with executable=False: still syntax-checked)."""
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        match = _FENCE.match(lines[i].strip())
        if match and match.group(1):
            lang = match.group(1)
            info = (match.group(2) or "").strip()
            start = i + 1
            j = start
            while j < len(lines) and lines[j].strip() != "```":
                j += 1
            if lang == "python":
                yield start + 1, "\n".join(lines[start:j]), \
                    "noexec" not in info
            i = j + 1
        else:
            i += 1


def _pages():
    assert DOCS.is_dir(), "docs/ tree missing"
    return sorted(p for p in DOCS.rglob("*.md"))


@pytest.mark.parametrize("page", _pages(), ids=lambda p: str(p.relative_to(DOCS)))
def test_doc_snippets_execute(page, tmp_path, monkeypatch):
    blocks = list(_python_blocks(page.read_text()))
    if not blocks:
        pytest.skip("page has no executable python blocks")

    from gofr_tpu.app import App

    monkeypatch.setattr(App, "run", lambda self: None)
    monkeypatch.chdir(tmp_path)          # no ./configs: defaults only
    # isolate env mutations a page makes (os.environ[...] = ...)
    snapshot = dict(os.environ)
    namespace = {"__name__": f"docs:{page.name}"}
    try:
        for lineno, source, executable in blocks:
            # noexec blocks still get syntax-checked (fragments may use
            # top-level await, hence the flag)
            code = compile(source, f"{page}:{lineno}", "exec",
                           flags=ast.PyCF_ALLOW_TOP_LEVEL_AWAIT)
            if executable:
                exec(code, namespace)    # noqa: S102 — the point of the test
    finally:
        for key in set(os.environ) - set(snapshot):
            del os.environ[key]
        os.environ.update(snapshot)


def test_docs_tree_covers_app_surface():
    """Every public App method must be mentioned by some doc page —
    the VERDICT r3 'done' criterion for the docs tree."""
    from gofr_tpu.app import App

    corpus = "\n".join(p.read_text() for p in _pages())
    public = [name for name in vars(App)
              if not name.startswith("_") and callable(getattr(App, name))]
    missing = [name for name in public if name not in corpus]
    assert not missing, f"app surface undocumented: {missing}"


def test_docs_tree_shape():
    """Structural parity with the reference tree (quick-start /
    advanced-guide / references) plus the TPU-native section."""
    for section, minimum in [("quick-start", 6), ("advanced-guide", 19),
                             ("references", 2), ("tpu", 5)]:
        pages = list((DOCS / section).glob("*.md"))
        assert len(pages) >= minimum, (
            f"docs/{section}: {len(pages)} pages, want >= {minimum}")
