"""Async task discipline: spawn background coroutines without losing
their deaths.

``asyncio.ensure_future``/``create_task`` detaches a coroutine; if
nobody awaits it, an escaped exception is only reported by the loop's
lost-task handler at GC time — a crashed subscriber loop or cron firing
looks exactly like a quiet one. Every fire-and-forget spawn in this
framework goes through :func:`spawn_logged` instead (enforced by
graftcheck rule GT002, docs/references/static-analysis.md): the task
gets a done-callback that logs the exception and increments
``app_async_task_failures_total{task=...}``, so a dying background loop
shows up on a dashboard and not just in a post-mortem.
"""

from __future__ import annotations

import asyncio


def spawn_logged(coro, logger=None, name: str = "task",
                 metrics=None) -> asyncio.Task:
    """Schedule ``coro`` as a task whose failure is observed.

    Cancellation is not a failure (it is how this framework stops its
    loops); any other escaped exception is logged under ``name`` and
    counted in ``app_async_task_failures_total{task=name}``. Returns the
    task, so callers can still keep a handle for cancellation.
    """
    task = asyncio.ensure_future(coro)
    try:
        task.set_name(name)
    except AttributeError:  # pragma: no cover - py<3.8 compat
        pass

    def _observe(done: asyncio.Task) -> None:
        if done.cancelled():
            return
        exc = done.exception()
        if exc is None:
            return
        if logger is not None:
            logger.error("background task %s died: %r", name, exc)
        if metrics is not None:
            metrics.increment_counter("app_async_task_failures_total",
                                      task=name)

    task.add_done_callback(_observe)
    return task
