"""Pallas TPU flash attention (prefill path).

The hot op of the Llama/BERT serve path, written per
/opt/skills/guides/pallas_guide.md as the canonical 3D-grid flash kernel:
grid (batch·q-heads, q-blocks, k-blocks) with the k-axis innermost
("arbitrary" semantics), flash statistics (m, l, acc) carried across k
steps in fp32 VMEM scratch. Only one (block_q, D) Q tile and one
(block_k, D) K/V tile live in VMEM per step — tested to S=32K on a single
v5e core where the dense path's (S, S) scores cannot exist. Causal Q/K
block pairs that are fully masked are skipped with ``pl.when`` (≈2× FLOPs
saved at long S).

GQA is expressed in the K/V BlockSpec index maps: the flattened (batch·Hq)
grid axis maps onto (batch·Hkv), so grouped heads read the same K/V tile
without materialising a repeat.

``flash_attention`` falls back to the dense einsum implementation when
shapes don't meet TPU tiling constraints (head_dim % 128, seq % block) or
off-TPU — same numerics either way (tests assert equality against
ops.attention).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  block_q: int, block_k: int, num_k: int, causal: bool,
                  sm_scale: float):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip K blocks strictly after the Q block
    should_run = True
    if causal:
        should_run = ki * block_k < (qi + 1) * block_q

    @pl.when(should_run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * sm_scale       # (bq, D)
        k_blk = k_ref[0].astype(jnp.float32)              # (bk, D)
        v_blk = v_ref[0].astype(jnp.float32)
        scores = jnp.dot(q, k_blk.T,
                         preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_blk = scores.max(axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def _pallas_flash(q, k, v, causal: bool, block_q: int, block_k: int,
                  interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, seq_len, q_heads, head_dim = q.shape
    kv_heads = k.shape[2]
    group = q_heads // kv_heads
    num_k = seq_len // block_k
    # (B, S, H, D) → (B·H, S, D): head-major layout for per-head tiles
    qf = q.transpose(0, 2, 1, 3).reshape(batch * q_heads, seq_len, head_dim)
    kf = k.transpose(0, 2, 1, 3).reshape(batch * kv_heads, seq_len, head_dim)
    vf = v.transpose(0, 2, 1, 3).reshape(batch * kv_heads, seq_len, head_dim)

    def kv_index(bh, qi, ki):
        return (bh // group if group > 1 else bh, ki, 0)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, num_k=num_k,
        causal=causal, sm_scale=head_dim ** -0.5)
    compiler_params = None
    if not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        kernel,
        grid=(batch * q_heads, seq_len // block_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, head_dim), kv_index),
            pl.BlockSpec((1, block_k, head_dim), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(batch, q_heads, seq_len, head_dim).transpose(
        0, 2, 1, 3)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True, block_q: int = 512,
                    block_k: int = 512,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention with automatic dense fallback.

    q (B,S,Hq,D), k/v (B,S,Hkv,D) → (B,S,Hq,D). Uses the Pallas kernel
    when S divides the block sizes and D meets lane tiling; otherwise the
    dense GQA einsum from gofr_tpu.ops.attention (identical numerics).
    """
    seq_len, head_dim = q.shape[1], q.shape[3]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, seq_len)
    block_k = min(block_k, seq_len)
    tileable = (seq_len % block_q == 0 and seq_len % block_k == 0
                and head_dim % 128 == 0 and seq_len >= 128)
    if not tileable:
        # the dense path materializes a (B, H, S, S) score tensor: falling
        # back *silently* turns a shape mistake into an opaque device OOM
        # (r5: 16 GB at B=1,H=8,S=32K). Warn whenever that tensor alone
        # would exceed ~2 GB — it scales with batch and heads, not S
        # only. Scores/softmax accumulate in fp32 regardless of input
        # dtype (ops/attention.py), so size at 4 bytes per element.
        score_bytes = q.shape[0] * q.shape[2] * seq_len * seq_len * 4
        if score_bytes > 2 * 1024**3:
            import warnings

            warnings.warn(
                f"flash_attention falling back to DENSE attention with a "
                f"{score_bytes / 2**30:.1f} GB score tensor "
                f"(B={q.shape[0]}, H={q.shape[2]}, S={seq_len}; "
                f"untileable: head_dim {head_dim} must be a multiple of "
                f"128 and S divisible by the block sizes) — this may "
                f"exceed HBM", stacklevel=2)
        from gofr_tpu.ops.attention import attention, causal_mask
        mask = causal_mask(seq_len)[None, None, None] if causal else None
        return attention(q, k, v, mask)
    return _pallas_flash(q, k, v, causal, block_q, block_k, interpret)
