"""graftcheck rule engine: repo-aware AST analysis with pragmas + baseline.

The serving stack's latency story rests on invariants nothing at runtime
can enforce cheaply — the asyncio loop must never block on a device sync,
fire-and-forget tasks must not swallow exceptions, jitted call sites must
not smuggle in recompile hazards, donated buffers must never be read
again. graftcheck machine-checks them ahead of deploy; PR 3's compile
ledger can only *count* recompile storms after one already stalled
traffic.

Architecture:

- :class:`ModuleInfo` — one parsed source file: AST, source lines,
  ``# graftcheck: ignore[RULE]`` pragma sites, import-alias table, and a
  child→parent node map (``ast`` does not keep parents).
- :class:`Rule` — per-rule ``check_module`` (file-local findings),
  ``finalize`` (cross-file joins, e.g. GT005's registered-vs-observed
  metric join), and ``check_project`` (whole-program findings over the
  :class:`~gofr_tpu.analysis.project.ProjectGraph` — interprocedural
  reachability, value flow, lock discipline).
- :func:`run` — hash every file, hit the incremental cache when nothing
  changed (a warm tier1 rerun is a JSON load, no parsing), else parse,
  build the project graph once, apply rules, subtract pragma
  suppressions, then subtract the committed baseline (grandfathered
  findings are *pinned by count per fingerprint*: fixing one and adding
  another at the same site still fails).
- :func:`audit_pragmas` — re-run with suppression disabled and report
  every pragma whose rule no longer fires on its line (stale
  suppressions rot into false documentation).

Fingerprints deliberately exclude line numbers so unrelated edits above a
grandfathered finding don't resurrect it; they include the enclosing
function so two distinct sites never share one baseline slot by accident.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

ROOT = pathlib.Path(__file__).resolve().parents[2]
PACKAGE = ROOT / "gofr_tpu"
DEFAULT_BASELINE = ROOT / "scripts" / "graftcheck_baseline.json"
DEFAULT_CACHE = ROOT / ".graftcheck_cache.json"

_PRAGMA_RE = re.compile(
    r"#\s*graftcheck:\s*ignore\[([A-Za-z0-9_*,\s]+)\]")
_PRAGMA_FILE_RE = re.compile(
    r"#\s*graftcheck:\s*ignore-file\[([A-Za-z0-9_*,\s]+)\]")


def _comment_lines(source: str) -> Set[int]:
    """1-based line numbers holding a real ``#`` comment token.
    Falls back to every line on tokenize errors (never *lose* a
    pragma to an exotic encoding — the AST parse will complain about
    genuinely broken files anyway)."""
    import io
    import tokenize
    lines: Set[int] = set()
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                lines.add(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {i + 1 for i in range(source.count("\n") + 1)}
    return lines


@dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str            # "GT001"
    path: str            # repo-relative posix path
    line: int            # 1-based
    message: str         # human-readable, printed as path:line: RULE msg
    severity: str = "error"
    key: str = ""        # stable fingerprint token (defaults to message)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.key or self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class PragmaSite:
    """One ``# graftcheck: ignore[...]`` occurrence: where it sits,
    which rules it names, and which source lines it covers."""

    line: int                    # the pragma comment's own line
    tags: Set[str]               # rule ids, possibly "*"
    covered: Set[int]            # statement lines this site suppresses
    file_scope: bool = False     # ignore-file[...] form


def relpath_of(path: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(ROOT).as_posix()
    except ValueError:
        return path.as_posix()


class ModuleInfo:
    """A parsed module plus the derived tables every rule needs."""

    def __init__(self, path: pathlib.Path, source: str):
        self.path = path
        self.relpath = relpath_of(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.pragma_sites: List[PragmaSite] = []
        self.ignores: Dict[int, Set[str]] = {}
        self.file_ignores: Set[str] = set()
        # pragmas live in real comments only — a docstring that *documents*
        # the syntax (every rule module does) is not a suppression site.
        # Most files carry no pragma at all: a cheap substring probe
        # skips the tokenizer pass entirely for them.
        comment_lines = (_comment_lines(source)
                         if "graftcheck:" in source else set())
        for lineno in sorted(comment_lines):
            text = self.lines[lineno - 1]
            match = _PRAGMA_RE.search(text)
            if match:
                tags = {token.strip()
                        for token in match.group(1).split(",")}
                covered = {lineno}
                # a pragma on a comment-only line covers the statement it
                # precedes: skip past the rest of the comment block
                if text.lstrip().startswith("#"):
                    nxt = lineno
                    while nxt < len(self.lines) and (
                            not self.lines[nxt].strip()
                            or self.lines[nxt].lstrip().startswith("#")):
                        nxt += 1
                    if nxt < len(self.lines):
                        covered.add(nxt + 1)
                self.pragma_sites.append(
                    PragmaSite(line=lineno, tags=tags, covered=covered))
                for cov in covered:
                    self.ignores.setdefault(cov, set()).update(tags)
            match = _PRAGMA_FILE_RE.search(text)
            if match:
                tags = {token.strip()
                        for token in match.group(1).split(",")}
                self.pragma_sites.append(
                    PragmaSite(line=lineno, tags=tags, covered=set(),
                               file_scope=True))
                self.file_ignores.update(tags)
        # import alias tables: "np" -> "numpy", "sleep" -> "time.sleep"
        self.import_aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or
                                        alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.import_aliases[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_ignores or "*" in self.file_ignores:
            return True
        # check the finding's own line plus the line above: findings inside
        # a multi-line statement report their continuation line, one past
        # the statement start the pragma covers
        for lineno in (finding.line, finding.line - 1):
            tags = self.ignores.get(lineno, ())
            if finding.rule in tags or "*" in tags:
                return True
        return False

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Resolve ``np.asarray`` → ``numpy.asarray`` through the module's
        import aliases; plain names resolve through from-imports. Returns
        None for expressions rooted at something other than a Name."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.import_aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cursor = self.parents.get(node)
        while cursor is not None:
            if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cursor
            cursor = self.parents.get(cursor)
        return None


class Rule:
    """Base rule. Subclasses set ``rule_id``/``title`` and override
    ``check_module`` (file-local), ``finalize`` (cross-file joins),
    and/or ``check_project`` (whole-program, given a ProjectGraph)."""

    rule_id = "GT000"
    title = ""
    severity = "error"
    # cross-file joins (finalize over the full module set) give false
    # positives on partial sets, so --changed-only skips them entirely
    cross_file = False

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finalize(self, modules: Sequence[ModuleInfo]) -> Iterable[Finding]:
        return ()

    def check_project(self, project) -> Iterable[Finding]:
        return ()

    def config_fingerprint(self) -> str:
        """Cache-key contribution: rules whose output depends on config
        beyond their own source (GT005's docs catalog) override this."""
        return self.rule_id


@dataclass
class Report:
    """Outcome of one analysis run."""

    new_findings: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    # every live finding BEFORE pragma/baseline filtering — complete only
    # on a cold full run (cache-reused files contribute nothing here);
    # feeds audit_pragmas(raw_findings=...) so a pragma audit can ride a
    # scan the caller already paid for
    raw_findings: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    parse_errors: List[str] = field(default_factory=list)
    files_scanned: int = 0
    from_cache: bool = False
    cached_files: int = 0
    timings: Dict[str, float] = field(default_factory=dict)

    @property
    def exit_code(self) -> int:
        return 1 if (self.new_findings or self.parse_errors) else 0


def load_baseline(path: pathlib.Path) -> Dict[str, int]:
    try:
        payload = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {}
    counts = payload.get("counts", {})
    return {str(k): int(v) for k, v in counts.items()}


def write_baseline(path: pathlib.Path, findings: Sequence[Finding]) -> None:
    counts: Dict[str, int] = {}
    for finding in findings:
        counts[finding.fingerprint] = counts.get(finding.fingerprint, 0) + 1
    payload = {
        "_comment": (
            "graftcheck grandfathered findings, pinned by count per "
            "fingerprint. Regenerate with: "
            "python -m gofr_tpu.analysis --write-baseline. Shrink it when "
            "you fix one; never grow it for new code."),
        "version": 1,
        "counts": {k: counts[k] for k in sorted(counts)},
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def iter_python_files(paths: Sequence[pathlib.Path]) -> List[pathlib.Path]:
    out: List[pathlib.Path] = []
    for path in paths:
        if path.is_dir():
            out.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            out.append(path)
    return out


def _apply_baseline(report: Report, kept: List[Finding],
                    baseline: Optional[Dict[str, int]]) -> None:
    budget = dict(baseline or {})
    for finding in sorted(kept, key=lambda f: (f.path, f.line, f.rule)):
        if budget.get(finding.fingerprint, 0) > 0:
            budget[finding.fingerprint] -= 1
            report.baselined.append(finding)
        else:
            report.new_findings.append(finding)
    report.stale_baseline = sorted(
        fp for fp, remaining in budget.items() if remaining > 0)


def _run_rules(rules: Sequence[Rule], modules: List[ModuleInfo],
               interprocedural: bool, timings: Dict[str, float],
               skip_cross_file: bool = False) -> List[Finding]:
    from gofr_tpu.analysis.project import ProjectGraph

    raw: List[Finding] = []
    t0 = time.perf_counter()
    project = ProjectGraph(modules, cross_module=interprocedural)
    timings["project-graph"] = \
        timings.get("project-graph", 0.0) + time.perf_counter() - t0
    for rule in rules:
        if skip_cross_file and rule.cross_file:
            continue
        t0 = time.perf_counter()
        for module in modules:
            raw.extend(rule.check_module(module))
        if not skip_cross_file:
            raw.extend(rule.finalize(modules))
        raw.extend(rule.check_project(project))
        timings[rule.rule_id] = \
            timings.get(rule.rule_id, 0.0) + time.perf_counter() - t0
    return raw


def run(paths: Optional[Sequence[pathlib.Path]] = None,
        rules: Optional[Sequence[Rule]] = None,
        baseline: Optional[Dict[str, int]] = None,
        *,
        interprocedural: bool = True,
        cache_path: Optional[pathlib.Path] = None,
        restrict: Optional[Set[str]] = None) -> Report:
    """Run ``rules`` over every ``*.py`` under ``paths``.

    ``baseline`` maps fingerprints to grandfathered counts; within one
    fingerprint the first N findings are baselined and the rest are new.
    ``interprocedural=False`` forces the v1 module-local call graph
    (regression tests pin what project mode buys). ``cache_path``
    enables the incremental cache; ``restrict`` (a set of repo-relative
    paths) is the ``--changed-only`` fast path — listed files are
    analyzed live, everything else reuses its SHA-matched cache entry.
    """
    from gofr_tpu.analysis import cache as cache_mod

    if rules is None:
        from gofr_tpu.analysis.rules import default_rules
        rules = default_rules()
    if paths is None:
        paths = [PACKAGE]
    report = Report()

    sources: Dict[pathlib.Path, str] = {}
    shas: Dict[str, str] = {}
    rel_to_path: Dict[str, pathlib.Path] = {}
    for path in iter_python_files(paths):
        try:
            text = path.read_text(encoding="utf-8")
        except OSError as exc:
            report.parse_errors.append(f"{path}: unparseable: {exc}")
            continue
        rel = relpath_of(path)
        sources[path] = text
        shas[rel] = cache_mod.sha_text(text)
        rel_to_path[rel] = path

    cache = (cache_mod.AnalysisCache(cache_path)
             if cache_path is not None else None)
    rkey = cache_mod.ruleset_key(rules)
    pkey = cache_mod.project_key(rkey, shas, interprocedural)

    # -- full warm hit: the entire report is a JSON load --------------------
    if cache is not None and restrict is None \
            and not report.parse_errors and cache.matches_project(pkey):
        entries = cache.all_entries()
        if all(rel in entries and entries[rel].get("sha") == shas[rel]
               for rel in shas):
            kept: List[Finding] = []
            for rel in sorted(shas):
                entry = entries[rel]
                kept.extend(cache_mod.decode_findings(
                    entry.get("findings", []), Finding))
                report.suppressed += int(entry.get("suppressed", 0))
            report.files_scanned = len(shas)
            report.cached_files = len(shas)
            report.from_cache = True
            _apply_baseline(report, kept, baseline)
            return report

    # -- choose live vs cache-reused files ----------------------------------
    live_rels = set(shas)
    reused: Dict[str, dict] = {}
    if restrict is not None and cache is not None \
            and cache.matches_ruleset(rkey):
        for rel in shas:
            if rel in restrict:
                continue
            entry = cache.file_entry(rel, shas[rel])
            if entry is not None:
                reused[rel] = entry
                live_rels.discard(rel)

    modules: List[ModuleInfo] = []
    for rel in sorted(live_rels):
        path = rel_to_path[rel]
        try:
            modules.append(ModuleInfo(path, sources[path]))
        except SyntaxError as exc:
            report.parse_errors.append(f"{path}: unparseable: {exc}")
    report.files_scanned = len(modules) + len(reused)
    report.cached_files = len(reused)

    raw = _run_rules(rules, modules, interprocedural, report.timings,
                     skip_cross_file=restrict is not None)
    report.raw_findings = list(raw)

    module_by_rel = {m.relpath: m for m in modules}
    kept = []
    suppressed_by_rel: Dict[str, int] = {}
    for finding in raw:
        module = module_by_rel.get(finding.path)
        if module is not None and module.suppressed(finding):
            report.suppressed += 1
            suppressed_by_rel[finding.path] = \
                suppressed_by_rel.get(finding.path, 0) + 1
        else:
            kept.append(finding)

    for rel, entry in reused.items():
        kept.extend(cache_mod.decode_findings(
            entry.get("findings", []), Finding))
        report.suppressed += int(entry.get("suppressed", 0))

    _apply_baseline(report, kept, baseline)

    # -- persist: only exact full runs write the cache ----------------------
    if cache is not None and restrict is None and not report.parse_errors:
        by_path = cache_mod.group_by_path(
            [f for f in kept if f.path in module_by_rel])
        files = {}
        for rel in shas:
            if rel not in module_by_rel:
                continue
            files[rel] = cache_mod.build_file_entry(
                shas[rel], by_path.get(rel, []),
                suppressed_by_rel.get(rel, 0))
        if len(files) == len(shas):
            cache.save(rkey, pkey, files)
    return report


@dataclass
class StalePragma:
    """A suppression whose rule no longer fires on its line."""

    path: str
    line: int
    tags: Set[str]
    file_scope: bool = False

    def render(self) -> str:
        scope = "ignore-file" if self.file_scope else "ignore"
        tags = ",".join(sorted(self.tags))
        return (f"{self.path}:{self.line}: stale pragma "
                f"{scope}[{tags}] — no {tags} finding is suppressed "
                f"here anymore; delete it")


def audit_pragmas(paths: Optional[Sequence[pathlib.Path]] = None,
                  rules: Optional[Sequence[Rule]] = None,
                  interprocedural: bool = True,
                  raw_findings: Optional[Sequence[Finding]] = None,
                  ) -> List[StalePragma]:
    """Find ``# graftcheck: ignore[...]`` pragmas that suppress nothing:
    run every rule with suppression disabled, then check each pragma
    site against the raw findings it claims to cover. A stale pragma is
    worse than none — it documents a hazard that is not there and hides
    the next real one someone writes on that line.

    ``raw_findings`` skips the rule pass entirely: pass
    ``Report.raw_findings`` from a COLD full run over the same paths
    (a warm-cache report carries none and every pragma would look
    stale). Only pragma-bearing files are parsed in that mode."""
    if rules is None:
        from gofr_tpu.analysis.rules import default_rules
        rules = default_rules()
    if paths is None:
        paths = [PACKAGE]
    modules: List[ModuleInfo] = []
    for path in iter_python_files(paths):
        try:
            source = path.read_text(encoding="utf-8")
            if raw_findings is not None and "graftcheck:" not in source:
                continue
            modules.append(ModuleInfo(path, source))
        except (OSError, SyntaxError):
            continue
    raw = (list(raw_findings) if raw_findings is not None
           else _run_rules(rules, modules, interprocedural, {}))
    by_rel: Dict[str, List[Finding]] = {}
    for finding in raw:
        by_rel.setdefault(finding.path, []).append(finding)

    stale: List[StalePragma] = []
    for module in modules:
        findings = by_rel.get(module.relpath, [])
        for site in module.pragma_sites:
            if site.file_scope:
                fired = any(f.rule in site.tags or "*" in site.tags
                            for f in findings)
            else:
                lines = set(site.covered) | {c + 1 for c in site.covered}
                fired = any(
                    (f.rule in site.tags or "*" in site.tags)
                    and f.line in lines
                    for f in findings)
            if not fired:
                stale.append(StalePragma(
                    path=module.relpath, line=site.line,
                    tags=site.tags, file_scope=site.file_scope))
    return stale
