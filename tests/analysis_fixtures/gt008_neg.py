"""GT008 negative fixture: bounded labels and the exemplar channel."""


def good_bounded_labels(metrics, replica, slot):
    metrics.increment_counter("app_requests_total", replica=replica.name)
    metrics.set_gauge("app_occupancy", 0.5, model=slot.model, cls=slot.cls)
    metrics.increment_counter("app_dropped_total", reason="expired")


def good_exemplar_carries_trace(metrics, span):
    # exemplars are the sanctioned channel for per-request ids
    metrics.record_histogram("app_ttft_seconds", 0.1,
                             exemplar=span.trace_id)


def good_pragma(metrics, tenant_id):
    metrics.increment_counter(  # graftcheck: ignore[GT008]
        "app_tenant_requests_total", session_id=tenant_id)
