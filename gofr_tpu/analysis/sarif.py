"""SARIF 2.1.0 emitter for graftcheck reports.

CI annotates PRs from a standard artifact instead of scraping stderr:
``python -m gofr_tpu.analysis --sarif out.sarif`` (tier1.sh writes one
on every run). Only *new* findings become ``results`` — baselined and
pragma-suppressed findings are the accepted state of the tree, not
review items; parse errors surface as tool execution notifications so
a broken file fails visibly in the same artifact.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, List, Sequence

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

_LEVELS = {"error": "error", "warning": "warning", "note": "note"}


def report_to_sarif(report, rules: Sequence[object]) -> Dict:
    rule_meta = []
    seen = set()
    for rule in rules:
        rule_id = getattr(rule, "rule_id", None)
        if rule_id is None or rule_id in seen:
            continue
        seen.add(rule_id)
        rule_meta.append({
            "id": rule_id,
            "name": getattr(rule, "title", "") or rule_id,
            "defaultConfiguration": {
                "level": _LEVELS.get(
                    getattr(rule, "severity", "error"), "error")},
            "helpUri": ("https://example.invalid/docs/references/"
                        "static-analysis.md"),
        })

    results: List[Dict] = []
    for finding in report.new_findings:
        results.append({
            "ruleId": finding.rule,
            "level": _LEVELS.get(finding.severity, "error"),
            "message": {"text": finding.message},
            "partialFingerprints": {
                "graftcheck/v1": finding.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, finding.line)},
                },
            }],
        })

    notifications = [{
        "level": "error",
        "message": {"text": text},
    } for text in report.parse_errors]

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftcheck",
                "informationUri": ("https://example.invalid/docs/"
                                   "references/static-analysis.md"),
                "rules": rule_meta,
            }},
            "results": results,
            "invocations": [{
                "executionSuccessful": report.exit_code == 0,
                "toolExecutionNotifications": notifications,
            }],
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
        }],
    }


def write_sarif(path: pathlib.Path, report, rules: Sequence[object]) -> None:
    payload = report_to_sarif(report, rules)
    pathlib.Path(path).write_text(
        json.dumps(payload, indent=2, sort_keys=False) + "\n",
        encoding="utf-8")
