"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

The image boots with ``JAX_PLATFORMS=axon`` (one real TPU chip behind a
relay); unit tests must instead exercise the multi-chip sharding paths
(gofr_tpu.parallel) on a virtual 8-device CPU mesh — the "miniredis of
XLA" strategy from SURVEY.md §4.  ``jax.config.update`` beats the env var
even though the axon sitecustomize imported jax at interpreter start.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def mock_container():
    from gofr_tpu.container import new_mock_container
    return new_mock_container()


@pytest.fixture(scope="session")
def cpu_mesh():
    """2×4 dp×tp mesh over the 8 virtual CPU devices."""
    return jax.make_mesh((2, 4), ("dp", "tp"))
