"""MQTT 3.1.1 backend — pure-Python wire client, no paho dependency.

Capability parity with ``pkg/gofr/datasource/pubsub/mqtt`` (mqtt.go:30-60:
per-topic channel map, QoS/retained config, default public broker when
unconfigured; subscribe via callback → buffered channel). The reference
wraps paho; this zero-egress image has no MQTT driver, so the client
implements the 3.1.1 wire protocol directly: CONNECT/CONNACK, PUBLISH
(QoS 0/1), SUBSCRIBE/SUBACK, PINGREQ keepalive, DISCONNECT.

Threading model: one reader thread decodes packets and fans PUBLISHes out
to per-topic thread-safe queues; ``subscribe`` awaits a queue via the
default executor so the event loop never blocks. A dead reader (broker
restart, dropped TCP) turns into a reconnect loop with exponential
backoff that re-subscribes every known topic — subscriptions made before
the outage survive it.

Trace propagation: MQTT 3.1.1 has no user properties (those are 5.0), so
when a span is active at publish time the W3C traceparent rides in the
opt-in byte envelope from ``base.py`` — same carrier as Kafka's
header-less message-set v1 — and the reader surfaces it as message
metadata. Untraced publishes keep the wire payload byte-identical.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from gofr_tpu.datasource.pubsub.base import (Message, PubSub,
                                             decode_trace_envelope,
                                             encode_trace_envelope)

# packet types << 4
CONNECT, CONNACK = 0x10, 0x20
PUBLISH, PUBACK = 0x30, 0x40
SUBSCRIBE, SUBACK = 0x82, 0x90  # SUBSCRIBE requires flags 0b0010
UNSUBSCRIBE = 0xA2
PINGREQ, PINGRESP = 0xC0, 0xD0
DISCONNECT = 0xE0

DEFAULT_PUBLIC_BROKER = "broker.hivemq.com"  # mqtt.go:19-22


class MQTTError(Exception):
    pass


def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        digit = n % 128
        n //= 128
        out.append(digit | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _encode_string(s: str) -> bytes:
    raw = s.encode()
    return struct.pack(">H", len(raw)) + raw


def encode_connect(client_id: str, keepalive: int, username: str = "",
                   password: str = "", clean: bool = True) -> bytes:
    flags = 0x02 if clean else 0x00
    payload = _encode_string(client_id)
    if username:
        flags |= 0x80
        payload += _encode_string(username)
        if password:
            flags |= 0x40
            payload += _encode_string(password)
    var_header = (_encode_string("MQTT") + bytes([4, flags])
                  + struct.pack(">H", keepalive))
    body = var_header + payload
    return bytes([CONNECT]) + _encode_varint(len(body)) + body


def encode_publish(topic: str, payload: bytes, packet_id: int = 0,
                   qos: int = 0, retain: bool = False) -> bytes:
    header = PUBLISH | (qos << 1) | (1 if retain else 0)
    body = _encode_string(topic)
    if qos > 0:
        body += struct.pack(">H", packet_id)
    body += payload
    return bytes([header]) + _encode_varint(len(body)) + body


def encode_subscribe(packet_id: int, topic: str, qos: int = 0) -> bytes:
    body = struct.pack(">H", packet_id) + _encode_string(topic) + bytes([qos])
    return bytes([SUBSCRIBE]) + _encode_varint(len(body)) + body


def decode_publish(flags: int, body: bytes) -> Tuple[str, bytes, int, int]:
    """→ (topic, payload, qos, packet_id)."""
    qos = (flags >> 1) & 0x03
    topic_len = struct.unpack_from(">H", body, 0)[0]
    topic = body[2:2 + topic_len].decode()
    offset = 2 + topic_len
    packet_id = 0
    if qos > 0:
        packet_id = struct.unpack_from(">H", body, offset)[0]
        offset += 2
    return topic, body[offset:], qos, packet_id


class MQTTClient(PubSub):
    def __init__(self, config, logger, metrics, tracer=None):
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self.host = config.get_or_default("MQTT_HOST", DEFAULT_PUBLIC_BROKER)
        self.port = config.get_int("MQTT_PORT", 1883)
        self.qos = config.get_int("MQTT_QOS", 0)
        self.keepalive = config.get_int("MQTT_KEEPALIVE", 30)
        self.client_id = config.get_or_default(
            "MQTT_CLIENT_ID", f"gofr-tpu-{int(time.time())}")
        self._username = config.get_or_default("MQTT_USER", "")
        self._password = config.get_or_default("MQTT_PASSWORD", "")
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._packet_id = 0
        self._queues: Dict[str, "queue.Queue[Optional[Message]]"] = {}
        self._subscribed: Dict[str, bool] = {}
        self._connected = threading.Event()
        # single-reconnector guard: a failed redial can orphan a reader
        # thread whose own death must not start a second reconnect loop
        self._reconnecting = threading.Lock()
        self._closed = False
        self._connect()

    # -- connection ---------------------------------------------------------
    def _connect(self) -> None:
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=10.0)
        self._sock.sendall(encode_connect(self.client_id, self.keepalive,
                                          self._username, self._password))
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="mqtt-reader")
        self._reader.start()
        if not self._connected.wait(10.0):
            raise MQTTError("CONNACK timeout")
        self._pinger = threading.Thread(target=self._ping_loop, daemon=True,
                                        name="mqtt-ping")
        self._pinger.start()
        for topic in list(self._subscribed):
            self._send_subscribe(topic)
        self.logger.info("mqtt connected %s:%d as %s", self.host, self.port,
                         self.client_id)

    def _next_packet_id(self) -> int:
        with self._lock:
            self._packet_id = (self._packet_id % 65535) + 1
            return self._packet_id

    def _send(self, data: bytes) -> None:
        with self._lock:
            if self._sock is None:
                raise MQTTError("not connected")
            self._sock.sendall(data)

    def _ping_loop(self) -> None:
        interval = max(5, self.keepalive // 2)
        while not self._closed:
            time.sleep(interval)
            try:
                self._send(bytes([PINGREQ, 0]))
            except Exception:
                return

    # -- packet reader ------------------------------------------------------
    def _read_exact(self, n: int) -> bytes:
        data = b""
        while len(data) < n:
            chunk = self._sock.recv(n - len(data))
            if not chunk:
                raise MQTTError("connection closed")
            data += chunk
        return data

    def _read_varint(self) -> int:
        value, multiplier = 0, 1
        while True:
            byte = self._read_exact(1)[0]
            value += (byte & 0x7F) * multiplier
            if not byte & 0x80:
                return value
            multiplier *= 128

    def _read_loop(self) -> None:
        try:
            while not self._closed:
                first = self._read_exact(1)[0]
                length = self._read_varint()
                body = self._read_exact(length) if length else b""
                self._on_packet(first, body)
        except Exception as exc:
            if self._closed:
                return
            # dead reader ≠ dead client: reconnect with backoff and
            # re-subscribe every known topic (see _connect). Only a
            # deliberate close() terminates subscribers with the None
            # sentinel — a broker restart must be invisible to them.
            if not self._reconnecting.acquire(blocking=False):
                return  # another (newer) reader already owns recovery
            try:
                self.logger.error("mqtt reader died (reconnecting): %r",
                                  exc)
                self._connected.clear()
                self._close_sock()
                self._reconnect_loop()
            finally:
                self._reconnecting.release()

    def _close_sock(self) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _reconnect_loop(self) -> None:
        """Runs on the dying reader thread: redial until the broker is
        back (or close()), then hand off to the fresh reader ``_connect``
        spawns. ``_connect`` re-subscribes ``self._subscribed``, so every
        topic registered before the outage keeps flowing."""
        backoff = 0.5
        while not self._closed:
            try:
                self._connect()
                return
            except Exception as exc:
                self._close_sock()  # orphan a half-open dial cleanly
                self.logger.warn(
                    "mqtt reconnect to %s:%d failed (retrying in %.1fs): "
                    "%r", self.host, self.port, backoff, exc)
                deadline = time.monotonic() + backoff
                while not self._closed \
                        and time.monotonic() < deadline:
                    time.sleep(0.1)
                backoff = min(backoff * 2, 30.0)

    def _on_packet(self, first: int, body: bytes) -> None:
        packet_type = first & 0xF0
        if packet_type == CONNACK:
            if len(body) >= 2 and body[1] == 0:
                self._connected.set()
            else:
                self.logger.error("mqtt CONNACK refused: %r", body)
            return
        if packet_type == PUBLISH:
            topic, payload, qos, packet_id = decode_publish(first & 0x0F,
                                                            body)
            if qos == 1:
                self._send(bytes([PUBACK, 2]) + struct.pack(">H", packet_id))
            traceparent, payload = decode_trace_envelope(payload)
            metadata = {"traceparent": traceparent} if traceparent else None
            message = Message(topic, payload, metadata=metadata,
                              committer=lambda: None)
            self._topic_queue(topic).put(message)
            return
        # SUBACK / PUBACK / PINGRESP need no action for QoS ≤ 1

    # -- PubSub contract ----------------------------------------------------
    def _topic_queue(self, topic: str) -> "queue.Queue":
        q = self._queues.get(topic)
        if q is None:
            q = queue.Queue(maxsize=65536)
            self._queues[topic] = q
        return q

    def _send_subscribe(self, topic: str) -> None:
        self._send(encode_subscribe(self._next_packet_id(), topic, self.qos))

    def publish(self, topic: str, payload: bytes, key: bytes = b"") -> None:
        self.metrics.increment_counter("app_pubsub_publish_total_count",
                                       topic=topic)
        # MQTT 3.1.1 has no user properties, so an in-flight trace rides
        # in the opt-in byte envelope (base.py). Publishes outside a span
        # keep the wire payload byte-for-byte unchanged.
        span = None
        if self.tracer is not None:
            from gofr_tpu.trace import current_span, format_traceparent
            if current_span() is not None:
                span = self.tracer.start_span("pubsub.publish")
                span.set_attribute("topic", topic)
                span.set_attribute("backend", "MQTT")
                payload = encode_trace_envelope(format_traceparent(span),
                                                payload)
        try:
            packet_id = self._next_packet_id() if self.qos else 0
            self._send(encode_publish(topic, payload, packet_id, self.qos))
        except Exception:
            if span is not None:
                span.set_status("ERROR")
            raise
        finally:
            if span is not None:
                span.finish()
        self.metrics.increment_counter("app_pubsub_publish_success_count",
                                       topic=topic)

    async def subscribe(self, topic: str) -> Optional[Message]:
        import asyncio
        if topic not in self._subscribed:
            self._subscribed[topic] = True
            self._send_subscribe(topic)
        self.metrics.increment_counter("app_pubsub_subscribe_total_count",
                                       topic=topic)
        q = self._topic_queue(topic)
        message = await asyncio.get_running_loop().run_in_executor(
            None, q.get)
        if message is not None:
            self.metrics.increment_counter(
                "app_pubsub_subscribe_success_count", topic=topic)
        return message

    def create_topic(self, topic: str) -> None:
        pass  # MQTT topics are implicit

    def delete_topic(self, topic: str) -> None:
        self._queues.pop(topic, None)

    def health_check(self) -> dict:
        up = self._connected.is_set() and not self._closed
        return {"status": "UP" if up else "DOWN",
                "details": {"backend": "MQTT",
                            "host": f"{self.host}:{self.port}",
                            "client_id": self.client_id}}

    def close(self) -> None:
        self._closed = True
        try:
            if self._sock is not None:
                self._sock.sendall(bytes([DISCONNECT, 0]))
                self._sock.close()
        except Exception:
            pass
        for q in self._queues.values():
            q.put(None)
