"""Prometheus text exposition format (v0.0.4) rendered from the Manager store.

The reference exports via the OTel->Prometheus bridge
(metrics/exporters/exporter.go:14-29); here we render the format directly —
fewer moving parts and no dependency on prometheus_client internals.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from gofr_tpu.metrics.manager import Manager

_KIND_TO_PROM = {
    "counter": "counter",
    "updown": "gauge",
    "gauge": "gauge",
    "histogram": "histogram",
}


def _fmt_labels(key) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_float(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _fmt_exemplar(exemplar) -> str:
    """OpenMetrics exemplar suffix: `` # {trace_id="..."} value timestamp``
    (OpenMetrics 1.0 §exemplars). Appended to ``_bucket`` sample lines so a
    latency histogram links back to one concrete traced request."""
    labels, value, ts = exemplar
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return f" # {{{inner}}} {_fmt_float(value)} {ts:.3f}"


def render_prometheus(manager: "Manager") -> str:
    lines = []
    for name, metric in sorted(manager.snapshot().items()):
        prom_kind = _KIND_TO_PROM[metric.kind]
        if metric.desc:
            lines.append(f"# HELP {name} {metric.desc}")
        lines.append(f"# TYPE {name} {prom_kind}")
        if metric.kind == "histogram":
            for key, state in sorted(metric.series.items()):
                assert isinstance(state, dict)
                exemplars = state.get("exemplars", {})
                cumulative = 0
                for i, (bound, count) in enumerate(
                        zip(metric.buckets, state["buckets"])):
                    cumulative += count
                    le_labels = dict(key)
                    le_labels["le"] = _fmt_float(bound)
                    line = (
                        f"{name}_bucket{_fmt_labels(tuple(sorted(le_labels.items())))} {cumulative}"
                    )
                    if i in exemplars:
                        line += _fmt_exemplar(exemplars[i])
                    lines.append(line)
                inf_labels = dict(key)
                inf_labels["le"] = "+Inf"
                line = (
                    f"{name}_bucket{_fmt_labels(tuple(sorted(inf_labels.items())))} {state['count']}"
                )
                if len(metric.buckets) in exemplars:
                    line += _fmt_exemplar(exemplars[len(metric.buckets)])
                lines.append(line)
                lines.append(f"{name}_sum{_fmt_labels(key)} {_fmt_float(state['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(key)} {state['count']}")
        else:
            for key, value in sorted(metric.series.items()):
                lines.append(f"{name}{_fmt_labels(key)} {_fmt_float(float(value))}")  # type: ignore[arg-type]
    return "\n".join(lines) + "\n"
