"""GT009 positive fixture: re-entrant cron handlers.

Parsed by graftcheck in tests, never imported.
"""


async def probe_sweep(ctx):
    # unbounded await, no guard: a slow sweep overlaps the next firing
    for replica in ctx.container.cluster.replicas():
        await replica.observe()


async def rebalance(ctx):
    # guard exists but sits AFTER the first await — two firings both
    # pass the await before either sets the flag
    snapshot = await ctx.container.cluster.snapshot()
    if snapshot.busy:
        return
    await ctx.container.cluster.rebalance(snapshot)


def wire(app):
    app.add_cron_job("* * * * *", "probe-sweep", probe_sweep)
    app.crontab.add_job("*/5 * * * *", "rebalance", func=rebalance)
