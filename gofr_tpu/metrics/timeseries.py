"""Bounded in-process time-series store: the telemetry plane's memory.

Every debug surface so far (statusz, varz, xlaz, clusterz, hbmz) is a
point-in-time snapshot, and the windowed digests in ``digest.py`` forget
everything past one window — so nothing can answer *how did goodput,
padding ratio, or queue depth move over the last ten minutes*. This
module is that history, with a hard memory ceiling:

- a fixed-cadence sampler (``TELEMETRY_INTERVAL_S``, default 1s)
  snapshots a registered set of signal callables into per-signal ring
  buffers with multi-resolution downsampling — 1s x 600, 10s x 360,
  60s x 240 buckets per signal (10 minutes at full rate, 1 hour at 10s,
  4 hours at 60s). Memory is a documented constant: each bucket is one
  ``[start, count, total, min, max]`` aggregate, so a signal costs at
  most ``1200`` buckets regardless of uptime (plus its share of the
  600-sample raw delta log shared by all signals).
- a robust z-score change-point detector per signal (median/MAD over
  the trailing 1s tier, hysteresis like the SLO watchdog) that
  annotates the series, emits ``app_tpu_anomaly_total{signal,direction}``
  and — for signals registered with a ``watch`` direction — feeds the
  watchdog so a goodput cliff flips health DEGRADED with the offending
  signal *named* in statusz.
- a cursor-based delta export (:meth:`delta`) so fleet probes pull only
  samples they have not seen, with a bounded payload — the input the
  fleet series rollup (``tpu/fleet.py``) and the autoscaler's
  short-window means build on.
- a flight-recorder-style ring of sampled decode-tick anatomies
  (:meth:`note_tick`), fed by the engine every
  ``TELEMETRY_TICK_SAMPLE``-th tick — what a p99 tick spends its time
  on, without firing the heavyweight single-flight profiler.

Like every windowed structure in the repo, all entry points take an
optional explicit ``now`` (monotonic seconds) so tests drive the clock.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "SeriesRing",
    "RobustDetector",
    "TimeSeriesStore",
    "new_timeseries",
    "register_default_signals",
    "TIERS",
    "MAX_BUCKETS_PER_SIGNAL",
]

# (tier name, bucket seconds, ring capacity). The capacities are the
# memory contract: a signal can never hold more than
# ``MAX_BUCKETS_PER_SIGNAL`` aggregates, whatever the process uptime.
TIERS: Tuple[Tuple[str, float, int], ...] = (
    ("1s", 1.0, 600),
    ("10s", 10.0, 360),
    ("60s", 60.0, 240),
)
MAX_BUCKETS_PER_SIGNAL = sum(cap for _, _, cap in TIERS)

# raw 1s samples kept for cursor-based fleet delta pulls (10 minutes)
DELTA_LOG_CAPACITY = 600
# samples shipped per delta() answer — bounds the probe payload even
# after a long probe outage (the puller resumes with reset=True)
DELTA_MAX_SAMPLES = 120

_SPARK_BLOCKS = " ▁▂▃▄▅▆▇█"


class SeriesRing:
    """One resolution tier of one signal: a fixed-capacity ring of
    aligned bucket aggregates ``[bucket_start, count, total, min, max]``.

    Buckets align on ``int(now // bucket_s) * bucket_s`` so every signal
    sampled at the same instant lands in the same bucket — the alignment
    the timez endpoint and the fleet rollup rely on."""

    __slots__ = ("bucket_s", "capacity", "_buckets")

    def __init__(self, bucket_s: float, capacity: int):
        self.bucket_s = float(bucket_s)
        self.capacity = int(capacity)
        self._buckets: deque = deque(maxlen=self.capacity)

    def add(self, value: float, now: float) -> None:
        start = int(now // self.bucket_s) * self.bucket_s
        if self._buckets and self._buckets[-1][0] == start:
            bucket = self._buckets[-1]
            bucket[1] += 1
            bucket[2] += value
            if value < bucket[3]:
                bucket[3] = value
            if value > bucket[4]:
                bucket[4] = value
        else:
            # deque(maxlen) evicts the oldest bucket for us
            self._buckets.append([start, 1, value, value, value])

    def __len__(self) -> int:
        return len(self._buckets)

    def points(self, limit: Optional[int] = None) -> List[Dict[str, float]]:
        """Oldest-first ``{t, mean, min, max, count}`` per bucket."""
        buckets = list(self._buckets)
        if limit is not None:
            buckets = buckets[-int(limit):]
        return [{"t": b[0], "mean": b[2] / b[1], "min": b[3],
                 "max": b[4], "count": b[1]} for b in buckets]

    def means(self, limit: Optional[int] = None) -> List[Tuple[float, float]]:
        buckets = list(self._buckets)
        if limit is not None:
            buckets = buckets[-int(limit):]
        return [(b[0], b[2] / b[1]) for b in buckets]

    def window_mean(self, window_s: float, now: float) -> Optional[float]:
        """Count-weighted mean of samples in ``[now - window_s, now]``;
        None when the window holds nothing."""
        cutoff = now - window_s
        count = 0
        total = 0.0
        for b in reversed(self._buckets):
            if b[0] + self.bucket_s < cutoff:
                break
            count += b[1]
            total += b[2]
        if count == 0:
            return None
        return total / count


class RobustDetector:
    """Per-signal change-point detector: robust z-score with hysteresis.

    Each observation is scored against the median/MAD of the trailing
    baseline (the signal's recent 1s bucket means, excluding the newest
    ``guard`` buckets so the anomaly itself never poisons its own
    baseline). ``trigger_after`` consecutive outliers in the same
    direction raise the anomaly; ``clear_after`` consecutive in-band
    observations clear it — the same streak shape as the SLO watchdog,
    so one noisy sample never flips anything."""

    __slots__ = ("threshold", "min_baseline", "guard", "trigger_after",
                 "clear_after", "active", "_hot_streak", "_hot_direction",
                 "_calm_streak", "last_z")

    def __init__(self, threshold: float = 6.0, min_baseline: int = 20,
                 guard: int = 5, trigger_after: int = 3,
                 clear_after: int = 5):
        self.threshold = float(threshold)
        self.min_baseline = int(min_baseline)
        self.guard = int(guard)
        self.trigger_after = max(1, int(trigger_after))
        self.clear_after = max(1, int(clear_after))
        self.active: Optional[Dict[str, Any]] = None
        self._hot_streak = 0
        self._hot_direction: Optional[str] = None
        self._calm_streak = 0
        self.last_z = 0.0

    def observe(self, value: float, ring: SeriesRing,
                now: float) -> Optional[Dict[str, Any]]:
        """Score one sample; returns a transition event dict when the
        anomaly state changed (``state`` raised|cleared), else None."""
        means = [m for _, m in ring.means()]
        baseline = means[:-self.guard] if self.guard else means
        if len(baseline) < self.min_baseline:
            return None
        ordered = sorted(baseline)
        mid = len(ordered) // 2
        median = ordered[mid] if len(ordered) % 2 else \
            (ordered[mid - 1] + ordered[mid]) / 2.0
        deviations = sorted(abs(m - median) for m in baseline)
        mad = deviations[len(deviations) // 2]
        if mad == 0.0 and median == 0.0:
            # dead-flat zero baseline: an idle signal starting to move
            # is cold start, not a change point — with no variance and
            # no level there is nothing to score it against, and the
            # epsilon floor would turn the first request after idle
            # into a z in the hundreds of thousands
            self._hot_streak = 0
            self._hot_direction = None
            return None
        # MAD floor: a flat baseline (mad == 0) must not turn every
        # wiggle into an infinite z — 5% of the median's magnitude (or
        # an absolute epsilon for signals hovering at zero) is the
        # smallest move worth scoring
        scale = max(mad / 0.6745, abs(median) * 0.05, 1e-6)
        z = (value - median) / scale
        self.last_z = z
        direction = "up" if z > 0 else "down"
        if abs(z) >= self.threshold:
            if self._hot_direction == direction:
                self._hot_streak += 1
            else:
                self._hot_direction = direction
                self._hot_streak = 1
            self._calm_streak = 0
            if self.active is None and \
                    self._hot_streak >= self.trigger_after:
                self.active = {"direction": direction, "since": now,
                               "z": round(z, 2), "baseline": round(median, 6)}
                return {"state": "raised", "direction": direction,
                        "z": round(z, 2), "at": now}
            if self.active is not None:
                self.active["z"] = round(z, 2)
        else:
            self._hot_streak = 0
            self._hot_direction = None
            if self.active is not None:
                self._calm_streak += 1
                if self._calm_streak >= self.clear_after:
                    cleared = self.active
                    self.active = None
                    self._calm_streak = 0
                    return {"state": "cleared",
                            "direction": cleared["direction"],
                            "z": round(z, 2), "at": now}
        return None


class _Signal:
    __slots__ = ("name", "fn", "kind", "watch", "rings", "detector",
                 "_last_raw", "_last_now")

    def __init__(self, name: str, fn: Optional[Callable[[], Any]],
                 kind: str, watch: Optional[str],
                 detector: RobustDetector):
        self.name = name
        self.fn = fn
        self.kind = kind        # "gauge" | "counter" (counter -> rate)
        self.watch = watch      # None | "up" | "down" | "both"
        self.rings = tuple(SeriesRing(b, cap) for _, b, cap in TIERS)
        self.detector = detector
        self._last_raw: Optional[float] = None
        self._last_now: Optional[float] = None

    def ingest(self, raw: float, now: float) -> Optional[float]:
        """Convert one raw reading into the recorded value: gauges pass
        through, counters difference into a per-second rate (first
        sample and clock stalls are skipped, resets clamp at 0)."""
        if self.kind != "counter":
            return raw
        last_raw, last_now = self._last_raw, self._last_now
        self._last_raw, self._last_now = raw, now
        if last_raw is None or last_now is None or now <= last_now:
            return None
        return max(0.0, raw - last_raw) / (now - last_now)


class TimeSeriesStore:
    """The telemetry plane: registered signals, multi-resolution rings,
    anomaly detection, cursor deltas, and the tick-anatomy ring.

    ``sample(now)`` is the one write path; ``start()`` runs it on a
    fixed cadence from the event loop. Every read path is a plain
    snapshot over bounded structures — safe to call from any debug
    handler."""

    def __init__(self, metrics: Any = None, logger: Any = None, *,
                 interval_s: float = 1.0, tick_sample: int = 64,
                 tick_capacity: int = 256,
                 detector_threshold: float = 6.0,
                 detector_min_baseline: int = 20,
                 detector_trigger_after: int = 3,
                 detector_clear_after: int = 5):
        self.metrics = metrics
        self.logger = logger
        self.interval_s = max(0.05, float(interval_s))
        self.tick_sample = max(1, int(tick_sample))
        self._detector_opts = dict(
            threshold=detector_threshold,
            min_baseline=detector_min_baseline,
            trigger_after=detector_trigger_after,
            clear_after=detector_clear_after)
        self._signals: Dict[str, _Signal] = {}
        self._providers: List[Tuple[Tuple[str, ...],
                                    Callable[[], Dict[str, Any]]]] = []
        self._seq = 0
        self._delta_log: deque = deque(maxlen=DELTA_LOG_CAPACITY)
        self._ticks: deque = deque(maxlen=max(1, int(tick_capacity)))
        self._anomaly_events: deque = deque(maxlen=64)
        self._task: Optional[asyncio.Task] = None

    # -- registration -------------------------------------------------------
    def register(self, name: str, fn: Callable[[], Any], *,
                 kind: str = "gauge",
                 watch: Optional[str] = None) -> None:
        """Register one signal. ``fn()`` returns the current reading (a
        number, or None while the signal is unavailable). ``kind``
        "counter" differences cumulative readings into a per-second
        rate. ``watch`` opts the signal's anomalies into the watchdog
        feed, filtered by direction ("down" = only a cliff degrades,
        "up" = only a spike, "both")."""
        self._signals[name] = _Signal(
            name, fn, kind, watch, RobustDetector(**self._detector_opts))

    def register_provider(self, names: Iterable[str],
                          fn: Callable[[], Dict[str, Any]], *,
                          kinds: Optional[Dict[str, str]] = None,
                          watch: Optional[Dict[str, str]] = None) -> None:
        """Register several signals fed by ONE snapshot callable — the
        provider runs once per sample, so signals sharing an expensive
        source (``stats()``, ``saturation()``) cost one call, not N."""
        names = tuple(names)
        kinds = kinds or {}
        watch = watch or {}
        for name in names:
            self._signals[name] = _Signal(
                name, None, kinds.get(name, "gauge"), watch.get(name),
                RobustDetector(**self._detector_opts))
        self._providers.append((names, fn))

    def signals(self) -> List[str]:
        return sorted(self._signals)

    # -- the write path -----------------------------------------------------
    def sample(self, now: Optional[float] = None) -> Dict[str, float]:
        """One sampling pass: read every signal, record into all tiers,
        run the detector, append to the delta log. A broken source
        skips its signals for this pass — telemetry must never take the
        serving plane down."""
        now = time.monotonic() if now is None else now
        raw: Dict[str, float] = {}
        for signal in self._signals.values():
            if signal.fn is None:
                continue
            try:
                value = signal.fn()
            except Exception:
                continue
            if value is not None:
                raw[signal.name] = float(value)
        for names, provider in self._providers:
            try:
                out = provider()
            except Exception:
                continue
            if not isinstance(out, dict):
                continue
            for name in names:
                value = out.get(name)
                if value is not None:
                    raw[name] = float(value)
        recorded: Dict[str, float] = {}
        for name, value in raw.items():
            signal = self._signals[name]
            cooked = signal.ingest(value, now)
            if cooked is None:
                continue
            for ring in signal.rings:
                ring.add(cooked, now)
            recorded[name] = cooked
            event = signal.detector.observe(cooked, signal.rings[0], now)
            if event is not None:
                self._note_anomaly(signal, event)
        self._seq += 1
        self._delta_log.append((self._seq, now, recorded))
        return recorded

    def _note_anomaly(self, signal: _Signal,
                      event: Dict[str, Any]) -> None:
        entry = dict(event, signal=signal.name)
        self._anomaly_events.append(entry)
        if event["state"] == "raised":
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_tpu_anomaly_total", signal=signal.name,
                    direction=event["direction"])
            if self.logger is not None:
                self.logger.warn(
                    "telemetry anomaly: %s %s (z=%.1f)", signal.name,
                    event["direction"], event["z"])
        elif self.logger is not None:
            self.logger.info("telemetry anomaly cleared: %s", signal.name)

    # -- anomaly views ------------------------------------------------------
    def anomalies(self) -> Dict[str, Any]:
        active = {
            name: dict(signal.detector.active)
            for name, signal in self._signals.items()
            if signal.detector.active is not None
        }
        return {"active": active,
                "recent": list(self._anomaly_events)}

    def watchdog_reasons(self) -> List[str]:
        """Active anomalies on watch-listed signals, rendered as
        watchdog reasons — the feed ``Watchdog.anomaly_fn`` consumes.
        Direction-filtered: a goodput *spike* is not a health problem."""
        reasons = []
        for name in sorted(self._signals):
            signal = self._signals[name]
            active = signal.detector.active
            if active is None or signal.watch is None:
                continue
            if signal.watch != "both" and active["direction"] != signal.watch:
                continue
            reasons.append(
                f"telemetry anomaly: {name} {active['direction']} "
                f"(z={active['z']:.1f}, baseline={active['baseline']:.3g})")
        return reasons

    # -- read paths ---------------------------------------------------------
    def window_mean(self, name: str, window_s: float,
                    now: Optional[float] = None) -> Optional[float]:
        """Count-weighted mean of one signal over the trailing
        ``window_s``, read from the *finest* tier whose span can cover
        the window (1s up to 10 minutes, 10s up to 1 hour, 60s up to 4
        hours). Every tier ingests every sample, so the mean is
        tier-consistent: a window that hops from the 10s ring to the
        60s ring sees the same count-weighted samples, just coarser
        bucket boundaries — the property the burn-rate plane's
        tier-boundary tests pin down (ISSUE 18). None when the signal
        is unknown or the window holds nothing."""
        signal = self._signals.get(name)
        if signal is None:
            return None
        now = time.monotonic() if now is None else now
        for (_, bucket_s, capacity), ring in zip(TIERS, signal.rings):
            if bucket_s * capacity >= window_s:
                return ring.window_mean(window_s, now)
        return signal.rings[-1].window_mean(window_s, now)

    def series(self, tier: str = "10s",
               signals: Optional[Iterable[str]] = None,
               limit: Optional[int] = None) -> Dict[str, Any]:
        """Aligned view of one tier: a shared ``t`` axis (bucket starts,
        oldest first) plus one value column per signal, None where a
        signal has no bucket at that instant."""
        try:
            tier_idx = [name for name, _, _ in TIERS].index(tier)
        except ValueError:
            raise ValueError(f"unknown tier {tier!r}; "
                             f"one of {[n for n, _, _ in TIERS]}")
        bucket_s = TIERS[tier_idx][1]
        chosen = sorted(signals) if signals is not None \
            else sorted(self._signals)
        per_signal: Dict[str, Dict[float, float]] = {}
        axis: set = set()
        for name in chosen:
            signal = self._signals.get(name)
            if signal is None:
                continue
            means = dict(signal.rings[tier_idx].means(limit))
            per_signal[name] = means
            axis.update(means)
        t = sorted(axis)
        if limit is not None:
            t = t[-int(limit):]
        return {
            "tier": tier,
            "bucket_s": bucket_s,
            "t": t,
            "series": {
                name: [means.get(ts) for ts in t]
                for name, means in per_signal.items()
            },
        }

    def delta(self, cursor: Optional[int] = None) -> Dict[str, Any]:
        """Samples after ``cursor`` (a sequence number from a previous
        answer), capped at ``DELTA_MAX_SAMPLES``. ``reset=True`` tells
        the puller its cursor fell off the log (long probe outage, or a
        replica restart rewound the sequence) — the samples carried are
        a fresh start, not a contiguous continuation. Timestamps are the
        *source* process's monotonic clock; pullers must re-stamp with
        their own."""
        reset = False
        if cursor is None:
            reset = True
            entries = list(self._delta_log)
        elif cursor > self._seq:
            # the replica restarted (sequence rewound): resync
            reset = True
            entries = list(self._delta_log)
        else:
            oldest = self._delta_log[0][0] if self._delta_log else self._seq
            if cursor + 1 < oldest:
                reset = True
                entries = list(self._delta_log)
            else:
                entries = [e for e in self._delta_log if e[0] > cursor]
        if len(entries) > DELTA_MAX_SAMPLES:
            reset = reset or cursor is not None
            entries = entries[-DELTA_MAX_SAMPLES:]
        return {
            "cursor": self._seq,
            "reset": reset,
            "interval_s": self.interval_s,
            "samples": [{"seq": seq, "t": t, "values": values}
                        for seq, t, values in entries],
        }

    def sparklines(self, tier: str = "10s", width: int = 30,
                   signals: Optional[Iterable[str]] = None) -> List[str]:
        """Compact ASCII sparkline per signal — the telemetry section
        statusz embeds."""
        try:
            tier_idx = [name for name, _, _ in TIERS].index(tier)
        except ValueError:
            tier_idx = 1
        lines = []
        chosen = sorted(signals) if signals is not None \
            else sorted(self._signals)
        for name in chosen:
            signal = self._signals.get(name)
            if signal is None:
                continue
            means = [m for _, m in signal.rings[tier_idx].means(width)]
            if not means:
                continue
            low, high = min(means), max(means)
            span = high - low
            if span <= 0:
                spark = _SPARK_BLOCKS[1] * len(means)
            else:
                top = len(_SPARK_BLOCKS) - 1
                spark = "".join(
                    _SPARK_BLOCKS[1 + int((m - low) / span * (top - 1))]
                    for m in means)
            flag = ""
            if signal.detector.active is not None:
                flag = f"  !! {signal.detector.active['direction']}"
            lines.append(f"{name:<22} {spark:<{width}} "
                         f"last={means[-1]:.3g} min={low:.3g} "
                         f"max={high:.3g}{flag}")
        return lines

    # -- tick anatomy -------------------------------------------------------
    def note_tick(self, entry: Dict[str, Any]) -> None:
        """Record one sampled decode-tick anatomy (the engine calls this
        for every ``tick_sample``-th tick)."""
        self._ticks.append(entry)

    def tick_anatomy(self, limit: int = 32) -> Dict[str, Any]:
        """The sampled-tick ring: recent entries plus per-phase
        aggregates (mean/max seconds over the whole ring)."""
        entries = list(self._ticks)
        phases: Dict[str, List[float]] = {}
        for entry in entries:
            for key, value in entry.items():
                if key.endswith("_s") and isinstance(value, (int, float)):
                    phases.setdefault(key, []).append(float(value))
        return {
            "sample_every": self.tick_sample,
            "recorded": len(entries),
            "capacity": self._ticks.maxlen,
            "phases": {
                key: {"mean_s": sum(vals) / len(vals),
                      "max_s": max(vals)}
                for key, vals in sorted(phases.items())
            },
            "recent": entries[-int(limit):],
        }

    # -- bookkeeping --------------------------------------------------------
    def memory_info(self) -> Dict[str, Any]:
        """The memory contract, live: per-signal bucket ceiling and the
        actual bucket counts (always <= the ceiling)."""
        return {
            "signals": len(self._signals),
            "max_buckets_per_signal": MAX_BUCKETS_PER_SIGNAL,
            "tiers": [{"tier": name, "bucket_s": b, "capacity": cap}
                      for name, b, cap in TIERS],
            "buckets_held": sum(
                len(ring) for signal in self._signals.values()
                for ring in signal.rings),
            "delta_log_capacity": DELTA_LOG_CAPACITY,
            "delta_log_held": len(self._delta_log),
            "tick_ring_capacity": self._ticks.maxlen,
            "tick_ring_held": len(self._ticks),
        }

    def statusz(self) -> Dict[str, Any]:
        """Compact rollup for embedding in /debug/statusz."""
        anomalies = self.anomalies()
        return {
            "signals": len(self._signals),
            "samples": self._seq,
            "interval_s": self.interval_s,
            "active_anomalies": anomalies["active"],
            "sparklines": self.sparklines(),
        }

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._task is None or self._task.done():
            from gofr_tpu.aio import spawn_logged
            self._task = spawn_logged(self._run(), self.logger,
                                      "telemetry.sampler",
                                      metrics=self.metrics)

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                self.sample()
            except Exception as exc:  # a telemetry bug must not kill the app
                if self.logger is not None:
                    self.logger.error("telemetry sample failed: %r", exc)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None


# -- default signal wiring ---------------------------------------------------

def register_default_signals(store: TimeSeriesStore, *, slo: Any = None,
                             tpu: Any = None,
                             container: Any = None) -> None:
    """Register the standard serving-signal set, duck-typed from
    whatever the deployment actually has: an SLOTracker, an executor
    (``saturation()``), a generation engine (``stats()``), the chaos
    plane, and the hbmz occupancy helper. Watch directions encode which
    way each signal fails: a goodput *cliff* and a padding *spike*
    degrade; the reverse moves are good news."""
    from gofr_tpu.tpu import faults

    if slo is not None:
        store.register("raw_tok_s",
                       lambda: slo.tokens.rate(30.0))
        store.register("goodput_tok_s",
                       lambda: slo.goodput_tokens.rate(30.0),
                       watch="down")

    store.register("fault_injected_total",
                   lambda: float(sum(faults.active().fired().values())),
                   kind="counter")

    if tpu is not None and hasattr(tpu, "saturation"):
        def _saturation() -> Dict[str, Any]:
            return tpu.saturation(60.0)
        store.register_provider(
            ("padding_ratio", "effective_mfu", "duty_cycle"), _saturation,
            watch={"padding_ratio": "up", "effective_mfu": "down"})

    engine = tpu if tpu is not None and hasattr(tpu, "stats") else None
    if engine is not None:
        max_slots = float(getattr(engine, "max_slots", 0) or 0)

        def _engine_stats() -> Dict[str, Any]:
            stats = engine.stats()
            out: Dict[str, Any] = {
                "queue_depth": stats.get("queue_depth", 0),
            }
            active = stats.get("active_slots")
            if active is not None and max_slots > 0:
                out["batch_fill"] = float(active) / max_slots
            pool = stats.get("kv_pool") or {}
            if "free_pages" in pool:
                out["kv_free_pages"] = pool["free_pages"]
            if "occupancy" in pool:
                out["kv_occupancy"] = pool["occupancy"]
            classes = (stats.get("classes") or {}).get("depths") or {}
            for cls, depth in classes.items():
                out[f"queue_{cls}"] = depth
            resilience = stats.get("resilience") or {}
            out["brownout_level"] = resilience.get("brownout_level", 0)
            out["quarantine_total"] = float(
                sum((resilience.get("quarantined") or {}).values()))
            return out

        names = ["queue_depth", "batch_fill", "kv_free_pages",
                 "kv_occupancy", "brownout_level", "quarantine_total"]
        try:
            weights = engine.stats().get("classes", {}).get("weights", {})
        except Exception:
            weights = {}
        names.extend(f"queue_{cls}" for cls in sorted(weights))
        store.register_provider(
            names, _engine_stats,
            kinds={"quarantine_total": "counter"},
            watch={"queue_depth": "up", "kv_occupancy": "up"})

    ledger = getattr(tpu, "ledger", None)
    if ledger is not None and hasattr(ledger, "serving_compiles"):
        store.register("serving_compiles",
                       lambda: float(ledger.serving_compiles(60.0)),
                       watch="up")

    if container is not None:
        from gofr_tpu.hbmz import hbm_occupancy
        store.register("hbm_occupancy",
                       lambda: hbm_occupancy(container), watch="up")


def new_timeseries(config: Any, *, slo: Any = None, tpu: Any = None,
                   container: Any = None, metrics: Any = None,
                   logger: Any = None) -> Optional[TimeSeriesStore]:
    """Config-driven factory (``TELEMETRY_ENABLED``, default on).
    Builds the store, registers the default signal set, and leaves
    ``start()`` to the app lifecycle."""
    if not config.get_bool("TELEMETRY_ENABLED", True):
        return None
    store = TimeSeriesStore(
        metrics=metrics, logger=logger,
        interval_s=config.get_float("TELEMETRY_INTERVAL_S", 1.0),
        tick_sample=int(config.get_float("TELEMETRY_TICK_SAMPLE", 64)))
    register_default_signals(store, slo=slo, tpu=tpu, container=container)
    return store
