"""Outbound HTTP client with decorator options: auth, default headers,
circuit breaker, health override (parity: pkg/gofr/service, SURVEY.md §2.5)."""

from gofr_tpu.service.circuit_breaker import (
    CircuitBreakerConfig,
    CircuitOpenError,
)
from gofr_tpu.service.client import (
    HTTPService,
    ServiceError,
    ServiceResponse,
)
from gofr_tpu.service.options import (
    APIKeyConfig,
    BasicAuthConfig,
    DefaultHeaders,
    HealthConfig,
    OAuthConfig,
    Option,
    new_http_service,
)

__all__ = [
    "APIKeyConfig", "BasicAuthConfig", "CircuitBreakerConfig",
    "CircuitOpenError", "DefaultHeaders", "HealthConfig", "HTTPService",
    "OAuthConfig", "Option", "ServiceError", "ServiceResponse",
    "new_http_service",
]
