"""GT017 lock-held-across-await + slot-table mutation mid-iteration.

Two async-concurrency shapes that deadlock or corrupt serving state
without ever failing a CPU test:

**A. A *thread* lock held across ``await``.** ::

    with self._pool.lock:          # threading.RLock
        await self._fetch(...)     # loop suspends, lock stays held

The ``await`` parks this coroutine but the OS lock stays owned by the
loop thread. Any executor thread then blocking on ``pool.lock``
(exactly what the donating-dispatch closures do) stalls — and if the
awaited future needs that executor, the loop and the pool deadlock.
Flagged: a **sync** ``with`` over a lock-ish expression (dotted path
whose last segment contains ``lock``) whose body contains an ``await``,
inside an ``async def``. ``async with`` is exempt — asyncio locks are
designed to be held across suspension points.

**B. Slot-table mutation across ``await`` during iteration.** ::

    for sid, slot in self._slots.items():
        await self._drain(slot)        # other coroutines run here
        del self._slots[sid]           # RuntimeError: dict changed size

Every ``await`` inside the loop is a window where another coroutine
admits or evicts a slot; mutating the table you are iterating then
raises ``RuntimeError`` (dict) or silently skips slots (list). Flagged:
a ``for`` over a slot-table receiver (``_slots``/``slots``/
``_sessions``/``sessions``/``slot_table``, plain or via ``.items()``/
``.values()``/``.keys()``), whose body contains both an ``await`` and a
mutation of that same receiver (``del t[k]`` / ``t[k] = ...`` /
``t.pop(...)``-style calls). The sanctioned shape — snapshot with
``list(table.items())``, or collect doomed keys and mutate after the
loop — passes by construction.

Suppress deliberate cases with ``# graftcheck: ignore[GT017]`` plus a
justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from gofr_tpu.analysis.dataflow import dotted_path
from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

_SLOT_TABLE_NAMES = {
    "_slots", "slots", "_sessions", "sessions", "slot_table",
    "_slot_table",
}
_VIEW_METHODS = {"items", "values", "keys"}
_MUTATING_CALLS = {
    "pop", "popitem", "clear", "remove", "discard", "append",
    "insert", "setdefault", "update", "add",
}


def _own_walk(node: ast.AST) -> Iterable[ast.AST]:
    """Descendants of ``node``, nested function/lambda bodies excluded
    (their awaits belong to another coroutine)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _own_walk(child)


def _is_lockish(path: Optional[str]) -> bool:
    if path is None:
        return False
    return "lock" in path.rsplit(".", 1)[-1].lower()


def _slot_table_path(iter_expr: ast.AST) -> Optional[str]:
    """``self._slots`` / ``self._slots.items()`` / ``slots.values()``
    → the table's dotted path when its last segment is slot-table
    named; ``list(...)`` snapshots return None (safe by construction)."""
    node = iter_expr
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _VIEW_METHODS:
            node = node.func.value
        else:
            return None  # list(...)/sorted(...)/tuple(...) snapshot
    path = dotted_path(node)
    if path is None:
        return None
    last = path.rsplit(".", 1)[-1]
    return path if last in _SLOT_TABLE_NAMES else None


def _enclosing_function(module: ModuleInfo, node: ast.AST):
    cursor = module.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cursor
        cursor = module.parents.get(cursor)
    return None


class LockAcrossAwaitRule(Rule):
    rule_id = "GT017"
    title = "lock-across-await"
    severity = "error"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        findings.extend(self._sync_lock_across_await(module))
        findings.extend(self._slot_table_mutation(module))
        return findings

    # -- shape A: sync `with lock:` containing await -------------------------
    def _sync_lock_across_await(self, module: ModuleInfo
                                ) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.With):
                continue
            lock_path = None
            for item in node.items:
                path = dotted_path(item.context_expr)
                if _is_lockish(path):
                    lock_path = path
                    break
            if lock_path is None:
                continue
            awaits = [n for n in _own_walk(node)
                      if isinstance(n, ast.Await)]
            if not awaits:
                continue
            owner = _enclosing_function(module, node)
            if owner is None or not isinstance(owner,
                                               ast.AsyncFunctionDef):
                continue
            findings.append(Finding(
                rule=self.rule_id, path=module.relpath,
                line=node.lineno,
                message=(
                    f"lock-across-await: 'with {lock_path}:' in async "
                    f"'{owner.name}' holds a thread lock across "
                    f"'await' (line {awaits[0].lineno}) — the loop "
                    f"suspends but the OS lock stays held, stalling "
                    f"every executor thread that contends for it "
                    f"(deadlock if the awaited work needs that "
                    f"thread); release before awaiting, or use an "
                    f"asyncio lock with 'async with'"),
                severity=self.severity,
                key=f"with {lock_path} across await in {owner.name}",
            ))
        return findings

    # -- shape B: slot-table mutated across await during iteration -----------
    def _slot_table_mutation(self, module: ModuleInfo
                             ) -> Iterable[Finding]:
        findings: List[Finding] = []
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor)):
                continue
            table = _slot_table_path(loop.iter)
            if table is None:
                continue
            body_nodes = []
            for stmt in loop.body:
                body_nodes.append(stmt)
                body_nodes.extend(_own_walk(stmt))
            awaits = [n for n in body_nodes if isinstance(n, ast.Await)]
            if not awaits:
                continue
            mutation = self._table_mutation(body_nodes, table)
            if mutation is None:
                continue
            owner = _enclosing_function(module, loop)
            owner_name = owner.name if owner is not None else "<module>"
            findings.append(Finding(
                rule=self.rule_id, path=module.relpath,
                line=mutation,
                message=(
                    f"slot-table-mutation-across-await: '{table}' is "
                    f"mutated at line {mutation} while being iterated "
                    f"(loop at line {loop.lineno}) with an 'await' in "
                    f"between (line {awaits[0].lineno}) — other "
                    f"coroutines admit/evict slots during the await, "
                    f"so this raises 'dict changed size during "
                    f"iteration' or skips slots; snapshot with "
                    f"'list({table}.items())' or collect keys and "
                    f"mutate after the loop"),
                severity=self.severity,
                key=f"slot-table mutation of {table} in {owner_name}",
            ))
        return findings

    @staticmethod
    def _table_mutation(body_nodes, table: str) -> Optional[int]:
        for node in body_nodes:
            if isinstance(node, ast.Delete):
                for target in node.targets:
                    if isinstance(target, ast.Subscript) \
                            and dotted_path(target.value) == table:
                        return node.lineno
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Subscript) \
                            and dotted_path(target.value) == table:
                        return node.lineno
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATING_CALLS \
                    and dotted_path(node.func.value) == table:
                return node.lineno
        return None
