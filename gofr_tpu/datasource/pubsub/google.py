"""Google Cloud Pub/Sub backend (gated on google-cloud-pubsub).

Capability parity with ``pkg/gofr/datasource/pubsub/google``
(google.go:27-60 client + New; Subscribe via streaming-pull into a local
queue; topic management; health.go:1-95). The driver is absent in this
zero-egress image, so construction raises a clear configuration error
unless google-cloud-pubsub is installed — the wrapper logic itself is
complete and drops in when it is.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from gofr_tpu.datasource.pubsub.base import Message, PubSub


class GoogleClientError(Exception):
    pass


class GoogleClient(PubSub):
    def __init__(self, config, logger, metrics, tracer=None):
        try:
            from google.cloud import pubsub_v1
        except ImportError as exc:
            raise GoogleClientError(
                "PUBSUB_BACKEND=GOOGLE requires google-cloud-pubsub, which "
                "is not installed in this image; use KAFKA, MQTT, or INMEM"
            ) from exc
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self.project = config.get("GOOGLE_PROJECT_ID")
        if not self.project:
            raise GoogleClientError("GOOGLE_PROJECT_ID is required")
        self.subscription_name = config.get_or_default(
            "GOOGLE_SUBSCRIPTION_NAME", "gofr-tpu")
        self._publisher = pubsub_v1.PublisherClient()
        self._subscriber = pubsub_v1.SubscriberClient()
        self._queues = {}
        self._pulls = {}
        self._lock = threading.Lock()
        logger.info("google pub/sub connected project=%s", self.project)

    def _topic_path(self, topic: str) -> str:
        return self._publisher.topic_path(self.project, topic)

    def publish(self, topic: str, payload: bytes, key: bytes = b"") -> None:
        self.metrics.increment_counter("app_pubsub_publish_total_count",
                                       topic=topic)
        # Pub/Sub has native message attributes, so the traceparent rides
        # as one (no byte envelope needed, unlike Kafka/MQTT). The
        # subscriber callback lifts it back into Message.metadata.
        attrs = {"key": key.decode() if key else ""}
        span = None
        if self.tracer is not None:
            from gofr_tpu.trace import current_span, format_traceparent
            if current_span() is not None:
                span = self.tracer.start_span("pubsub.publish")
                span.set_attribute("topic", topic)
                span.set_attribute("backend", "GOOGLE")
                attrs["traceparent"] = format_traceparent(span)
        try:
            future = self._publisher.publish(self._topic_path(topic),
                                             payload, **attrs)
            future.result(timeout=30)
        except Exception:
            if span is not None:
                span.set_status("ERROR")
            raise
        finally:
            if span is not None:
                span.finish()
        self.metrics.increment_counter("app_pubsub_publish_success_count",
                                       topic=topic)

    def _ensure_pull(self, topic: str) -> "queue.Queue":
        with self._lock:
            if topic in self._queues:
                return self._queues[topic]
            local = queue.Queue(maxsize=65536)
            self._queues[topic] = local
            sub_path = self._subscriber.subscription_path(
                self.project, f"{self.subscription_name}-{topic}")
            try:
                self._subscriber.create_subscription(
                    request={"name": sub_path,
                             "topic": self._topic_path(topic)})
            except Exception:
                pass  # already exists

            def callback(received):
                attrs = dict(getattr(received, "attributes", None) or {})
                traceparent = attrs.get("traceparent")
                metadata = ({"traceparent": traceparent}
                            if traceparent else None)
                local.put(Message(topic, received.data, metadata=metadata,
                                  committer=received.ack))

            self._pulls[topic] = self._subscriber.subscribe(sub_path,
                                                            callback)
            return local

    async def subscribe(self, topic: str) -> Optional[Message]:
        import asyncio
        self.metrics.increment_counter("app_pubsub_subscribe_total_count",
                                       topic=topic)
        local = self._ensure_pull(topic)
        message = await asyncio.get_running_loop().run_in_executor(
            None, local.get)
        if message is not None:
            self.metrics.increment_counter(
                "app_pubsub_subscribe_success_count", topic=topic)
        return message

    def create_topic(self, topic: str) -> None:
        try:
            self._publisher.create_topic(
                request={"name": self._topic_path(topic)})
        except Exception:
            pass  # already exists

    def delete_topic(self, topic: str) -> None:
        self._publisher.delete_topic(
            request={"topic": self._topic_path(topic)})

    def health_check(self) -> dict:
        try:
            self._publisher.list_topics(
                request={"project": f"projects/{self.project}",
                         "page_size": 1})
            return {"status": "UP", "details": {"backend": "GOOGLE",
                                                "project": self.project}}
        except Exception as exc:
            return {"status": "DOWN", "details": {"error": repr(exc)}}

    def close(self) -> None:
        for pull in self._pulls.values():
            pull.cancel()
