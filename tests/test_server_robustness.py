"""HTTP server robustness: hostile bytes, protocol edges, pipelining,
and shutdown-with-live-connections — all over real sockets against the
real server (VERDICT r3 #5 test-depth push)."""

import asyncio

from tests.util import http_request, make_app, run, serving


def _echo_app():
    app = make_app()

    def echo(ctx):
        return {"len": len(ctx.request.body)}

    app.post("/echo", echo)
    app.get("/ping", lambda ctx: "pong")
    return app


async def _raw(port: int, payload: bytes, timeout: float = 10.0) -> bytes:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(payload)
    await writer.drain()
    writer.write_eof()
    try:
        return await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()


def test_garbage_bytes_get_400_and_server_survives():
    app = _echo_app()

    async def main():
        async with serving(app) as port:
            raw = await _raw(port, b"\x00\xff\x13GARBAGE\r\n\r\n")
            assert b"400" in raw.split(b"\r\n")[0]
            # server still serves the next, clean connection
            ok = await http_request(port, "GET", "/ping")
            assert ok.status == 200
    run(main())


def test_oversized_headers_rejected():
    app = _echo_app()

    async def main():
        async with serving(app) as port:
            # just past the 64 KB cap, no terminator: the server consumes
            # everything sent, answers 400, and closes cleanly. (With many
            # KB still in flight the close would RST and eat the response
            # — also legitimate refusal, but unassertable.)
            blob = b"GET /ping HTTP/1.1\r\nX-Big: " + b"a" * (65 * 1024)
            raw = await _raw(port, blob)
            assert b"400" in raw.split(b"\r\n")[0]
            ok = await http_request(port, "GET", "/ping")
            assert ok.status == 200
    run(main())


def test_huge_declared_body_rejected_without_reading_it():
    """A Content-Length over the cap answers 413 immediately — the server
    must not wait for (or buffer) the claimed 100 MB."""
    app = _echo_app()

    async def main():
        async with serving(app) as port:
            head = (b"POST /echo HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Length: 104857600\r\n\r\n")
            raw = await asyncio.wait_for(_raw(port, head), 5.0)
            assert b"413" in raw.split(b"\r\n")[0]
    run(main())


def test_malformed_content_length_rejected():
    app = _echo_app()

    async def main():
        async with serving(app) as port:
            for bad in (b"banana", b"-5", b"1e3"):
                raw = await _raw(
                    port, b"POST /echo HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Length: " + bad + b"\r\n\r\n")
                assert b"400" in raw.split(b"\r\n")[0], bad
            ok = await http_request(port, "GET", "/ping")
            assert ok.status == 200
    run(main())


def test_keepalive_pipelined_requests_one_connection():
    """Two requests written in ONE send must both be answered, in order,
    on the same connection (body boundaries respected)."""
    app = _echo_app()

    async def main():
        async with serving(app) as port:
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            blob = (b"POST /echo HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Length: 3\r\n\r\nabc"
                    b"GET /ping HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Length: 0\r\n\r\n")
            writer.write(blob)
            await writer.drain()

            async def read_one():
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), 10.0)
                length = 0
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        length = int(line.split(b":", 1)[1])
                body = await asyncio.wait_for(reader.readexactly(length),
                                              10.0)
                return head.split(b"\r\n")[0], body

            first_status, first_body = await read_one()
            second_status, second_body = await read_one()
            assert b"201" in first_status and b'"len": 3' in first_body
            assert b"200" in second_status and b"pong" in second_body
            writer.close()
    run(main())


def test_connection_close_honored():
    app = _echo_app()

    async def main():
        async with serving(app) as port:
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            writer.write(b"GET /ping HTTP/1.1\r\nHost: x\r\n"
                         b"Connection: close\r\n\r\n")
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), 10.0)  # EOF = closed
            assert b"200" in raw.split(b"\r\n")[0]
            assert b"Connection: close" in raw
            writer.close()
    run(main())


def test_shutdown_reaps_idle_keepalive_connection():
    """An idle keep-alive client must not park shutdown (Python 3.12's
    Server.wait_closed waits on live handlers; server.py closes their
    transports first)."""
    app = _echo_app()

    async def main():
        await app.start()
        port = app._http_server.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        # one completed request leaves the connection idle in keep-alive
        writer.write(b"GET /ping HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 10.0)
        # shutdown with the socket still open must complete promptly
        await asyncio.wait_for(app.stop(), 10.0)
        writer.close()
    run(main())


def test_shutdown_reaps_live_websocket():
    """Same for an ACTIVE websocket mid-conversation (found via the
    websocket-chat example: stop() hung until the client went away)."""
    import base64
    import os

    app = make_app()

    async def forever_echo(ctx):
        while True:
            message = await ctx.read_message()
            await ctx.write_message(message)

    app.websocket("/ws", forever_echo)

    async def main():
        await app.start()
        port = app._http_server.bound_port
        key = base64.b64encode(os.urandom(16)).decode()
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write((
            "GET /ws HTTP/1.1\r\nHost: x\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n\r\n").encode())
        await writer.drain()
        status = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 10.0)
        assert b"101" in status.split(b"\r\n")[0]
        await asyncio.wait_for(app.stop(), 10.0)
        writer.close()
    run(main())


def test_many_sequential_connections_no_leak():
    """Churn 30 connections; the server's connection registry must drain
    back to empty (no protocol objects leak)."""
    app = _echo_app()

    async def main():
        async with serving(app) as port:
            for _ in range(30):
                ok = await http_request(port, "GET", "/ping")
                assert ok.status == 200
            await asyncio.sleep(0.05)
            assert len(app._http_server._connections) == 0
    run(main())


def test_shutdown_lets_inflight_request_complete():
    """Graceful drain: a request already being handled when stop() is
    called must still get its response (connection then closes); only
    idle connections are cut immediately."""
    app = make_app()

    async def slow(ctx):
        await asyncio.sleep(0.4)
        return {"done": True}

    app.get("/slow", slow)

    async def main():
        await app.start()
        port = app._http_server.bound_port
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /slow HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        await asyncio.sleep(0.1)          # handler is now mid-sleep
        stop = asyncio.ensure_future(app.stop())
        raw = await asyncio.wait_for(reader.read(), 10.0)
        await asyncio.wait_for(stop, 10.0)
        assert b"200" in raw.split(b"\r\n")[0]
        assert b'"done": true' in raw
        # drain forces the connection closed after the response
        assert b"Connection: close" in raw
        writer.close()
    run(main())
