"""SLO-class weighted-fair admission queues.

Multi-model tenancy (ISSUE 7) turns the engine's single FIFO admission
queue into a fairness problem: co-resident models and tenants share one
page pool and one decode tick, so a burst of batch traffic must not
starve interactive requests that carry tight deadlines — and vice versa,
an interactive tenant must not monopolize every admission round just by
arriving often. The classic answer is weighted fair queueing over
*virtual time*: each class owns a FIFO; a pop takes from the non-empty
class with the smallest virtual clock, then advances that clock by
``1 / weight``. A class with weight 4 therefore drains 4 items for every
1 a weight-1 class drains when both are backlogged, yet an idle class
loses nothing (its clock is re-anchored to the current minimum on first
arrival, the standard anti-starvation rule — an empty class must not
bank credit while idle and then lock out everyone else).

Classes are derived from the request deadline (``slo.py`` contract):

- ``interactive`` — deadline budget at or under ``SLO_CLASS_INTERACTIVE_MS``
  (default 2000 ms); a human is waiting.
- ``standard`` — any other finite deadline.
- ``batch`` — no deadline; throughput traffic.

The same class labels flow through to the overflow deque (requests
admitted past the free-slot/page budget), per-class shed accounting, and
the ``app_tpu_admission_queue_depth{model,cls}`` gauge, so one tenant's
burst is visible — and sheddable — without touching another class.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Deque, Dict, Iterable, Optional, Tuple

CLASS_INTERACTIVE = "interactive"
CLASS_STANDARD = "standard"
CLASS_BATCH = "batch"
# Disaggregated serving (ISSUE 8): requests whose prefill already ran on
# another replica. Their KV pages are on the wire or already resident, so
# stalling them wastes work two replicas performed — decode replicas give
# them the highest default weight.
CLASS_MIGRATED = "migrated"

SLO_CLASSES = (CLASS_INTERACTIVE, CLASS_STANDARD, CLASS_BATCH)

DEFAULT_CLASS_WEIGHTS: Dict[str, float] = {
    CLASS_INTERACTIVE: 4.0,
    CLASS_STANDARD: 2.0,
    CLASS_BATCH: 1.0,
}

# Per-role admission presets (tpu/cluster.py roles). A prefill replica's
# product is TTFT, so interactive traffic dominates harder than the
# shared default; a decode replica must land migrated KV before anything
# else (see CLASS_MIGRATED); ``both`` is the monolithic default.
ROLE_CLASS_WEIGHTS: Dict[str, Dict[str, float]] = {
    "prefill": {CLASS_INTERACTIVE: 8.0, CLASS_STANDARD: 2.0,
                CLASS_BATCH: 1.0},
    "decode": {CLASS_MIGRATED: 8.0, CLASS_INTERACTIVE: 4.0,
               CLASS_STANDARD: 2.0, CLASS_BATCH: 1.0},
    "both": dict(DEFAULT_CLASS_WEIGHTS),
}


def role_class_weights(role: str,
                       spec: Optional[str] = None) -> Dict[str, float]:
    """Admission weights for a replica role, with an optional
    ``SLO_CLASS_WEIGHTS``-style override spec layered on top (explicit
    operator knobs beat role presets)."""
    weights = dict(ROLE_CLASS_WEIGHTS.get(role, DEFAULT_CLASS_WEIGHTS))
    # only classes the spec names are layered on top — running the spec
    # through parse_class_weights would re-apply the shared defaults and
    # silently undo the role preset for every unmentioned class
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, raw = part.partition(":")
        try:
            weight = float(raw)
        except ValueError:
            continue
        if weight > 0:
            weights[name.strip()] = weight
    return weights

# Deadline budget at or below this is "a human is waiting" traffic.
DEFAULT_INTERACTIVE_BUDGET_S = 2.0


def deadline_class(deadline: Optional[float], now: Optional[float] = None,
                   interactive_budget_s: float = DEFAULT_INTERACTIVE_BUDGET_S
                   ) -> str:
    """Map an absolute monotonic deadline to an SLO class."""
    if deadline is None:
        return CLASS_BATCH
    now = time.monotonic() if now is None else now
    if deadline - now <= interactive_budget_s:
        return CLASS_INTERACTIVE
    return CLASS_STANDARD


# Brownout ladder (slo.py BrownoutLadder): which classes a replica sheds
# at each degradation level, ordered by expendability. Level 1 sheds
# batch only; deeper levels also act on speculative decode (engine-side)
# before the watchdog's full shed breaker fires. CLASS_MIGRATED is never
# shed — its prefill work already happened on another replica.
BROWNOUT_SHED = (
    (),                      # level 0: healthy, shed nothing
    (CLASS_BATCH,),          # level 1: throughput traffic waits
    (CLASS_BATCH,),          # level 2: + spec-decode γ capped at 1
    (CLASS_BATCH,),          # level 3: + speculative decode off
)


def brownout_shed_classes(level: int) -> Tuple[str, ...]:
    """Admission classes a replica refuses at brownout ``level``."""
    if level <= 0:
        return BROWNOUT_SHED[0]
    return BROWNOUT_SHED[min(level, len(BROWNOUT_SHED) - 1)]


def parse_class_weights(spec: Optional[str]) -> Dict[str, float]:
    """Parse ``"interactive:4,standard:2,batch:1"`` into a weight map.

    Unknown class names are accepted (forward-compatible with per-tenant
    classes); malformed entries are skipped rather than failing startup —
    a bad knob must never take the replica down. Missing classes fall
    back to the defaults so a partial override stays safe.
    """
    weights = dict(DEFAULT_CLASS_WEIGHTS)
    if not spec:
        return weights
    for part in spec.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, raw = part.partition(":")
        try:
            weight = float(raw)
        except ValueError:
            continue
        if weight > 0:
            weights[name.strip()] = weight
    return weights


class ClassQueues:
    """Weighted-fair pending queue, API-compatible with the subset of
    ``asyncio.Queue`` the generation engine uses (``put`` / ``get_nowait``
    / ``empty`` / ``qsize``). ``put`` never blocks — admission control
    happens downstream at the free-slot/page-budget gate — but stays a
    coroutine so existing ``await pending.put(...)`` call sites work
    unchanged."""

    def __init__(self, weights: Optional[Dict[str, float]] = None):
        self._weights = dict(weights or DEFAULT_CLASS_WEIGHTS)
        self._queues: Dict[str, Deque[Any]] = {}
        self._vt: Dict[str, float] = {}
        self._served: Dict[str, int] = {}

    def _weight(self, cls: str) -> float:
        return self._weights.get(cls, 1.0)

    async def put(self, item: Any, cls: str = CLASS_BATCH) -> None:
        self.put_nowait(item, cls)

    def put_nowait(self, item: Any, cls: str = CLASS_BATCH) -> None:
        queue = self._queues.get(cls)
        if queue is None:
            queue = self._queues[cls] = deque()
            self._vt.setdefault(cls, 0.0)
        if not queue:
            # re-anchor: an idle class resumes at the current minimum so
            # it neither banks credit nor starts hopelessly behind
            active = [self._vt[c] for c, q in self._queues.items() if q]
            floor = min(active) if active else 0.0
            self._vt[cls] = max(self._vt.get(cls, 0.0), floor)
        queue.append(item)

    def get_nowait(self) -> Any:
        """Pop from the backlogged class with the smallest virtual time."""
        candidates = [(self._vt[c], c) for c, q in self._queues.items() if q]
        if not candidates:
            raise IndexError("get_nowait() on empty ClassQueues")
        _, cls = min(candidates)
        item = self._queues[cls].popleft()
        self._vt[cls] += 1.0 / self._weight(cls)
        self._served[cls] = self._served.get(cls, 0) + 1
        return item

    def empty(self) -> bool:
        return not any(self._queues.values())

    def qsize(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def depths(self) -> Dict[str, int]:
        """Per-class backlog, always including the three standard classes
        (a zero row is a signal too — gauges should not disappear)."""
        out = {cls: 0 for cls in SLO_CLASSES}
        for cls, queue in self._queues.items():
            out[cls] = len(queue)
        return out

    def served(self) -> Dict[str, int]:
        return dict(self._served)

    def weights(self) -> Dict[str, float]:
        return dict(self._weights)

    def set_weights(self, weights: Dict[str, float]) -> None:
        """Atomically replace the per-class weights — the guarded
        operating-point apply path (ISSUE 19,
        ``GenerationEngine.apply_operating_point``). Virtual clocks and
        backlogs are untouched, so already-queued items keep their drain
        order and only *future* pops feel the new shares. Non-positive
        and malformed entries are rejected loudly (unlike the startup
        parser, a runtime retune has a caller to answer to)."""
        cleaned: Dict[str, float] = {}
        for name, raw in weights.items():
            weight = float(raw)
            if weight <= 0:
                raise ValueError(
                    f"class weight {name!r}={raw!r} must be > 0")
            cleaned[str(name)] = weight
        if not cleaned:
            raise ValueError("set_weights: empty weight map")
        self._weights = cleaned

    def drain(self) -> Iterable[Tuple[str, Any]]:
        """Remove and yield every queued ``(cls, item)`` — shutdown path."""
        for cls, queue in self._queues.items():
            while queue:
                yield cls, queue.popleft()


__all__ = [
    "CLASS_INTERACTIVE", "CLASS_STANDARD", "CLASS_BATCH", "CLASS_MIGRATED",
    "SLO_CLASSES", "DEFAULT_CLASS_WEIGHTS", "ROLE_CLASS_WEIGHTS",
    "DEFAULT_INTERACTIVE_BUDGET_S", "deadline_class", "parse_class_weights",
    "role_class_weights", "ClassQueues", "BROWNOUT_SHED",
    "brownout_shed_classes",
]
