"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Must run before the first ``import jax`` anywhere in the test session so the
multi-chip sharding paths (gofr_tpu.parallel) are exercised without TPU
hardware — the "miniredis of XLA" strategy from SURVEY.md §4.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# keep XLA quiet + snappy in unit tests
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture()
def mock_container():
    from gofr_tpu.container import new_mock_container
    return new_mock_container()
