"""Pallas TPU kernels for the hot ops (see pallas_guide.md)."""

from gofr_tpu.ops.pallas.decode_attention import flash_decode_attention
from gofr_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention", "flash_decode_attention"]
