"""Inference flight recorder (ISSUE 1): one trace_id connects the HTTP
request span, the engine's ``queue.wait``/``prefill``/``decode`` child
spans, and the batched engine-step spans via span links; ``/debug/statusz``
serves the live timeline and ``/metrics`` carries trace_id exemplars."""

import asyncio
import json

import jax
import pytest

from gofr_tpu.app import App
from gofr_tpu.container import new_mock_container
from gofr_tpu.models import llama
from gofr_tpu.tpu import FlightRecorder, RequestRecord
from gofr_tpu.tpu.generate import GenerationEngine
from gofr_tpu.trace import ListExporter, Tracer
from tests.util import http_request, run, serving


@pytest.fixture(scope="module")
def setup():
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _traced_app(config=None):
    """make_app() variant whose tracer exports into an in-memory list (the
    middleware captures the tracer at App construction, so it must be
    swapped before App() runs)."""
    container = new_mock_container(config)
    exporter = ListExporter()
    container.tracer = Tracer(exporter=exporter)
    app = App(config=container.config, container=container)
    app.http_port = 0
    app.metrics_port = 0
    return app, exporter


def _wire_engine(app, cfg, params):
    engine = GenerationEngine(cfg, params, max_slots=2, max_len=64,
                              prompt_buckets=(8,),
                              logger=app.logger,
                              metrics=app.container.metrics,
                              tracer=app.container.tracer)
    app.container.tpu = engine
    app.enable_statusz()

    async def generate(ctx):
        await engine.start()
        data = ctx.bind()
        out = await engine.generate(
            data["prompt"], max_new_tokens=int(data.get("max_new_tokens", 4)))
        return {"tokens": out}

    app.post("/generate", generate)
    return engine


async def _post_generate(port, prompt, max_new_tokens=4):
    return await asyncio.wait_for(http_request(
        port, "POST", "/generate",
        body=json.dumps({"prompt": prompt,
                         "max_new_tokens": max_new_tokens}).encode(),
        headers={"Content-Type": "application/json"}), 120.0)


def test_one_trace_id_connects_http_to_engine_step(setup):
    """The acceptance path: request → queue.wait/prefill/decode children
    sharing the HTTP trace, and root engine-step spans whose links carry
    the request's span id (many requests : one batched step)."""
    cfg, params = setup

    async def main():
        app, exporter = _traced_app()
        engine = _wire_engine(app, cfg, params)
        async with serving(app) as port:
            resp = await _post_generate(port, [1, 2, 3], max_new_tokens=4)
            assert resp.status == 201
            assert len(resp.json()["data"]["tokens"]) == 4
            trace_id = resp.headers["x-trace-id"]
            await engine.stop()
        # app.stop() → container.close() → tracer.shutdown() drained the
        # export queue, so every finished span is in the exporter now
        return exporter, trace_id

    exporter, trace_id = run(main())

    http_spans = [s for s in exporter.find("POST /generate")
                  if s.trace_id == trace_id]
    assert len(http_spans) == 1
    req_span = http_spans[0]

    for name in ("queue.wait", "prefill", "decode"):
        children = [s for s in exporter.find(name)
                    if s.trace_id == trace_id]
        assert children, f"no {name} span in the request's trace"
        assert children[0].parent_id == req_span.span_id

    steps = exporter.find("tpu.engine.prefill") + exporter.find("tpu.engine.step")
    assert steps, "engine emitted no step spans"
    want = {"trace_id": trace_id, "span_id": req_span.span_id}
    linked = [s for s in steps if want in s.links]
    assert linked, "no engine step span links back to the request span"
    # step spans are engine-internal roots, not children of any request
    assert all(s.parent_id is None for s in steps)


def test_statusz_and_metrics_exemplars(setup):
    cfg, params = setup

    async def main():
        app, _ = _traced_app()
        engine = _wire_engine(app, cfg, params)
        async with serving(app) as port:
            resp = await _post_generate(port, [5, 6, 7], max_new_tokens=4)
            assert resp.status == 201
            trace_id = resp.headers["x-trace-id"]

            statusz = await http_request(port, "GET",
                                         "/debug/statusz?recent=8")
            snap = statusz.json()["data"]
            assert snap["app"]["name"]
            engine_snap = snap["engine"]
            assert engine_snap["queue_depth"] == 0
            assert len(engine_snap["slots"]) == 2
            for slot in engine_snap["slots"]:
                assert slot["state"] in ("active", "free")
            kv = engine_snap["kv_cache"]
            assert kv["max_slots"] == 2 and kv["max_len"] == 64
            assert 0.0 <= kv["occupancy"] <= 1.0
            assert snap["devices"]["status"] == "UP"

            timelines = engine_snap["requests"]
            assert timelines["total_requests"] >= 1
            newest = timelines["recent"][0]
            assert newest["trace_id"] == trace_id
            assert newest["status"] == "done"
            assert newest["tokens"] == 4
            assert newest["queue_wait_s"] is not None
            assert newest["ttft_s"] is not None
            assert newest["tokens_per_s"] > 0
            assert newest["batch_sizes"]["ticks"] >= 1
            assert newest["batch_sizes"]["max"] >= 1

            mport = app._metrics_server.bound_port
            text = (await http_request(mport, "GET", "/metrics")
                    ).body.decode()
            ttft_exemplars = [
                line for line in text.splitlines()
                if line.startswith("app_tpu_ttft_bucket") and " # {" in line]
            assert ttft_exemplars, "no exemplar on the TTFT histogram"
            assert any(f'trace_id="{trace_id}"' in line
                       for line in ttft_exemplars)
            await engine.stop()
    run(main())


def test_flight_recorder_ring_and_lifecycle():
    recorder = FlightRecorder(capacity=2)
    for i in range(3):
        record = RequestRecord(model="generate", prompt_len=3, budget=4,
                               trace_id=f"trace-{i}", span_id=f"span-{i}")
        recorder.start(record)
        record.admitted()
        record.rode_batch(2)
        record.rode_batch(1)
        record.first_token()
        record.tokens = 4
        recorder.finish(record, "done")
    snap = recorder.snapshot()
    assert snap["total_requests"] == 3
    assert snap["in_flight"] == []
    assert len(snap["recent"]) == 2          # ring stays bounded
    assert snap["recent"][0]["trace_id"] == "trace-2"   # newest first
    newest = snap["recent"][0]
    assert newest["status"] == "done"
    assert newest["queue_wait_s"] >= 0.0
    assert newest["ttft_s"] >= newest["queue_wait_s"]
    assert newest["batch_sizes"] == {"ticks": 2, "min": 1, "max": 2,
                                     "mean": 1.5}


def test_flight_recorder_tracks_in_flight():
    recorder = FlightRecorder(capacity=4)
    record = recorder.start(RequestRecord(prompt_len=1, budget=2))
    snap = recorder.snapshot()
    assert len(snap["in_flight"]) == 1
    assert snap["in_flight"][0]["status"] == "queued"
    recorder.finish(record, "cancelled")
    snap = recorder.snapshot()
    assert snap["in_flight"] == []
    assert snap["recent"][0]["status"] == "cancelled"
    # double-finish is a no-op, not a duplicate ring entry
    recorder.finish(record, "done")
    assert len(recorder.snapshot()["recent"]) == 1


def test_batcher_step_span_links_requests():
    """ctx.predict path: the batcher opens a queue.wait child per request
    and one root tpu.batch step span linked to every coalesced request;
    the executor stamps the step's trace onto app_tpu_execute."""
    import jax.numpy as jnp
    import numpy as np

    from gofr_tpu.metrics import render_prometheus

    async def main():
        app, exporter = _traced_app({"TPU_ENABLED": "true"})
        app.add_model("clf", lambda p, x: x @ p["w"],
                      params={"w": jnp.eye(3)}, buckets=(1, 2, 4))

        async def classify(ctx):
            out = await ctx.predict(
                "clf", np.asarray(ctx.bind()["x"], np.float32))
            return {"y": [float(v) for v in out]}

        app.post("/classify", classify)
        async with serving(app) as port:
            resp = await http_request(
                port, "POST", "/classify",
                body=json.dumps({"x": [1.0, 0.0, 0.0]}).encode(),
                headers={"Content-Type": "application/json"})
            assert resp.status == 201
            trace_id = resp.headers["x-trace-id"]
            text = render_prometheus(app.container.metrics)
        return exporter, trace_id, text

    exporter, trace_id, text = run(main())

    qwaits = [s for s in exporter.find("queue.wait")
              if s.trace_id == trace_id]
    assert qwaits and qwaits[0].attributes["model"] == "clf"
    batches = exporter.find("tpu.batch")
    assert batches, "batcher emitted no step span"
    assert any(any(link["trace_id"] == trace_id for link in s.links)
               for s in batches)
    assert any(line.startswith("app_tpu_execute_bucket") and " # {" in line
               for line in text.splitlines()), \
        "no exemplar on app_tpu_execute"
