"""Property tests for pure components: BPE tokenizer round-trips,
window-rung selection, cron field edges, and env-file parsing
(test-depth push, VERDICT r3 #5; sampling semantics live in
test_sampling.py). Seeded RNG — failures reproduce."""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# -- tokenizer ---------------------------------------------------------------

from gofr_tpu.tokenizer import Tokenizer


def test_tokenizer_bytes_roundtrip_fuzz():
    """Byte-level tokenizer (no merges): encode∘decode is identity for
    arbitrary unicode, including astral plane and control chars."""
    tok = Tokenizer()
    rng = random.Random(5)
    pool = "abc 123 \t\n éü 日本語 🎉🚀 "
    for _ in range(100):
        text = "".join(rng.choice(pool) for _ in range(rng.randint(0, 80)))
        assert tok.decode(tok.encode(text)) == text


def test_trained_tokenizer_roundtrip_and_compression():
    corpus = ["the quick brown fox jumps over the lazy dog",
              "the lazy dog sleeps", "quick quick brown fox"] * 10
    tok = Tokenizer.train(corpus, vocab_size=300)
    for text in corpus + ["the fox", "dog dog dog", "völlig neu"]:
        ids = tok.encode(text)
        assert tok.decode(ids) == text
    # merges actually fire: trained encoding is shorter than byte-level
    assert len(tok.encode(corpus[0])) < len(corpus[0].encode())


def test_tokenizer_native_matches_python_path():
    """When the C++ extension is present both paths must agree exactly."""
    corpus = ["abcabcabc", "banana bandana"] * 5
    tok = Tokenizer.train(corpus, vocab_size=280)
    if tok._native is None:
        pytest.skip("native tokenizer not built in this environment")
    rng = random.Random(9)
    for _ in range(50):
        text = "".join(rng.choice("abnd ") for _ in range(rng.randint(0, 60)))
        assert tok._encode_native(text.encode()) == \
            tok._encode_python(text.encode())


# -- engine window-rung selection -------------------------------------------

def _ladder_engine(max_len):
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.generate import GenerationEngine
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    return GenerationEngine(cfg, params, max_slots=2, max_len=max_len,
                            prompt_buckets=(8,))


def test_window_ladder_shape():
    assert _ladder_engine(64)._window_ladder == [None]
    assert _ladder_engine(512)._window_ladder == [128, 256, None]
    assert _ladder_engine(1024)._window_ladder == [128, 256, 512, None]


def test_window_rung_selection_boundaries():
    engine = _ladder_engine(512)
    assert engine._pick_window([100], 8) == 128      # 108 fits 128
    assert engine._pick_window([120], 8) == 128      # 128 exactly fits
    assert engine._pick_window([121], 8) == 256      # 129 spills to 256
    assert engine._pick_window([240], 8) == 256      # 248 fits 256
    assert engine._pick_window([250], 8) is None     # 258 → full cache
    assert engine._pick_window([300], 8) is None
    assert engine._pick_window([], 4) == 128         # no active fills
    # the max across slots drives the rung
    assert engine._pick_window([10, 200], 4) == 256


def test_window_ladder_off_by_flag():
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.generate import GenerationEngine
    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    engine = GenerationEngine(cfg, params, max_slots=2, max_len=512,
                              prompt_buckets=(8,), window_ladder=False)
    assert engine._window_ladder == [None]
    assert engine._pick_window([10], 1) is None


# -- cron field edges --------------------------------------------------------

from gofr_tpu.cron import CronJob, CronParseError, parse_schedule  # noqa


def test_cron_dow_sunday_convention():
    job = CronJob("0 0 * * 0", "sunday-job", lambda ctx: None)
    sunday = time.struct_time((2026, 8, 2, 0, 0, 0, 6, 214, -1))   # tm_wday 6
    monday = time.struct_time((2026, 8, 3, 0, 0, 0, 0, 215, -1))
    assert job.due(sunday)
    assert not job.due(monday)


def test_cron_month_and_dom_bounds():
    assert parse_schedule("0 0 1 1 *")["month"] == {1}
    assert parse_schedule("0 0 31 12 *")["day"] == {31}
    for bad in ("0 0 0 * *", "0 0 32 * *", "0 0 * 13 *", "60 * * * *",
                "* 24 * * *", "* * * * 7"):
        with pytest.raises(CronParseError):
            parse_schedule(bad)


def test_cron_combined_list_range_step():
    minutes = parse_schedule("1,5-9,*/20 * * * *")["minute"]
    assert minutes == {1, 5, 6, 7, 8, 9, 0, 20, 40}


# -- env-file parsing --------------------------------------------------------

from gofr_tpu.config import EnvConfig, load_env_file  # noqa: E402


def test_env_file_parsing_edges(tmp_path):
    env = tmp_path / ".env"
    env.write_text(
        "# comment line\n"
        "PLAIN=value\n"
        "QUOTED=\"with spaces\"\n"
        "SINGLE='single quoted'\n"
        "EMPTY=\n"
        "SPACED =  padded  \n"
        "\n"
        "NOEQUALS\n"
        "INLINE=x # trailing comment not stripped\n")
    values = load_env_file(str(env))
    assert values["PLAIN"] == "value"
    assert values["QUOTED"] == "with spaces"
    assert values["SINGLE"] == "single quoted"
    assert values["EMPTY"] == ""
    assert values["SPACED"] == "padded"
    assert "NOEQUALS" not in values


def test_env_overlay_precedence(tmp_path, monkeypatch):
    configs = tmp_path / "configs"
    configs.mkdir()
    (configs / ".env").write_text("A=base\nB=base\nC=base\n")
    (configs / ".prod.env").write_text("B=prod\n")
    monkeypatch.setenv("APP_ENV", "prod")
    monkeypatch.setenv("C", "process")
    config = EnvConfig(str(configs))
    assert config.get("A") == "base"        # base survives
    assert config.get("B") == "prod"        # overlay wins over base
    assert config.get("C") == "process"     # process env wins over all


def test_window_gauge_and_stats_exposed():
    """The attention-window rung is observable: stats() lists the ladder
    and a tick sets the app_tpu_attention_window gauge."""
    import asyncio

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.generate import GenerationEngine

    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))
    # no manual registration: the framework catalog (container.py
    # register_framework_metrics) must provide the gauge
    container = new_mock_container()
    engine = GenerationEngine(cfg, params, max_slots=2, max_len=256,
                              prompt_buckets=(8,),
                              logger=container.logger,
                              metrics=container.metrics)
    assert engine.stats()["window_ladder"] == [128, 256]

    async def main():
        await engine.start()
        try:
            await asyncio.wait_for(
                engine.generate([1, 2, 3], max_new_tokens=4), 60.0)
            assert container.metrics.value(
                "app_tpu_attention_window", model="generate") == 128.0
        finally:
            await engine.stop()
    asyncio.run(main())
