"""Kafka backend — pure-Python wire-protocol client, no driver dependency.

Capability parity with ``pkg/gofr/datasource/pubsub/kafka`` (kafka.go:42-105
client + dial + writer config; Publish 127-165 w/ counters; Subscribe
167-220 lazily creating a per-topic reader; commit-on-success via
``kafkaMessage.Commit``; Create/DeleteTopic 247-264; health.go). The
reference wraps segmentio/kafka-go; this zero-egress image has no Kafka
driver, so the client speaks the wire protocol directly:

  Metadata v1 · Produce v2 (message-set v1 + CRC32) · Fetch v2 ·
  ListOffsets v1 · OffsetFetch v1 · OffsetCommit v2 ·
  CreateTopics v0 · DeleteTopics v0

Consumer model: per-topic poller thread fetches every partition from the
group's committed offset (offset storage on the broker, simple static
assignment — group *rebalancing* is delegated to deployment the way the
reference delegates scale-out to consumer groups + k8s, SURVEY.md §2.8).
Commit-on-success: ``Message.commit()`` advances the group offset.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from gofr_tpu.datasource.pubsub.base import Message, PubSub

API_PRODUCE, API_FETCH, API_LIST_OFFSETS, API_METADATA = 0, 1, 2, 3
API_OFFSET_COMMIT, API_OFFSET_FETCH = 8, 9
API_CREATE_TOPICS, API_DELETE_TOPICS = 19, 20


class KafkaError(Exception):
    pass


class KafkaOffsetOutOfRange(KafkaError):
    """Fetch error 1: committed offset expired (retention) or invalid —
    the consumer must reset to the earliest available offset."""


# -- primitive codecs --------------------------------------------------------

def _string(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode()
    return struct.pack(">h", len(raw)) + raw


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.offset = 0

    def int8(self):  return self._unpack(">b", 1)
    def int16(self): return self._unpack(">h", 2)
    def int32(self): return self._unpack(">i", 4)
    def int64(self): return self._unpack(">q", 8)

    def _unpack(self, fmt, size):
        value = struct.unpack_from(fmt, self.data, self.offset)[0]
        self.offset += size
        return value

    def string(self) -> Optional[str]:
        n = self.int16()
        if n == -1:
            return None
        raw = self.data[self.offset:self.offset + n]
        self.offset += n
        return raw.decode()

    def raw_bytes(self) -> Optional[bytes]:
        n = self.int32()
        if n == -1:
            return None
        raw = self.data[self.offset:self.offset + n]
        self.offset += n
        return raw


def encode_message_set(items: List[Tuple[bytes, bytes]]) -> bytes:
    """Message-set v1 (magic 1): [offset][size][crc][magic][attrs][ts][k][v]."""
    out = bytearray()
    timestamp = int(time.time() * 1000)
    for key, value in items:
        body = (struct.pack(">bbq", 1, 0, timestamp) + _bytes(key or None)
                + _bytes(value))
        crc = zlib.crc32(body) & 0xFFFFFFFF
        message = struct.pack(">I", crc) + body
        out += struct.pack(">q", 0) + struct.pack(">i", len(message)) + message
    return bytes(out)


def decode_message_set(data: bytes, queue_offset: int
                       ) -> List[Tuple[int, bytes, bytes]]:
    """→ [(offset, key, value)]; tolerates a truncated trailing message."""
    out: List[Tuple[int, bytes, bytes]] = []
    reader = _Reader(data)
    while reader.offset + 12 <= len(data):
        offset = reader.int64()
        size = reader.int32()
        if reader.offset + size > len(data):
            break
        end = reader.offset + size
        reader.int32()                       # crc (trusting TCP checksums)
        magic = reader.int8()
        attrs = reader.int8()
        if magic >= 1:
            reader.int64()                   # timestamp
        key = reader.raw_bytes() or b""
        value = reader.raw_bytes() or b""
        if attrs & 0x07:
            raise KafkaError("compressed message sets not supported")
        if offset >= queue_offset:
            out.append((offset, key, value))
        reader.offset = end
    return out


class _Broker:
    """One TCP connection + request/response correlation."""

    def __init__(self, host: str, port: int, client_id: str):
        self.host = host
        self.port = port
        self.client_id = client_id
        self.correlation = 0
        self.lock = threading.Lock()
        self.sock = None
        self.closed = False
        self._connect()

    def _connect(self) -> None:
        if self.closed:
            raise KafkaError("broker handle is closed")
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        sock = socket.create_connection((self.host, self.port),
                                        timeout=10.0)
        self.sock = sock
        if self.closed:   # close() raced the reconnect: don't leak it
            sock.close()
            raise KafkaError("broker handle is closed")

    def call(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        # One reconnect-and-reissue on transport failure (dead socket —
        # broker restart), the same treatment as the Redis wire client.
        # Standard Kafka at-least-once semantics: a retried Produce whose
        # first attempt partially landed may duplicate, never lose.
        with self.lock:
            try:
                response = self._exchange(api_key, api_version, body)
            except OSError:
                self._connect()     # refuses after close(): no leaks
                response = self._exchange(api_key, api_version, body)
            expected = self.correlation
        reader = _Reader(response)
        correlation = reader.int32()
        if correlation != expected:
            raise KafkaError("correlation id mismatch")
        return reader

    def _exchange(self, api_key: int, api_version: int,
                  body: bytes) -> bytes:
        self.correlation += 1
        header = (struct.pack(">hhi", api_key, api_version,
                              self.correlation)
                  + _string(self.client_id))
        payload = header + body
        self.sock.sendall(struct.pack(">i", len(payload)) + payload)
        size = struct.unpack(">i", self._read(4))[0]
        return self._read(size)

    def _read(self, n: int) -> bytes:
        data = b""
        while len(data) < n:
            chunk = self.sock.recv(n - len(data))
            if not chunk:
                raise ConnectionError("broker connection closed")
            data += chunk
        return data

    def close(self):
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass


class KafkaClient(PubSub):
    def __init__(self, config, logger, metrics):
        self.logger = logger
        self.metrics = metrics
        broker = config.get_or_default("PUBSUB_BROKER",
                                       config.get_or_default("KAFKA_BROKER",
                                                             "localhost:9092"))
        host, _, port = broker.partition(":")
        self.bootstrap = (host, int(port or 9092))
        self.group = config.get_or_default("CONSUMER_ID", "gofr-tpu")
        self.client_id = config.get_or_default("APP_NAME", "gofr-tpu-app")
        self.fetch_max_wait_ms = config.get_int("KAFKA_FETCH_MAX_WAIT_MS", 250)
        self._brokers: Dict[Tuple[str, int], _Broker] = {}
        self._meta_lock = threading.Lock()
        self._leaders: Dict[Tuple[str, int], Tuple[str, int]] = {}
        self._queues: Dict[str, "queue.Queue[Optional[Message]]"] = {}
        self._pollers: Dict[str, threading.Thread] = {}
        self._closed = False
        self._broker(self.bootstrap)  # fail fast if unreachable
        logger.info("kafka connected %s:%d group=%s", *self.bootstrap,
                    self.group)

    def _broker(self, addr: Tuple[str, int]) -> _Broker:
        broker = self._brokers.get(addr)
        if broker is None:
            broker = _Broker(addr[0], addr[1], self.client_id)
            self._brokers[addr] = broker
        return broker

    # -- metadata / leader routing -----------------------------------------
    def _refresh_metadata(self, topic: str) -> List[int]:
        reader = self._broker(self.bootstrap).call(
            API_METADATA, 1, struct.pack(">i", 1) + _string(topic))
        nodes: Dict[int, Tuple[str, int]] = {}
        for _ in range(reader.int32()):          # brokers
            node_id = reader.int32()
            host = reader.string()
            port = reader.int32()
            reader.string()                      # rack
            nodes[node_id] = (host, port)
        reader.int32()                           # controller id
        partitions: List[int] = []
        for _ in range(reader.int32()):          # topics
            reader.int16()                       # topic error
            name = reader.string()
            reader.int8()                        # is_internal
            for _ in range(reader.int32()):
                reader.int16()                   # partition error
                partition = reader.int32()
                leader = reader.int32()
                for _ in range(reader.int32()):  # replicas
                    reader.int32()
                for _ in range(reader.int32()):  # isr
                    reader.int32()
                if name == topic:
                    partitions.append(partition)
                    if leader in nodes:
                        with self._meta_lock:
                            self._leaders[(topic, partition)] = nodes[leader]
        return sorted(partitions)

    def _leader(self, topic: str, partition: int) -> _Broker:
        addr = self._leaders.get((topic, partition))
        if addr is None:
            self._refresh_metadata(topic)
            addr = self._leaders.get((topic, partition), self.bootstrap)
        return self._broker(addr)

    # -- produce ------------------------------------------------------------
    def publish(self, topic: str, payload: bytes, key: bytes = b"") -> None:
        self.metrics.increment_counter("app_pubsub_publish_total_count",
                                       topic=topic)
        partitions = self._refresh_metadata(topic) or [0]
        partition = (zlib.crc32(key) % len(partitions)) if key \
            else int(time.time() * 1e6) % len(partitions)
        message_set = encode_message_set([(key, payload)])
        body = (struct.pack(">hi", 1, 10000)          # acks=1, timeout
                + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition)
                + _bytes(message_set))
        reader = self._leader(topic, partition).call(API_PRODUCE, 2, body)
        for _ in range(reader.int32()):
            reader.string()                           # topic
            for _ in range(reader.int32()):
                reader.int32()                        # partition
                error = reader.int16()
                reader.int64()                        # base offset
                reader.int64()                        # log append time
                if error:
                    raise KafkaError(f"produce error code {error}")
        self.metrics.increment_counter("app_pubsub_publish_success_count",
                                       topic=topic)

    # -- offsets ------------------------------------------------------------
    def _committed_offset(self, topic: str, partition: int) -> int:
        body = (_string(self.group) + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition))
        reader = self._broker(self.bootstrap).call(API_OFFSET_FETCH, 1, body)
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()
                offset = reader.int64()
                reader.string()                       # metadata
                reader.int16()                        # error
                return max(0, offset)
        return 0

    def _earliest_offset(self, topic: str, partition: int) -> int:
        body = (struct.pack(">i", -1) + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iq", partition, -2))   # -2 = earliest
        reader = self._leader(topic, partition).call(API_LIST_OFFSETS, 1,
                                                     body)
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()
                error = reader.int16()
                reader.int64()                        # timestamp
                offset = reader.int64()
                if error:
                    raise KafkaError(f"list offsets error {error}")
                return offset
        return 0

    def _commit_offset(self, topic: str, partition: int, offset: int) -> None:
        body = (_string(self.group) + struct.pack(">i", -1) + _string("")
                + struct.pack(">q", -1)
                + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iq", partition, offset) + _string(None))
        reader = self._broker(self.bootstrap).call(API_OFFSET_COMMIT, 2, body)
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()
                error = reader.int16()
                if error:
                    self.logger.error("kafka offset commit error %d", error)

    # -- fetch loop (per-topic reader, kafka.go:181-186) --------------------
    def _poll_topic(self, topic: str) -> None:
        """Per-topic fetch loop. Survives broker outages: an errored pass
        (fetch/metadata failure beyond call()'s one immediate reconnect)
        backs off and retries from the committed offset instead of dying —
        otherwise the first multi-second restart would permanently kill
        the subscription while publish happily recovers."""
        q = self._queues[topic]
        backoff = 0.1
        metadata_refresh_s = 30.0
        while not self._closed:
            try:
                offsets: Dict[int, int] = {}
                partitions = self._refresh_metadata(topic)
                if not partitions:
                    # topic doesn't exist yet (or metadata stale): retry
                    # via the backoff path instead of idling forever
                    raise KafkaError(f"no partitions for topic {topic!r}")
                for partition in partitions:
                    committed = self._committed_offset(topic, partition)
                    offsets[partition] = committed or self._earliest_offset(
                        topic, partition)
                refresh_at = time.monotonic() + metadata_refresh_s
                while not self._closed:
                    got_any = False
                    for partition in partitions:
                        try:
                            batch = self._fetch(topic, partition,
                                                offsets[partition])
                        except KafkaOffsetOutOfRange:
                            # retention expired past the committed offset:
                            # reset to earliest (auto.offset.reset analog)
                            offsets[partition] = self._earliest_offset(
                                topic, partition)
                            continue
                        for offset, key, value in batch:
                            offsets[partition] = offset + 1
                            committer = self._make_committer(
                                topic, partition, offset + 1)
                            q.put(Message(topic, value, key,
                                          metadata={"partition": partition,
                                                    "offset": offset},
                                          committer=committer))
                            got_any = True
                    backoff = 0.1   # a clean pass resets the backoff
                    if time.monotonic() >= refresh_at:
                        # periodically re-learn partitions (growth after
                        # subscribe) without waiting for an error
                        new = self._refresh_metadata(topic)
                        for partition in new:
                            if partition not in offsets:
                                offsets[partition] = self._earliest_offset(
                                    topic, partition)
                        partitions = new or partitions
                        refresh_at = time.monotonic() + metadata_refresh_s
                    if not got_any:
                        time.sleep(self.fetch_max_wait_ms / 1000.0)
            except Exception as exc:
                if self._closed:
                    break
                self.logger.error(
                    "kafka poller %s errored (retrying in %.1fs): %r",
                    topic, backoff, exc)
                time.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
        q.put(None)

    def _make_committer(self, topic, partition, next_offset):
        return lambda: self._commit_offset(topic, partition, next_offset)

    def _fetch(self, topic: str, partition: int,
               offset: int) -> List[Tuple[int, bytes, bytes]]:
        body = (struct.pack(">iii", -1, self.fetch_max_wait_ms, 1)
                + struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 1)
                + struct.pack(">iqi", partition, offset, 4 * 1024 * 1024))
        reader = self._leader(topic, partition).call(API_FETCH, 2, body)
        reader.int32()                                # throttle time
        out: List[Tuple[int, bytes, bytes]] = []
        for _ in range(reader.int32()):
            reader.string()
            for _ in range(reader.int32()):
                reader.int32()                        # partition
                error = reader.int16()
                reader.int64()                        # high watermark
                message_set = reader.raw_bytes() or b""
                if error == 1:
                    raise KafkaOffsetOutOfRange(
                        f"offset {offset} out of range for "
                        f"{topic}/{partition}")
                if error:
                    raise KafkaError(f"fetch error code {error}")
                out.extend(decode_message_set(message_set, offset))
        return out

    async def subscribe(self, topic: str) -> Optional[Message]:
        import asyncio
        self.metrics.increment_counter("app_pubsub_subscribe_total_count",
                                       topic=topic)
        if topic not in self._pollers:
            self._queues[topic] = queue.Queue(maxsize=65536)
            poller = threading.Thread(target=self._poll_topic, args=(topic,),
                                      daemon=True, name=f"kafka-{topic}")
            self._pollers[topic] = poller
            poller.start()
        message = await asyncio.get_running_loop().run_in_executor(
            None, self._queues[topic].get)
        if message is not None:
            self.metrics.increment_counter(
                "app_pubsub_subscribe_success_count", topic=topic)
        return message

    # -- topic admin (kafka.go:247-264) -------------------------------------
    def create_topic(self, topic: str, partitions: int = 1,
                     replication: int = 1) -> None:
        body = (struct.pack(">i", 1) + _string(topic)
                + struct.pack(">ih", partitions, replication)
                + struct.pack(">i", 0)                # assignments
                + struct.pack(">i", 0)                # configs
                + struct.pack(">i", 10000))           # timeout
        reader = self._broker(self.bootstrap).call(API_CREATE_TOPICS, 0, body)
        for _ in range(reader.int32()):
            reader.string()
            error = reader.int16()
            if error and error != 36:                 # 36 = already exists
                raise KafkaError(f"create topic error {error}")

    def delete_topic(self, topic: str) -> None:
        body = (struct.pack(">i", 1) + _string(topic)
                + struct.pack(">i", 10000))
        reader = self._broker(self.bootstrap).call(API_DELETE_TOPICS, 0, body)
        for _ in range(reader.int32()):
            reader.string()
            error = reader.int16()
            if error and error != 3:                  # 3 = unknown topic
                raise KafkaError(f"delete topic error {error}")

    def health_check(self) -> dict:
        try:
            self._broker(self.bootstrap).call(
                API_METADATA, 1, struct.pack(">i", 0))
            return {"status": "UP",
                    "details": {"backend": "KAFKA",
                                "broker": f"{self.bootstrap[0]}:"
                                          f"{self.bootstrap[1]}",
                                "group": self.group}}
        except Exception as exc:
            return {"status": "DOWN", "details": {"error": repr(exc)}}

    def close(self) -> None:
        self._closed = True
        for q in self._queues.values():
            q.put(None)
        for broker in self._brokers.values():
            broker.close()
