"""GT004 traced side effects: host-world calls inside jit-traced bodies.

A ``print`` / logger / metrics call inside a jit-traced function body
runs **once, at trace time**, then never again — the dashboard metric
you think is per-step is per-compile, and the log line prints a tracer.
A Python ``if`` on a traced value is worse: ``ConcretizationTypeError``
at trace time, or — when callers happen to pass Python scalars — a
hidden static argument that recompiles per distinct value.

Traced bodies are resolved module-locally: functions decorated with
``@jax.jit`` / ``@partial(jax.jit, ...)``, functions wrapped by a
``jax.jit(fn)`` call in the same scope, and every ``def`` nested inside
a traced body (``lax.scan`` step functions — see
``GenerationEngine._decode_fn``'s ``one``).

Flags inside a traced body:

- calls to ``print`` and to logger-shaped receivers
  (``logger.info/debug/warning/error/...``) — use ``jax.debug.print`` /
  ``jax.debug.callback`` when you really need trace-time output;
- Manager metric observations (``increment_counter`` etc.) — record
  metrics at the dispatch site, outside the traced body;
- ``if``/ternary on a bare parameter of the traced function. Structure
  checks stay exempt: ``x is None``, ``isinstance(...)``,
  ``x.shape/ndim/dtype/size``, ``len(x)`` are resolved at trace time
  and legitimately steer tracing.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule
from gofr_tpu.analysis.rules.gt003_recompile import (
    _is_jit,
    _static_positions,
)

_LOGGER_METHODS = {"debug", "info", "warn", "warning", "error", "exception",
                   "critical", "fatal"}
_METRIC_METHODS = {"increment_counter", "delta_updown_counter",
                   "record_histogram", "set_gauge"}
_DEBUG_OK = {"jax.debug.print", "jax.debug.callback",
             "jax.experimental.io_callback", "io_callback"}


def _traced_defs(module: ModuleInfo) -> List[ast.AST]:
    """Function defs whose bodies jit traces, with their static argnames
    attached as ``_graftcheck_static``."""
    by_name = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    traced: List[ast.AST] = []

    def mark(defn: ast.AST, static_nums: Set[int],
             static_names: Set[str]) -> None:
        params = [a.arg for a in defn.args.args]
        static = set(static_names)
        static.update(params[i] for i in static_nums if i < len(params))
        defn._graftcheck_static = static
        traced.append(defn)

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if module.dotted(deco) in ("jax.jit", "jax.api.jit"):
                    mark(node, set(), set())
                else:
                    jit_call = _is_jit(module, deco)
                    if jit_call is not None:
                        nums, names = _static_positions(jit_call)
                        mark(node, nums, names)
        jit_call = _is_jit(module, node) if isinstance(node, ast.Call) \
            else None
        if jit_call is not None and jit_call.args:
            target = jit_call.args[0]
            if isinstance(target, ast.Name) and target.id in by_name:
                nums, names = _static_positions(jit_call)
                for defn in by_name[target.id]:
                    if not hasattr(defn, "_graftcheck_static"):
                        mark(defn, nums, names)
    return traced


class TracedSideEffectsRule(Rule):
    rule_id = "GT004"
    title = "traced-side-effects"
    severity = "error"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for defn in _traced_defs(module):
            static = getattr(defn, "_graftcheck_static", set())
            params = {a.arg for a in defn.args.args}
            # nested defs (lax.scan step fns) trace too — their params
            # carry tracers from the enclosing trace
            for node in ast.walk(defn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and node is not defn:
                    params.update(a.arg for a in node.args.args)
            params -= static
            for node in ast.walk(defn):
                findings.extend(
                    self._check_node(module, defn, node, params))
        # dedupe: nested traced defs are walked once via their parent and
        # once if independently marked
        unique = {}
        for finding in findings:
            unique[(finding.path, finding.line, finding.key)] = finding
        return list(unique.values())

    def _check_node(self, module: ModuleInfo, defn: ast.AST, node: ast.AST,
                    params: Set[str]) -> Iterable[Finding]:
        if isinstance(node, ast.Call):
            label = self._effect_label(module, node)
            if label is not None:
                return (Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=node.lineno,
                    message=(
                        f"traced side effect: {label} inside jit-traced "
                        f"'{defn.name}' runs once at trace time, not per "
                        f"step — hoist it to the dispatch site or use "
                        f"jax.debug.print/callback"),
                    severity=self.severity,
                    key=f"{label} in {defn.name}",
                ),)
        if isinstance(node, (ast.If, ast.IfExp)):
            name = self._tracer_test(module, node.test, params)
            if name is not None:
                return (Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=node.lineno,
                    message=(
                        f"traced side effect: Python 'if' on traced "
                        f"parameter '{name}' of jit-traced '{defn.name}' "
                        f"— concretization error at trace time (or a "
                        f"hidden per-value recompile); use jnp.where/"
                        f"lax.cond, or declare the arg static"),
                    severity=self.severity,
                    key=f"if {name} in {defn.name}",
                ),)
        return ()

    def _effect_label(self, module: ModuleInfo,
                      call: ast.Call) -> Optional[str]:
        dotted = module.dotted(call.func)
        if dotted in _DEBUG_OK:
            return None
        if isinstance(call.func, ast.Name) and call.func.id == "print":
            return "print(...)"
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            receiver = call.func.value
            receiver_name = ""
            if isinstance(receiver, ast.Name):
                receiver_name = receiver.id
            elif isinstance(receiver, ast.Attribute):
                receiver_name = receiver.attr
            if attr in _LOGGER_METHODS and "log" in receiver_name.lower():
                return f"{receiver_name}.{attr}(...)"
            if attr in _METRIC_METHODS:
                return f".{attr}(...)"
        return None

    def _tracer_test(self, module: ModuleInfo, test: ast.AST,
                     params: Set[str]) -> Optional[str]:
        """Name of a traced param the test branches on, or None if the
        test only inspects static structure."""

        def walk_skipping_is(node):
            # `x is None` / `x is not None` compares pytree structure,
            # resolved at trace time — never a tracer branch
            if isinstance(node, ast.Compare) and \
                    any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
                return
            yield node
            for child in ast.iter_child_nodes(node):
                yield from walk_skipping_is(child)

        for node in walk_skipping_is(test):
            if not (isinstance(node, ast.Name) and node.id in params):
                continue
            parent = module.parents.get(node)
            if isinstance(parent, ast.Attribute):
                continue  # x.shape / x.dtype / cfg.flag — static lookups
            if isinstance(parent, ast.Call) and node in parent.args and \
                    isinstance(parent.func, ast.Name) and \
                    parent.func.id in ("len", "isinstance", "getattr",
                                       "hasattr", "type"):
                continue
            return node.id
        return None
