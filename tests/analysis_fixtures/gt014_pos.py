"""GT014 positive fixture: serving knobs written directly from outside
the owning object's guarded apply path — every one of these bypasses
pre-warm, brownout refusal, and the atomic swap."""


def cron_quick_fix(engine):
    # direct knob writes from a cron handler: the canonical bypass
    engine.steps_per_tick = 8
    engine.prompt_buckets = (16, 64)


def handler_tweaks(ctx):
    batcher = ctx.container.tpu_batcher
    # batcher coalescing knobs are serving knobs too
    batcher.max_batch = 64
    batcher.max_delay = 0.01


def creeping_writes(engine):
    # augmented assignment is the same mutation
    engine.slots_cap += 2
    # subscript store mutates the knob in place
    engine.class_weights["batch"] = 9.0
    # one more underscore is not a laundering device
    engine._gamma_cap = 1


def sanctioned_forensics(engine):
    # a deliberate, reviewed exception rides the pragma
    engine.steps_per_tick = 1  # graftcheck: ignore[GT014]
