"""Fused ragged paged attention — one Pallas TPU kernel over
variable-length page tables (ROADMAP top item, after "Ragged Paged
Attention", arxiv 2604.15464).

The gather formulation (ops/attention.paged_decode_attention) simulates
raggedness: it materializes a dense ``(B, P*page)`` KV view per layer
with ``P`` a static ladder rung, so every width rung is a separately
compiled executable (graftcheck's GT003 page-width hazard class exists
because of it) and HBM bandwidth is spent rebuilding views the kernel
could walk in place. This kernel walks them in place:

- Grid ``(slot, kv-page-block)``; the page table and per-slot fill ride
  **scalar prefetch**, so each program's K/V BlockSpec index map reads
  its slot's *actual* pool row directly from the table — no materialized
  gather, no static width ladder, one executable for every fill level.
- TWO-PHASE page walk for token identity: the page-block axis runs the
  table twice. Phase 0 streams K only and finishes the softmax
  statistics (max and normalizer in VMEM scratch); phase 1 re-derives
  each block's scores, materializes the *final* per-position
  probabilities, and accumulates P·V. A single-pass online-softmax
  kernel is cheaper but renormalizes probabilities with correction
  factors the gather oracle never applies — its probs are rounded to the
  cache dtype *after* global normalization, and at bf16 that rounding
  difference walks greedy decode off the oracle's token stream within a
  few ticks. Phase 1 reproduces the oracle's rounding points exactly
  (scores rounded at the einsum boundary, probs rounded post-
  normalization, cache/new contributions added in cache dtype), so
  kernel vs gather is bit-equal up to f32 sum-order noise that the
  dtype rounding absorbs. Cost: K streams twice, V once (V's index map
  parks on one row during phase 0 so no dead fetches) — still far below
  the gather path, which writes AND reads a materialized (B, P·page)
  copy of both K and V every layer.
- Pages past the slot's fill are clamped to the last valid row in the
  index map (the pipeline elides re-fetching an unchanged block) and
  their compute is skipped with ``pl.when`` — sentinel page ids are
  never dereferenced, which the tests assert by poisoning unreferenced
  pages with NaN.
- int8 pools dequantize **in-kernel** from the scale planes that live
  beside the pages (k/v scaled to f32 before the dots — the same math
  as the gather path's post-einsum score folding, without ever
  materializing a converted cache copy).
- The γ+1-token query variant (:func:`ragged_paged_verify_attention`)
  backs speculative verify: G queries at positions ``cache_len + g``
  attend the paged cache plus each other causally, so verify stops
  paying prefill-shaped attention.

Post-mortem context (ops/pallas/decode_attention): the dense flash
prototype lost 5x *inside* the per-layer scan because each pallas_call
is an opaque boundary to XLA's weight-prefetch pipeline. The economics
here differ — this kernel *replaces* a per-layer HBM gather
materialization instead of competing with a fused einsum — but the same
rule applies: judge it on the full decode tick (bench.py
``llama_ragged_attn``), never the standalone op. Off-TPU or on
tiling-miss shapes it falls back to the gather formulation, which stays
the correctness oracle.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.ops.pallas.fallback import (ragged_shapes_supported,
                                          resolve_interpret)

_NEG_INF = -1e30

__all__ = ["ragged_paged_decode_attention", "ragged_paged_verify_attention",
           "ragged_supported"]


def ragged_supported(head_dim: int, q_heads: int, kv_heads: int, page: int,
                     interpret: Optional[bool] = None) -> bool:
    """Would these shapes run the fused kernel (vs the gather fallback)?
    The engine's ``ragged_attn="auto"`` resolves through this."""
    return ragged_shapes_supported(head_dim, q_heads, kv_heads, page,
                                   resolve_interpret(interpret))


def _ragged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, kn_ref, vn_ref,
                   *rest, page: int, num_pi: int, kv_heads: int, group: int,
                   g_len: int, int8: bool, sm_scale: float):
    """One (slot, walk-step) program on the doubled page-block axis.

    Steps ``[0, num_pi)`` are phase 0 (K only): accumulate the softmax
    max and normalizer over the slot's live pages, then fold the G new
    tokens' scores so the statistics are FINAL. Steps
    ``[num_pi, 2*num_pi)`` are phase 1: re-derive each block's scores,
    form the oracle's exact per-position probabilities (rounded to the
    cache dtype after normalization, just like the gather path's
    ``probs.astype(q.dtype)``), and accumulate P·V; the last step adds
    the new tokens' contribution and writes the output. ``rest`` is
    (ks, vs, out, acc, m, l) on int8 pools — the scale-plane blocks ride
    the same index maps as their pages — and (out, acc, m, l) on bf16
    pools, so bf16 never fetches a dead operand."""
    from jax.experimental import pallas as pl

    if int8:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest

    b = pl.program_id(0)
    pi = pl.program_id(1)
    pj = lax.rem(pi, num_pi)                   # page index within a phase
    length = len_ref[b]                        # valid tokens, excl. new
    cdt = o_ref.dtype                          # the oracle's cache dtype

    rp_bits = None
    if jnp.finfo(cdt).bits < 32:
        rp_bits = (jnp.finfo(cdt).nexp, jnp.finfo(cdt).nmant)

    def _round(x):
        # the gather oracle snaps to the cache dtype's precision at every
        # materialization point (ops/attention._snap): mimic it with the
        # same reduce_precision — an astype round-trip could be folded
        # away by the compiler, silently moving the rounding points
        # (identity at f32)
        if rp_bits is None:
            return x
        return lax.reduce_precision(x, *rp_bits)

    @pl.when(pi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def q_rows():
        # (G, Hq, D) -> per-kv-head (G*group, D) row stacks; query g of
        # kv-head h owns rows [g*group, (g+1)*group). UNSCALED: the
        # oracle applies sm_scale after the (rounded) score einsum.
        q = q_ref[0].astype(jnp.float32).reshape(g_len, kv_heads, group, -1)
        return [q[:, h].reshape(g_len * group, -1) for h in range(kv_heads)]

    def block_scores():
        # per-kv-head dots unrolled in Python: Mosaic does not lower a
        # batched dot_general with unequal non-contracting dims. Rounding
        # order matches the oracle exactly: dot -> cache-dtype round ->
        # * sm_scale -> (* k_scale on int8) -> length mask.
        qh = q_rows()
        k_blk = k_ref[0].astype(jnp.float32)       # (page, Hkv, D)
        parts = []
        for h in range(kv_heads):
            s_h = _round(jnp.dot(qh[h], k_blk[:, h, :].T,
                                 preferred_element_type=jnp.float32))
            s_h = s_h * sm_scale                   # (G*grp, page)
            if int8:
                # fused dequant, oracle formulation: the int8 scores are
                # exact through the rounded dot, and the per-vector scale
                # folds into f32 AFTER — never a converted cache copy
                s_h = s_h * ks_ref[0][:, h][None, :]
            parts.append(s_h)
        scores = jnp.concatenate(parts, axis=0)    # (rows, page)
        pos = pj * page + lax.broadcasted_iota(jnp.int32, (1, page), 1)
        return jnp.where(pos < length, scores, _NEG_INF)

    def new_scores():
        # the G new tokens (positions length..length+G-1, causal among
        # themselves: key u attends to query s iff u <= s); their K
        # arrives unquantized even on int8 pools (oracle contract)
        qh = q_rows()
        k_new = kn_ref[0].astype(jnp.float32)      # (G, Hkv, D)
        s_new = jnp.concatenate(
            [_round(jnp.dot(qh[h], k_new[:, h, :].T,
                            preferred_element_type=jnp.float32)) * sm_scale
             for h in range(kv_heads)], axis=0)    # (rows, G)
        q_pos = lax.broadcasted_iota(
            jnp.int32, (g_len * group, g_len), 0) // group
        u_pos = lax.broadcasted_iota(
            jnp.int32, (g_len * group, g_len), 1)
        causal = u_pos <= q_pos
        return jnp.where(jnp.tile(causal, (kv_heads, 1)), s_new, _NEG_INF)

    # -- phase 0: softmax statistics over the live pages ------------------
    @pl.when(jnp.logical_and(pi < num_pi, pj * page < length))
    def _stats_step():
        scores = block_scores()
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = (l_prev * corr
                    + jnp.exp(scores - m_new).sum(axis=-1, keepdims=True))

    @pl.when(pi == num_pi - 1)
    def _stats_finish():
        # fold the new tokens' scores: m/l are FINAL after this step (the
        # causal diagonal guarantees l >= 1, so phase 1 never divides by
        # zero)
        s_new = new_scores()
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_fin = jnp.maximum(m_prev, s_new.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_fin)
        m_ref[:] = m_fin
        l_ref[:] = (l_prev * corr
                    + jnp.exp(s_new - m_fin).sum(axis=-1, keepdims=True))

    # -- phase 1: oracle-identical probabilities, P·V accumulation --------
    @pl.when(jnp.logical_and(pi >= num_pi, pj * page < length))
    def _value_step():
        p = jnp.exp(block_scores() - m_ref[:]) / l_ref[:]  # (rows, page)
        if not int8:
            p = _round(p)                      # probs.astype(q.dtype)
        v_blk = v_ref[0].astype(jnp.float32)
        p3 = p.reshape(kv_heads, g_len * group, page)
        parts = []
        for h in range(kv_heads):
            ph = p3[h]
            if int8:
                # oracle int8 V path: normalized probs stay f32 and the
                # per-vector scale folds in pre-einsum (precision over
                # bandwidth — see decode_attention_cached)
                ph = ph * vs_ref[0][:, h][None, :]
            parts.append(jnp.dot(ph, v_blk[:, h, :],
                                 preferred_element_type=jnp.float32))
        acc_ref[:] += jnp.concatenate(parts, axis=0)       # (rows, D)

    @pl.when(pi == 2 * num_pi - 1)
    def _finish():
        p_new = _round(jnp.exp(new_scores() - m_ref[:]) / l_ref[:])
        v_new = vn_ref[0].astype(jnp.float32)      # (G, Hkv, D)
        p3 = p_new.reshape(kv_heads, g_len * group, g_len)
        pv = jnp.concatenate(
            [jnp.dot(p3[h], v_new[:, h, :],
                     preferred_element_type=jnp.float32)
             for h in range(kv_heads)], axis=0)            # (rows, D)
        # the oracle snaps the cache and new-token einsum outputs, adds
        # them in f32 and snaps the sum (ops/attention._snap schedule)
        out = _round(_round(acc_ref[:]) + _round(pv))      # (rows, D)
        head_dim = out.shape[-1]
        o_ref[0] = out.reshape(kv_heads, g_len, group, head_dim) \
            .swapaxes(0, 1).reshape(g_len, kv_heads * group, head_dim) \
            .astype(o_ref.dtype)


def _pallas_ragged(q, k_pages, v_pages, page_table, k_new, v_new,
                   cache_len, k_scale_pages, v_scale_pages,
                   interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, g_len, q_heads, head_dim = q.shape
    num_pages, page, kv_heads, _ = k_pages.shape
    group = q_heads // kv_heads
    num_pi = page_table.shape[1]
    int8 = k_scale_pages is not None
    table = page_table.astype(jnp.int32)
    lens = cache_len.astype(jnp.int32)

    def _row(b, pj, table_ref, len_ref):
        # scalar-prefetch table walk: fetch this slot's ACTUAL pool row.
        # Clamp pj to the last page holding valid tokens (the pipeline
        # elides re-fetching an unchanged row, so the dead tail of the
        # table is never streamed), then clamp a sentinel id in-bounds —
        # its compute is skipped by pl.when, never attended.
        length = len_ref[b]
        last = jnp.maximum(lax.div(length + page - 1, page) - 1, 0)
        pid = table_ref[b, jnp.minimum(pj, last)]
        return jnp.minimum(pid, num_pages - 1)

    def k_index(b, pi, table_ref, len_ref):
        # K streams in BOTH phases (scores are re-derived in phase 1)
        return (_row(b, lax.rem(pi, num_pi), table_ref, len_ref), 0, 0, 0)

    def v_index(b, pi, table_ref, len_ref):
        # V is only read in phase 1; during phase 0 the map parks on the
        # row phase 1 fetches first, so no dead V block is ever streamed
        pj = jnp.where(pi >= num_pi, lax.rem(pi, num_pi), 0)
        return (_row(b, pj, table_ref, len_ref), 0, 0, 0)

    def ks_index(b, pi, table_ref, len_ref):
        return k_index(b, pi, table_ref, len_ref)[:3]

    def vs_index(b, pi, table_ref, len_ref):
        return v_index(b, pi, table_ref, len_ref)[:3]

    def q_index(b, pi, table_ref, len_ref):
        return (b, 0, 0, 0)

    kernel = functools.partial(
        _ragged_kernel, page=page, num_pi=num_pi, kv_heads=kv_heads,
        group=group, g_len=g_len, int8=int8, sm_scale=head_dim ** -0.5)
    in_specs = [
        pl.BlockSpec((1, g_len, q_heads, head_dim), q_index),
        pl.BlockSpec((1, page, kv_heads, head_dim), k_index),
        pl.BlockSpec((1, page, kv_heads, head_dim), v_index),
        pl.BlockSpec((1, g_len, kv_heads, head_dim), q_index),
        pl.BlockSpec((1, g_len, kv_heads, head_dim), q_index),
    ]
    operands = [q, k_pages, v_pages, k_new, v_new]
    if int8:
        in_specs += [pl.BlockSpec((1, page, kv_heads), ks_index),
                     pl.BlockSpec((1, page, kv_heads), vs_index)]
        operands += [k_scale_pages, v_scale_pages]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, 2 * num_pi),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, g_len, q_heads, head_dim), q_index),
        scratch_shapes=[
            pltpu.VMEM((g_len * q_heads, head_dim), jnp.float32),
            pltpu.VMEM((g_len * q_heads, 1), jnp.float32),
            pltpu.VMEM((g_len * q_heads, 1), jnp.float32),
        ],
    )
    compiler_params = None
    if not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=compiler_params,
        interpret=interpret,
    )(table, lens, *operands)


def ragged_paged_decode_attention(q, k_pages, v_pages, page_table, k_new,
                                  v_new, cache_len, k_scale_pages=None,
                                  v_scale_pages=None,
                                  interpret: Optional[bool] = None
                                  ) -> jnp.ndarray:
    """Drop-in for ops.attention.paged_decode_attention with automatic
    gather fallback. q (B,1,Hq,D); k_pages/v_pages (num_pages,page,Hkv,D);
    page_table (B,P) int32 with ``num_pages`` the unallocated sentinel;
    k_new/v_new (B,Hkv,D); cache_len (B,) valid tokens excluding the
    current one; int8 pools pass the (num_pages,page,Hkv) scale planes.
    Returns (B,1,Hq,D)."""
    interpret = resolve_interpret(interpret)
    _, _, q_heads, head_dim = q.shape
    page, kv_heads = k_pages.shape[1], k_pages.shape[2]
    if not ragged_shapes_supported(head_dim, q_heads, kv_heads, page,
                                   interpret):
        from gofr_tpu.ops.attention import paged_decode_attention
        return paged_decode_attention(q, k_pages, v_pages, page_table,
                                      k_new, v_new, cache_len,
                                      k_scale_pages=k_scale_pages,
                                      v_scale_pages=v_scale_pages)
    return _pallas_ragged(q, k_pages, v_pages, page_table,
                          k_new[:, None], v_new[:, None], cache_len,
                          k_scale_pages, v_scale_pages, interpret)


def ragged_paged_verify_attention(q, k_pages, v_pages, page_table, k_new,
                                  v_new, cache_len, k_scale_pages=None,
                                  v_scale_pages=None,
                                  interpret: Optional[bool] = None
                                  ) -> jnp.ndarray:
    """γ+1-token variant backing speculative verify: drop-in for
    ops.attention.paged_verify_attention. q (B,G,Hq,D); k_new/v_new
    (B,G,Hkv,D) — query g sits at position ``cache_len + g``, attends
    the paged cache (< cache_len) plus the new tokens causally
    (u <= g). Falls back to the gather formulation exactly like the
    decode variant. Returns (B,G,Hq,D)."""
    interpret = resolve_interpret(interpret)
    _, _, q_heads, head_dim = q.shape
    page, kv_heads = k_pages.shape[1], k_pages.shape[2]
    if not ragged_shapes_supported(head_dim, q_heads, kv_heads, page,
                                   interpret):
        from gofr_tpu.ops.attention import paged_verify_attention
        return paged_verify_attention(q, k_pages, v_pages, page_table,
                                      k_new, v_new, cache_len,
                                      k_scale_pages=k_scale_pages,
                                      v_scale_pages=v_scale_pages)
    return _pallas_ragged(q, k_pages, v_pages, page_table, k_new, v_new,
                          cache_len, k_scale_pages, v_scale_pages,
                          interpret)
