"""Two-process DCN proof: jax.distributed over a real coordinator.

VERDICT r3 missing #6: ``multihost.initialize_distributed`` had never
executed with >1 real process. This module is both the child program and
the parent-side launcher for a 2-process CPU check that exercises the
REAL multi-host path end-to-end:

- each process boots its own JAX runtime (N virtual CPU devices),
- ``initialize_distributed`` wires them through the coordinator
  (the same env contract a k8s deployment would use),
- ``hybrid_mesh`` lays out a dcn-outermost × ici-innermost mesh over the
  2×N global device view,
- one dp all-reduce (psum over both axes, compiled under jit via
  shard_map) runs across the process boundary and both processes assert
  the globally-reduced value.

Run standalone:  python -m gofr_tpu.parallel.dcn_check
(parent mode: spawns both children, prints their reports).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from typing import Dict, List

_CHILD_ENV_FLAG = "GOFR_DCN_CHECK_CHILD"


def _child() -> None:
    """One process of the 2-process job. Must configure platform/devices
    before any JAX backend use."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from gofr_tpu.parallel import multihost

    started = multihost.initialize_distributed()
    assert started, "initialize_distributed must start with JAX_COORDINATOR"

    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map  # type: ignore[attr-defined]
    except ImportError:                              # older jax
        from jax.experimental.shard_map import shard_map

    mesh = multihost.hybrid_mesh(
        {"dp": jax.local_device_count()},
        {"dp_outer": jax.process_count()})
    n_global = jax.device_count()
    data = np.arange(n_global, dtype=np.float32)
    sharding = NamedSharding(mesh, P(("dp_outer", "dp")))
    x = jax.make_array_from_callback(
        (n_global,), sharding, lambda index: data[index])

    @jax.jit
    def global_sum(values):
        return shard_map(
            lambda v: jax.lax.psum(jnp.sum(v), ("dp_outer", "dp")),
            mesh=mesh, in_specs=P(("dp_outer", "dp")), out_specs=P(),
        )(values)

    reduced = float(global_sum(x))
    expected = float(data.sum())
    report = {
        "process": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": n_global,
        "psum": reduced,
        "expected": expected,
        "ok": abs(reduced - expected) < 1e-6,
    }
    print(json.dumps(report), flush=True)
    assert report["ok"], report


def _free_port() -> int:
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        return probe.getsockname()[1]


def run_two_process_check(local_devices: int = 4,
                          timeout: float = 180.0) -> List[Dict]:
    """Spawn the 2-process job; returns both children's reports (parent
    asserts nothing itself — callers check ``ok``/``psum``)."""
    import re
    import tempfile

    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    children = []
    for process_id in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env[_CHILD_ENV_FLAG] = "1"
        env["JAX_COORDINATOR"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(process_id)
        # preserve inherited XLA_FLAGS (dump/determinism flags), only
        # overriding the forced device count
        flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{local_devices}").strip()
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        # stderr → a temp file: both children must reach the collective
        # for either to exit, so an undrained stderr PIPE filling up
        # would deadlock the pair (and eat the diagnostics)
        errfile = tempfile.TemporaryFile(mode="w+")
        child = subprocess.Popen(
            [sys.executable, "-m", "gofr_tpu.parallel.dcn_check"],
            env=env, stdout=subprocess.PIPE, stderr=errfile, text=True)
        children.append((child, errfile))
    reports = []
    try:
        for child, errfile in children:
            try:
                out, _ = child.communicate(timeout=timeout)
            finally:
                errfile.seek(0)
                err = errfile.read()
            if child.returncode != 0:
                raise RuntimeError(
                    f"dcn check child failed rc={child.returncode}:\n"
                    f"{err[-2000:]}")
            reports.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for child, errfile in children:
            if child.poll() is None:
                child.kill()
            errfile.close()
    return reports


if __name__ == "__main__":
    if os.environ.get(_CHILD_ENV_FLAG):
        _child()
    else:
        for entry in run_two_process_check():
            print(json.dumps(entry))
