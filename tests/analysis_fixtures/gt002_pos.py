"""GT002 positive fixture: fire-and-forget task spawns.

Parsed by graftcheck in tests, never imported.
"""

import asyncio


async def worker():
    return 1


def dropped():
    asyncio.ensure_future(worker())


def passed_along(tasks):
    tasks.append(asyncio.create_task(worker()))


class Engine:
    def start(self):
        # stored but never awaited / given a done-callback in this scope;
        # "stop() awaits it later" still loses every exception in between
        self._task = asyncio.create_task(worker())
