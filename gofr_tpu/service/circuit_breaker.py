"""Circuit breaker for the outbound client.

Capability parity with ``pkg/gofr/service/circuit_breaker.go``
(CircuitBreakerConfig{Threshold,Interval} 24-27; closed/open states 12-15;
executeWithCircuitBreaker 59-90; wraps all verbs 216-271), extended with a
**half-open** state the Go port lacks: instead of a background health
ticker silently reopening the circuit to full traffic, the first request
after the cooldown ``interval`` becomes a *single-flight trial* — it alone
reaches the peer while concurrent requests keep fast-failing. A
successful trial closes the circuit; a failed one reopens it for another
full cooldown. State transitions are counted in
``app_tpu_circuit_state_total{state}`` so a flapping peer is visible as a
transition rate, not just an open/closed gauge.
"""

from __future__ import annotations

import threading
import time

from gofr_tpu.service.client import HTTPService, ServiceError
from gofr_tpu.service.options import Option

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"


class CircuitOpenError(ServiceError):
    """Fast-fail while the circuit is open (or a half-open trial is
    already in flight)."""


class CircuitBreakerConfig(Option):
    def __init__(self, threshold: int = 5, interval: float = 10.0):
        self.threshold = threshold
        self.interval = interval

    def add_option(self, service: HTTPService) -> HTTPService:
        return _CircuitBreakerService(service, self.threshold, self.interval)


class _CircuitBreakerService(HTTPService):
    def __init__(self, inner: HTTPService, threshold: int, interval: float):
        self.__dict__.update(inner.__dict__)
        self._inner = inner
        self._threshold = threshold
        self._interval = interval
        self._failures = 0
        self._state = STATE_CLOSED
        self._opened_at = 0.0
        self._trial_inflight = False
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def is_open(self) -> bool:
        """True while requests would fast-fail *right now*: open and
        still cooling down, or half-open with the trial in flight. An
        open circuit past its cooldown reads as routable again — the
        next request through is the trial."""
        with self._lock:
            if self._state == STATE_OPEN:
                return time.monotonic() - self._opened_at < self._interval
            if self._state == STATE_HALF_OPEN:
                return self._trial_inflight
            return False

    def request(self, method, path, params=None, body=None, headers=None):
        trial = False
        with self._lock:
            if self._state == STATE_OPEN:
                if time.monotonic() - self._opened_at < self._interval:
                    raise CircuitOpenError(
                        f"circuit open for {self.service_name}")
                # cooldown over: this request is the single-flight trial
                self._transition(STATE_HALF_OPEN)
                self._trial_inflight = True
                trial = True
            elif self._state == STATE_HALF_OPEN:
                if self._trial_inflight:
                    raise CircuitOpenError(
                        f"circuit half-open for {self.service_name}: "
                        "trial request already in flight")
                self._trial_inflight = True
                trial = True
        try:
            response = self._inner.request(method, path, params=params,
                                           body=body, headers=headers)
        except ServiceError:
            self._on_failure(trial)
            raise
        if response.status_code >= 500:
            self._on_failure(trial)
        else:
            self._on_success(trial)
        return response

    def _on_failure(self, trial: bool = False) -> None:
        with self._lock:
            if trial or self._state == STATE_HALF_OPEN:
                # the trial failed — back to a full cooldown
                self._trial_inflight = False
                self._opened_at = time.monotonic()
                self._failures = self._threshold
                self._transition(STATE_OPEN)
                return
            self._failures += 1
            if self._failures >= self._threshold \
                    and self._state == STATE_CLOSED:
                self._opened_at = time.monotonic()
                self._transition(STATE_OPEN)

    def _on_success(self, trial: bool) -> None:
        with self._lock:
            self._failures = 0
            if trial or self._state != STATE_CLOSED:
                self._trial_inflight = False
                self._transition(STATE_CLOSED)

    def _transition(self, to: str) -> None:
        """State change under ``self._lock``; logs + transition counter."""
        if to == self._state:
            return
        came_from = self._state
        self._state = to
        if self.logger is not None:
            log = self.logger.warn if to == STATE_OPEN else self.logger.info
            log("circuit %s for %s (was %s, %d failures)",
                to.upper(), self.service_name, came_from, self._failures)
        metrics = getattr(self, "metrics", None)
        if metrics is not None:
            metrics.increment_counter(
                "app_tpu_circuit_state_total", state=to)

    def health_check(self):
        health = self._inner.health_check()
        health.setdefault("details", {})["circuit"] = self.state
        return health

    def close(self) -> None:
        """Kept for API compatibility with the probe-thread breaker; the
        half-open design has no background thread to stop."""
