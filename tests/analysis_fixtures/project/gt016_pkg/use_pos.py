"""GT016 positives: free-list mutation reachable with no lock held."""

from gt016_pkg.pool import SharedPool


class Admitter:
    def __init__(self, pool: SharedPool):
        self.pool = pool

    def admit(self):
        return self.pool.alloc()     # BAD: bare mutator call, no lock

    def evict(self, pid):
        self.pool.release(pid)       # BAD: same, via a second mutator
