"""Llama /generate endpoint — continuous-batching serving with HBM KV cache
(BASELINE.md config 5).

Serving engine: slot-based continuous batching (gofr_tpu.tpu.GenerationEngine)
— concurrent requests share decode steps; prompts prefill into per-slot KV
cache regions without recompiles. Uses the framework BPE tokenizer (C++
encode path when the toolchain is present).

For tensor parallelism over a slice set ``MESH=tp:8`` (or ``MESH=8``;
legacy ``TPU_MESH=dp:1,tp:8`` still works): the engine shards params with
gofr_tpu.parallel.llama_param_specs (Megatron column/row specs) and the KV
cache with llama_cache_specs (slots on dp, kv-heads on tp); XLA inserts the
all-reduces over ICI. The 7B presets default to a sharded mesh over every
addressable device — a 7B model does not fit one chip's HBM, so monolithic
single-device serving was never a real deployment; set ``MESH=off`` to
force the old single-device path.

Disaggregated serving (ISSUE 8): ``CLUSTER_ROLE=prefill|decode|both`` tags
this replica's phase; ``CLUSTER_PEERS=name=role@url[#grpc],...`` registers
remote replicas. The replica then exposes the handoff plane —
``POST /disagg/prefill`` (run prefill, park packed KV in the handoff
table), ``GET /disagg/fetch`` + gRPC stream ``/gofr.Disagg/fetch`` (pull
the KV blob, chunked), ``POST /disagg/adopt`` (admit shipped KV pages,
decode, return tokens) — and ``POST /disagg/generate``, the router
front-end that prefills on one replica and decodes on another
(``KV_WIRE_CODEC=auto|bf16|int8`` pins the wire format).
``POST /disagg/drain`` {"replica": name} drains a replica: routing stops
immediately, in-flight streams finish, its pool pages come back.

Fleet control plane (ISSUE 12): ``FLEET_ROUTING=1`` upgrades the router
to prefix-affinity routing off clusterz digests, live session migration
(``POST /disagg/adopt_session`` is the target half), and
drain-by-migration; ``FLEET_AUTOSCALE=<cron spec>`` registers the
decode-pool autoscaler (``FLEET_MIN_DECODE``/``FLEET_MAX_DECODE``/
``FLEET_QUEUE_HIGH``/``FLEET_QUEUE_LOW``/``FLEET_HBM_HIGH``/
``FLEET_COOLDOWN_S`` tune it) as a single-flight cron job guarded by the
cooldown and the compile ledger.

Multi-model serving (ISSUE 7): ``MODELS=big=small>cheap,cheap=tiny,moe=moe``
registers several named engines behind one ModelRegistry — ``name=preset``
entries, ``>fallback`` names the model DEGRADED traffic shifts to, the first
entry is the default. Co-resident llama models share one KV page pool when
``GENERATE_PAGED_KV=1``. Per-model routes:

POST /v1/{model}/generate and /v1/{model}/generate/stream — same bodies as
below, routed through the registry (503 when the model and its fallback
cannot serve).

POST /generate {"prompt": "...", "max_new_tokens": 32,
                "temperature": 0.8, "top_k": 40, "top_p": 0.95, "seed": 1}
POST /generate/stream — same body, Server-Sent-Events: one ``data:`` frame
per token as it is decoded (time-to-first-token = prefill latency), then a
final ``[DONE]`` frame. gRPC analog: server-streaming
``/gofr.Llama/generate`` (one JSON message per token).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import new_app
from gofr_tpu.tokenizer import Tokenizer


def build_app():
    import jax

    from gofr_tpu.models import llama, moe
    from gofr_tpu.tpu import (ClusterRegistry, DisaggRouter,
                              GenerationEngine, HTTPTransport,
                              InProcTransport, ModelRegistry,
                              ModelUnavailable, NoReplicaAvailable,
                              PagePool, kv_wire, parse_peers)
    from gofr_tpu.tpu.cluster import HandoffExpired, HandoffTable
    from gofr_tpu.tpu.constrain import token_byte_table
    from gofr_tpu.tpu.sched import role_class_weights

    app = new_app()
    kv_int8 = os.environ.get("LLAMA_KV_INT8") == "1"
    paged_kv = os.environ.get("GENERATE_PAGED_KV") == "1"
    kv_page = int(os.environ.get("GENERATE_KV_PAGE", "32"))
    # fused ragged paged attention: auto = Pallas page-table kernel on
    # TPU when the geometry tiles, on = force (interpret off-TPU),
    # off = gather formulation (docs/tpu/model-serving.md)
    ragged_attn = (os.environ.get("GENERATE_RAGGED_ATTN", "auto")
                   .strip().lower() or "auto")
    # disaggregated serving: this replica's phase + the remote fleet
    cluster_role = os.environ.get("CLUSTER_ROLE", "both").strip() or "both"
    cluster_peers = parse_peers(os.environ.get("CLUSTER_PEERS"))
    # SLO-class weighted-fair scheduling, seeded from the replica role
    # (decode replicas weight migrated-KV traffic highest; explicit
    # SLO_CLASS_WEIGHTS entries override the preset per class)
    class_weights = role_class_weights(
        cluster_role, os.environ.get("SLO_CLASS_WEIGHTS"))
    # speculative decode: a cheap draft proposes GENERATE_SPEC_GAMMA
    # tokens per tick, the target verifies them in one batched forward
    draft_preset = os.environ.get("GENERATE_DRAFT_MODEL")
    spec_gamma = int(os.environ.get("GENERATE_SPEC_GAMMA", "4"))
    default_preset = os.environ.get("LLAMA_PRESET", "small")

    mesh = None
    mesh_spec = (os.environ.get("MESH")
                 or app.config.get("TPU_MESH") or "").strip()
    if mesh_spec.lower() == "off":
        mesh = None
    else:
        from gofr_tpu.parallel import make_mesh, parse_mesh_spec
        axes = parse_mesh_spec(mesh_spec)
        if axes is None and default_preset in ("7b", "llama3-8b") \
                and len(jax.devices()) > 1:
            # sharded-by-default for the 7B-class presets: tp over the
            # whole slice (the BASELINE.json v5e-8 serving topology)
            axes = {"dp": 1, "tp": -1}
        if axes is not None:
            mesh = make_mesh(axes)

    def model_config(preset):
        """`moe`/`moe-<preset>` → MoE variant; anything else is a llama
        preset. Byte-level vocab either way."""
        if preset == "moe" or preset.startswith("moe-"):
            base = preset[4:] if preset.startswith("moe-") else "tiny"
            return moe, moe.config(
                base=llama.config(base, vocab_size=256, kv_int8=kv_int8))
        return llama, llama.config(preset, vocab_size=256, kv_int8=kv_int8)

    def make_engine(preset, name, seed, with_draft, page_pool=None):
        module, cfg = model_config(preset)
        params = module.init(cfg, jax.random.PRNGKey(seed))
        draft_cfg = draft_params = None
        if with_draft and module is llama and draft_preset:
            draft_cfg = llama.config(draft_preset, vocab_size=256)
            draft_params = llama.init(draft_cfg, jax.random.PRNGKey(seed + 1))
        return GenerationEngine(
            cfg, params, mesh=mesh if module is llama else None,
            max_slots=int(os.environ.get("GENERATE_SLOTS", "8")),
            max_len=min(cfg.max_seq_len, 1024),
            # fused decode steps per host round trip (amortises dispatch;
            # the adaptive ladder drops back to 1 while admissions wait).
            # r5 measured K=8 ticks costing less device time than their own
            # dispatch on a high-latency host — 16 is the safer default, 32
            # for throughput-first serving (docs/tpu/benchmarking.md)
            steps_per_tick=int(os.environ.get("STEPS_PER_TICK", "16")),
            # decode ticks in flight before the oldest fetch must land:
            # token fetches overlap device compute and each other
            max_inflight_ticks=int(os.environ.get("INFLIGHT_TICKS", "4")),
            # prefix KV reuse: shared prompt prefixes (system prompts,
            # few-shot templates) prefill only their suffix against cached
            # KV pages; greedy outputs stay token-identical with bf16
            # caches (docs/tpu/model-serving.md "Prefix KV reuse")
            prefix_cache=(module is llama
                          and os.environ.get("GENERATE_PREFIX_CACHE") == "1"),
            prefix_cache_bytes=int(os.environ.get(
                "GENERATE_PREFIX_CACHE_BYTES", str(64 << 20))),
            # unified paged KV: one page pool shared by prefill output, the
            # prefix cache and decode (MoE serves dense — no paged step)
            paged_kv=paged_kv and module is llama,
            kv_page=kv_page,
            ragged_attn=(ragged_attn if paged_kv and module is llama
                         else "auto"),
            kv_pool_bytes=(int(os.environ["GENERATE_KV_POOL_BYTES"])
                           if "GENERATE_KV_POOL_BYTES" in os.environ
                           and page_pool is None else None),
            page_pool=page_pool,
            model_module=None if module is llama else module,
            model_name=name,
            draft_cfg=draft_cfg, draft_params=draft_params,
            spec_gamma=spec_gamma,
            class_weights=class_weights,
            # zero-copy data plane: pack each tick's small control-array
            # uploads into ONE transfer (bit-exact bitcast split — token-
            # identical output), and ship token deltas one coalesced
            # queue frame per tick (docs/tpu/model-serving.md "Data
            # plane"); both off by default pending more TPU soak time
            coalesce_uploads=(
                os.environ.get("GENERATE_COALESCE_UPLOADS") == "1"),
            coalesce_stream=(
                os.environ.get("GENERATE_COALESCE_STREAM") == "1"),
            # constrained decoding (response_format): token byte table
            # from THIS tokenizer so grammar masks match what decode()
            # renders; cache compiled grammars across requests
            token_table=token_byte_table(tokenizer,
                                         vocab_size=cfg.vocab_size),
            grammar_cache_entries=int(os.environ.get(
                "GENERATE_CONSTRAIN_CACHE", "32")),
            logger=app.logger, metrics=app.container.metrics,
            # flight recorder: queue.wait/prefill/decode child spans per
            # request, engine-step spans with links, /debug/statusz views
            tracer=app.container.tracer,
            # SLO accounting: X-Request-Deadline-Ms classification (ok/
            # violated/expired), windowed TTFT quantiles, goodput vs raw
            # tokens/s — feeds /debug/varz and the degradation watchdog
            slo=app.container.slo)

    tokenizer = Tokenizer()  # byte-level; swap in a trained vocab via load()
    models_spec = os.environ.get("MODELS", "").strip()
    registry = None
    if models_spec:
        # "name=preset[>fallback]" entries, comma-separated, first=default
        registry = ModelRegistry(
            watchdog=getattr(app.container, "watchdog", None),
            logger=app.logger, metrics=app.container.metrics)
        parsed = []
        for part in models_spec.split(","):
            name, _, rest = part.strip().partition("=")
            preset, _, fallback = rest.partition(">")
            parsed.append((name.strip(), (preset or "small").strip(),
                           fallback.strip() or None))
        shared_pool = None
        if paged_kv:
            # co-resident llama engines share one page pool: page ids are
            # interchangeable, occupancy is chip-global
            _, pool_cfg = model_config(parsed[0][1])
            shared_pool = PagePool(
                pool_cfg, page=kv_page, mesh=mesh,
                budget_bytes=int(os.environ.get(
                    "GENERATE_KV_POOL_BYTES", str(256 << 20))),
                metrics=app.container.metrics)
            registry.page_pool = shared_pool
        for seed, (name, preset, fallback) in enumerate(parsed):
            module, cfg = model_config(preset)
            pool = shared_pool if module is llama else None
            eng = make_engine(preset, name, seed * 2, seed == 0,
                              page_pool=pool)
            registry.register(name, eng, fallback=fallback,
                              default=(seed == 0), role=cluster_role)
        engine = registry.engine()     # default model (admin accessor —
        app.container.tpu = registry   # entries are LOADING until warmup);
        #                                per-model health/statusz/varz/xlaz
    else:
        engine = make_engine(default_preset, "generate", 0, True)
        app.container.tpu = engine  # surfaces engine health at /.well-known
    app.enable_statusz()        # live queue/slot/KV-cache/timeline snapshot
    app.enable_varz()           # windowed SLO/goodput/saturation numbers
    app.enable_xlaz()           # compile ledger + prompt-bucket fit view
    app.enable_hbmz()           # device-memory attribution + watchdog HBM
    app.enable_timez()          # multi-res series + anomalies + tick anatomy
    app.enable_workloadz()      # traffic-shape ring + trace export + roofline
    app.enable_sloz()           # error-budget burn rates + worst offenders
    app.enable_whyz()           # per-trace slow-request root-cause verdicts
    app.enable_tunez()          # operating point + auto-tuner candidate ledger
    app.enable_profiler()       # duration-capped on-demand XLA captures

    @app.on_startup
    async def warm_engine():
        # precompile the decode ladder + prefill/insert executables before
        # the first request: a cold compile is seconds of request latency
        if registry is not None:
            for name in registry.models():
                eng = registry.engine(name)
                await registry.warmup(
                    name, prompt_counts=(1, eng.max_slots))
            await registry.start()
        else:
            await engine.warmup(prompt_counts=(1, engine.max_slots))
            await engine.start()

    @app.on_shutdown
    async def log_suggested_ladder():
        # close the bucket-tuning loop (docs/tpu/model-serving.md): the
        # padding-optimal prompt ladder for the traffic this process saw,
        # ready to paste into the next deploy's prompt_buckets
        fit = engine.xlaz()["models"]["prompt"]
        if fit["suggested_ladder"]:
            app.logger.info(
                "prompt-bucket fit at shutdown: configured=%s observed=%s "
                "suggested=%s", fit["ladder"],
                fit["observed_batch_sizes"], fit["suggested_ladder"])

    from gofr_tpu.http.errors import HTTPError
    from gofr_tpu.tpu.generate import Sampling

    class BadRequest(HTTPError):
        status_code = 400

    class Unavailable(HTTPError):
        status_code = 503

    def resolve_engine(ctx=None):
        """Default engine, or the registry route for /v1/{model}/..."""
        name = ctx.path_param("model") if ctx is not None else None
        if registry is None:
            if name:
                raise BadRequest(
                    "multi-model routing is off (set MODELS to enable)")
            return engine
        try:
            return registry.route(name or None)
        except KeyError as exc:
            raise BadRequest(str(exc)) from exc
        except ModelUnavailable as exc:
            raise Unavailable(str(exc)) from exc

    def parse_request(data):
        try:
            prompt_ids = tokenizer.encode(data["prompt"])[-512:]
            max_new = int(data.get("max_new_tokens", 32))
            seed = data.get("seed")
            # seed omitted → fresh entropy per request (two sampled
            # requests differ); an explicit seed reproduces a completion
            sampling = Sampling(
                temperature=float(data.get("temperature", 0.0)),
                top_k=int(data.get("top_k", 0)),
                top_p=float(data.get("top_p", 1.0)),
                seed=int(seed) if seed is not None else None)
        except KeyError as exc:
            raise BadRequest(f"missing field: {exc}") from exc
        except (TypeError, ValueError) as exc:
            raise BadRequest(f"bad field value: {exc}") from exc
        # constrained decoding: {"type": "regex", "pattern": ...} or
        # {"type": "json_schema", "json_schema": {...}} — grammar compile
        # errors surface as 400s from the engine's ValueError
        response_format = data.get("response_format")
        if response_format is not None and not isinstance(response_format,
                                                          dict):
            raise BadRequest("response_format must be an object")
        return prompt_ids, max_new, sampling, response_format

    async def start_stream(eng, data):
        """Validate + admit eagerly so bad requests fail with a 400 before
        any stream bytes are written."""
        prompt_ids, max_new, sampling, response_format = parse_request(data)
        try:
            return await eng.generate_stream(
                prompt_ids, max_new_tokens=max_new, sampling=sampling,
                response_format=response_format)
        except ValueError as exc:
            raise BadRequest(str(exc)) from exc

    async def generate(ctx):
        eng = resolve_engine(ctx)
        await eng.start()  # idempotent; binds to the serving loop
        prompt_ids, max_new, sampling, response_format = \
            parse_request(ctx.bind())
        try:
            out = await eng.generate(prompt_ids, max_new_tokens=max_new,
                                     sampling=sampling,
                                     response_format=response_format)
        except ValueError as exc:
            raise BadRequest(str(exc)) from exc
        return {"completion": tokenizer.decode(out),
                "tokens": out, "model": eng.model_name,
                "engine": eng.stats()}

    async def generate_stream(ctx):
        from gofr_tpu.http.response import Stream
        eng = resolve_engine(ctx)
        await eng.start()
        stream = await start_stream(eng, ctx.bind())

        async def frames():
            import json
            try:
                async for token in stream:
                    yield json.dumps({"token": token,
                                      "text": tokenizer.decode([token])})
                yield "[DONE]"
            finally:
                # client disconnect acloses frames(); propagate to the
                # engine stream so the slot stops decoding
                await stream.aclose()

        # on_close covers the one path frames()'s finally cannot: the
        # client vanishing before the response writer ever starts the
        # generator (an unstarted generator's aclose skips the body)
        return Stream(frames(), sse=True, on_close=stream.cancel)

    async def generate_grpc_stream(ctx):
        eng = resolve_engine()
        await eng.start()
        stream = await start_stream(eng, ctx.request.payload)

        async def tokens():
            try:
                async for token in stream:
                    yield {"token": token,
                           "text": tokenizer.decode([token])}
            finally:
                await stream.aclose()   # RPC cancelled → free the slot

        return tokens()

    # -- disaggregated serving plane (ISSUE 8) ------------------------------
    # handoff table: packed KV parked between /disagg/prefill and the
    # peer's chunked fetch; cluster registry: local engine under its
    # CLUSTER_ROLE + every CLUSTER_PEERS entry behind a circuit breaker
    import asyncio
    import base64

    from gofr_tpu.http.response import FileResponse

    # KV_WIRE_CODEC=auto|bf16|int8, validated against the pool storage
    # format at startup — a transcoding mismatch is a deploy error
    kv_wire.resolve_codec(os.environ.get("KV_WIRE_CODEC", "auto"),
                          engine.cfg)
    handoffs = HandoffTable(
        capacity=int(os.environ.get("DISAGG_HANDOFF_CAPACITY", "64")),
        ttl_s=float(os.environ.get("DISAGG_HANDOFF_TTL_S", "120")),
        logger=app.logger, metrics=app.container.metrics)
    cluster = ClusterRegistry(logger=app.logger,
                              metrics=app.container.metrics)
    cluster.register("local", cluster_role, InProcTransport(engine))
    for peer_name, peer_role, peer_url, peer_grpc in cluster_peers:
        cluster.register(
            peer_name, peer_role,
            HTTPTransport(peer_url, grpc_target=peer_grpc,
                          logger=app.logger,
                          metrics=app.container.metrics,
                          tracer=app.container.tracer))
    app.container.cluster = cluster  # role-aware readiness in health()
    # FLEET_ROUTING=1 upgrades the router to the fleet control plane
    # (ISSUE 12): prefix-affinity routing off clusterz digests, live
    # session migration, drain-by-migration
    fleet_routing = os.environ.get("FLEET_ROUTING", "").strip() in (
        "1", "true", "on", "yes")
    if fleet_routing:
        from gofr_tpu.tpu.fleet import Autoscaler, FleetRouter
        router = FleetRouter(
            cluster, logger=app.logger,
            metrics=app.container.metrics,
            tracer=app.container.tracer,
            digest_entries=int(
                os.environ.get("FLEET_DIGEST_ENTRIES", "512")))
    else:
        router = DisaggRouter(cluster, logger=app.logger,
                              metrics=app.container.metrics,
                              tracer=app.container.tracer)
    app.container.cluster_router = router  # clusterz/tracez discovery
    app.enable_clusterz()       # fleet rollup over the replica registry
    app.enable_tracez()         # stitched per-trace_id disagg timelines

    if fleet_routing:
        # keep the affinity index warm: one digest sweep a minute. The
        # handler bails out when the previous sweep is still probing
        # (the cron plane overlaps firings by design — graftcheck GT009)
        refresh_state = {"busy": False}

        async def fleet_refresh(ctx=None):
            if refresh_state["busy"]:
                return
            refresh_state["busy"] = True
            try:
                await router.refresh()
            finally:
                refresh_state["busy"] = False

        app.add_cron_job("* * * * *", "fleet-refresh", fleet_refresh)

        # FLEET_AUTOSCALE=<cron spec> registers the decode-pool
        # autoscaler. The example owns no orchestrator, so scale-up is
        # the operator hook (a log line to replace) and scale-down
        # drains the victim by migration — sessions move to a peer, the
        # replica empties in milliseconds
        autoscale_spec = os.environ.get("FLEET_AUTOSCALE", "").strip()
        if autoscale_spec:
            def request_capacity():
                app.logger.info(
                    "fleet autoscaler: scale-up requested — wire your "
                    "orchestrator (spawn a replica, resize the "
                    "deployment) here")

            autoscaler = Autoscaler(
                cluster,
                scale_up=request_capacity,
                scale_down=lambda name: router.drain(name),
                router=router,
                metrics=app.container.metrics, logger=app.logger,
                container=app.container,
                compile_ledger=getattr(app.container.tpu, "ledger",
                                       None),
                min_decode=int(os.environ.get("FLEET_MIN_DECODE", "1")),
                max_decode=int(os.environ.get("FLEET_MAX_DECODE", "4")),
                queue_high=int(os.environ.get("FLEET_QUEUE_HIGH", "8")),
                queue_low=int(os.environ.get("FLEET_QUEUE_LOW", "1")),
                hbm_high=float(os.environ.get("FLEET_HBM_HIGH", "0.85")),
                cooldown_s=float(
                    os.environ.get("FLEET_COOLDOWN_S", "60")))
            router.autoscaler = autoscaler  # clusterz fleet rollup
            app.add_cron_job(autoscale_spec, "fleet-autoscale",
                             autoscaler)

    def parse_sampling(get):
        """Sampling from flat key→value accessors (query params or JSON);
        absent keys fall back to greedy."""
        seed = get("seed")
        return Sampling(
            temperature=float(get("temperature") or 0.0),
            top_k=int(get("top_k") or 0),
            top_p=float(get("top_p") or 1.0),
            seed=int(seed) if seed not in (None, "") else None)

    async def disagg_prefill(ctx):
        # prefill locally, pack off the event loop, park for pickup
        await engine.start()
        data = ctx.bind()
        try:
            prompt_ids = [int(t) for t in data["prompt"]]
            sampling = parse_sampling((data.get("sampling") or {}).get)
            payload = await engine.prefill_export(
                prompt_ids, sampling=sampling,
                traceparent=ctx.header("traceparent") or None)
        except KeyError as exc:
            raise BadRequest(f"missing field: {exc}") from exc
        except (TypeError, ValueError) as exc:
            raise BadRequest(str(exc)) from exc
        loop = asyncio.get_running_loop()
        blob = await loop.run_in_executor(None, kv_wire.pack, payload)
        return {"handoff": handoffs.put(blob), "bytes": len(blob),
                "payload": payload.describe()}

    class HandoffGone(HTTPError):
        status_code = 410

    async def disagg_fetch(ctx):
        try:
            blob = handoffs.get(ctx.param("handoff"))
        except HandoffExpired as exc:
            # the id WAS real — the TTL lapsed before pickup. 410, not a
            # generic 400: the adopting side should re-prefill, not debug
            raise HandoffGone(str(exc)) from exc
        except KeyError as exc:
            raise BadRequest(str(exc)) from exc
        return FileResponse(content=blob)

    async def disagg_fetch_grpc(ctx):
        blob = handoffs.get(ctx.request.payload["handoff"])

        async def chunks():
            for chunk in kv_wire.iter_chunks(blob):
                yield {"chunk": base64.b64encode(chunk).decode("ascii")}

        return chunks()

    async def disagg_adopt(ctx):
        # admit shipped KV pages (zero local prefill), decode to the
        # budget, return the whole completion — the buffered half of the
        # handoff; cross-process token streaming stays on gRPC generate
        await engine.start()
        blob = ctx.request.body
        try:
            max_new = int(ctx.param("max_new_tokens") or 32)
            eos_raw = ctx.param("eos_id")
            sampling = parse_sampling(
                lambda key: ctx.param(key) or None)
            loop = asyncio.get_running_loop()
            payload = await loop.run_in_executor(None, kv_wire.unpack, blob)
            stream = await engine.adopt_kv(
                payload, max_new, eos_id=int(eos_raw) if eos_raw else None,
                sampling=sampling,
                traceparent=ctx.header("traceparent") or None,
                transfer_bytes=len(blob))
        except kv_wire.KVWireError as exc:
            raise BadRequest(str(exc)) from exc
        except (TypeError, ValueError) as exc:
            raise BadRequest(str(exc)) from exc
        tokens = [token async for token in stream]
        return {"tokens": tokens, "model": engine.model_name}

    async def disagg_adopt_session(ctx):
        # the target half of live migration (ISSUE 12): admit a peer's
        # exported session snapshot mid-stream — zero re-prefill, the
        # remaining budget and sampling state ride the query params, the
        # buffered remainder of the completion is the response
        await engine.start()
        blob = ctx.request.body
        try:
            remaining = int(ctx.param("remaining") or 0)
            eos_raw = ctx.param("eos_id")
            sampling = parse_sampling(
                lambda key: ctx.param(key) or None)
            loop = asyncio.get_running_loop()
            payload = await loop.run_in_executor(None, kv_wire.unpack, blob)
            stream = await engine.adopt_session(
                payload, remaining,
                eos_id=int(eos_raw) if eos_raw else None,
                sampling=sampling,
                traceparent=ctx.header("traceparent") or None,
                transfer_bytes=len(blob))
        except kv_wire.KVWireError as exc:
            raise BadRequest(str(exc)) from exc
        except (TypeError, ValueError) as exc:
            raise BadRequest(str(exc)) from exc
        tokens = [token async for token in stream]
        return {"tokens": tokens, "model": engine.model_name}

    async def disagg_generate(ctx):
        # router front-end: prefill replica → KV handoff → decode replica
        await engine.start()
        # the disagg relay decodes on a remote replica; constrained
        # decoding stays a local-lane feature for now
        prompt_ids, max_new, sampling, _ = parse_request(ctx.bind())
        try:
            out = await router.generate(prompt_ids, max_new,
                                        sampling=sampling)
        except NoReplicaAvailable as exc:
            raise Unavailable(str(exc)) from exc
        except kv_wire.KVWireError as exc:
            raise BadRequest(str(exc)) from exc
        except ValueError as exc:
            raise BadRequest(str(exc)) from exc
        return {"completion": tokenizer.decode(out), "tokens": out,
                "router": router.stats()}

    async def disagg_drain(ctx):
        name = (ctx.bind() or {}).get("replica", "local")
        try:
            # the fleet router drains by migrating live sessions to a
            # peer first (milliseconds); the base registry drain waits
            # out the in-flight streams
            fleet_drain = getattr(router, "drain", None)
            if fleet_drain is not None:
                drained = await fleet_drain(name)
            else:
                drained = await cluster.drain(name)
        except KeyError as exc:
            raise BadRequest(str(exc)) from exc
        return {"replica": name, "drained": drained,
                "cluster": cluster.stats()}

    # async inference lane (ISSUE 11): BATCH_LANE_TOPIC + a PUBSUB_BACKEND
    # turn this replica into a batch-job consumer. Pre-wired here (rather
    # than letting App.start build it from config) so jobs can carry text
    # "prompt" fields and results carry decoded "text" — the lane gets
    # this app's tokenizer as its encode/decode hooks.
    if app.config.get("BATCH_LANE_TOPIC") \
            and app.container.pubsub is not None:
        from gofr_tpu.tpu.batch_lane import new_batch_lane
        app.container.batch_lane = new_batch_lane(
            app.config, app.container.tpu, app.container,
            encode=lambda text: tokenizer.encode(text)[-512:],
            decode=tokenizer.decode)

    app.post("/generate", generate)
    app.post("/generate/stream", generate_stream)
    app.post("/v1/{model}/generate", generate)
    app.post("/v1/{model}/generate/stream", generate_stream)
    app.register_grpc_stream("Llama", "generate", generate_grpc_stream)
    app.post("/disagg/prefill", disagg_prefill)
    app.get("/disagg/fetch", disagg_fetch)
    app.post("/disagg/adopt", disagg_adopt)
    app.post("/disagg/adopt_session", disagg_adopt_session)
    app.post("/disagg/generate", disagg_generate)
    app.post("/disagg/drain", disagg_drain)
    app.register_grpc_stream("Disagg", "fetch", disagg_fetch_grpc)
    return app


if __name__ == "__main__":
    build_app().run()
