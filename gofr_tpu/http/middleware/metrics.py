"""Metrics middleware: per-request latency histogram.

Capability parity with ``pkg/gofr/http/middleware/metrics.go:21-42``
(``app_http_response`` histogram labeled path/method/status).
"""

from __future__ import annotations

import time

from gofr_tpu.http.router import Middleware, WireHandler
from gofr_tpu.metrics import Manager


def metrics_middleware(manager: Manager) -> Middleware:
    def middleware(next_handler: WireHandler) -> WireHandler:
        async def handle(request):
            start = time.perf_counter()
            status, headers, body = await next_handler(request)
            from gofr_tpu.http.response import StreamBody
            if isinstance(body, StreamBody):
                # a stream's latency is its full production time, and a
                # producer failure mid-stream is a 500, not the header
                # status — observe at completion instead of header time
                def observe(ok: bool, messages: int,
                            status=status) -> None:
                    manager.record_histogram(
                        "app_http_response", time.perf_counter() - start,
                        path=request.path, method=request.method,
                        status=str(status if ok else 500))

                body.on_complete(observe)
            else:
                manager.record_histogram(
                    "app_http_response", time.perf_counter() - start,
                    path=request.path, method=request.method,
                    status=str(status),
                )
            return status, headers, body
        return handle
    return middleware
