from gofr_tpu.metrics import Manager, render_prometheus


def test_counter_and_labels():
    manager = Manager()
    manager.new_counter("hits", "total hits")
    manager.increment_counter("hits", path="/a")
    manager.increment_counter("hits", path="/a")
    manager.increment_counter("hits", path="/b")
    assert manager.value("hits", path="/a") == 2
    assert manager.value("hits", path="/b") == 1


def test_label_name_collision_with_positional():
    manager = Manager()
    manager.new_gauge("app_info")
    manager.set_gauge("app_info", 1.0, name="svc", version="1.2")
    assert manager.value("app_info", name="svc", version="1.2") == 1.0


def test_histogram_buckets():
    manager = Manager()
    manager.new_histogram("lat", "latency", buckets=(0.01, 0.1, 1.0))
    for value in (0.005, 0.05, 0.5, 5.0):
        manager.record_histogram("lat", value)
    text = render_prometheus(manager)
    assert 'lat_bucket{le="0.01"} 1' in text
    assert 'lat_bucket{le="0.1"} 2' in text
    assert 'lat_bucket{le="1"} 3' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text


def test_wrong_kind_is_noop():
    manager = Manager()
    manager.new_counter("c")
    manager.set_gauge("c", 5.0)  # wrong kind: logged, not raised
    assert manager.value("c") is None


def test_updown_and_exposition_format():
    manager = Manager()
    manager.new_updown_counter("inflight")
    manager.delta_updown_counter("inflight", 3)
    manager.delta_updown_counter("inflight", -1)
    text = render_prometheus(manager)
    assert "# TYPE inflight gauge" in text
    assert "inflight 2" in text
