"""ISSUE 2 satellite coverage: the metrics middleware's inflight gauge and
escaped-exception accounting, and per-job cron duration/outcome metrics."""

import asyncio

from gofr_tpu.container import new_mock_container
from gofr_tpu.cron import CronJob, Crontab
from gofr_tpu.http.middleware.metrics import metrics_middleware
from gofr_tpu.http.response import Stream

from tests.util import http_request, make_app, parse_sse, run, serving


class _FakeRequest:
    path = "/boom"
    route = "/boom"   # what dispatch stamps after the route matched
    method = "GET"


def test_middleware_observes_escaped_exception_as_500():
    """An exception escaping the handler layer entirely (normally it is
    converted to a 500 response before reaching middleware) must still hit
    the latency histogram and release the inflight gauge."""
    container = new_mock_container()
    manager = container.metrics

    async def exploding(request):
        raise RuntimeError("kaboom")

    handle = metrics_middleware(manager)(exploding)

    async def main():
        try:
            await handle(_FakeRequest())
        except RuntimeError:
            return True
        return False

    assert asyncio.run(main())
    assert manager.value("app_http_response", path="/boom", method="GET",
                         status="500") == 1
    assert manager.value("app_http_inflight") == 0.0


def test_inflight_gauge_rises_and_settles():
    """app_http_inflight counts requests between arrival and response —
    observed mid-request from inside the handler, and back at zero after
    every outcome class including streams."""
    async def main():
        app = make_app()
        metrics = app.container.metrics
        seen = {}

        async def slow(ctx):
            seen["inflight"] = metrics.value("app_http_inflight")
            return {"ok": True}

        async def panic(ctx):
            raise RuntimeError("kaboom")

        async def stream(ctx):
            async def frames():
                for i in range(2):
                    yield str(i)
            return Stream(frames(), sse=True)

        app.get("/slow", slow)
        app.get("/panic", panic)
        app.get("/stream", stream)
        async with serving(app) as port:
            assert (await http_request(port, "GET", "/slow")).status == 200
            assert seen["inflight"] == 1.0
            assert (await http_request(port, "GET", "/panic")).status == 500
            result = await http_request(port, "GET", "/stream")
            assert parse_sse(result.body) == ["0", "1"]
            await asyncio.sleep(0.05)   # stream observer fires on close
        assert metrics.value("app_http_inflight") == 0.0
    run(main())


def test_cron_job_metrics_success_and_failure():
    container = new_mock_container()
    crontab = Crontab(container)

    async def good(ctx):
        return None

    def bad(ctx):
        raise RuntimeError("nightly job fell over")

    async def main():
        await crontab._run_job(CronJob("* * * * *", "good", good))
        await crontab._run_job(CronJob("* * * * *", "good", good))
        await crontab._run_job(CronJob("* * * * *", "bad", bad))

    asyncio.run(main())
    metrics = container.metrics
    assert metrics.value("app_cron_runs_total", job="good",
                         result="success") == 2
    assert metrics.value("app_cron_runs_total", job="good",
                         result="failure") is None
    assert metrics.value("app_cron_runs_total", job="bad",
                         result="failure") == 1
    # the duration histogram observes every firing, success or not
    assert metrics.value("app_cron_duration", job="good") == 2
    assert metrics.value("app_cron_duration", job="bad") == 1
