"""SQL datasource: dialect-aware DB wrapper with query logging + metrics.

Capability parity with ``pkg/gofr/datasource/sql`` (sql.go:37-92 env-driven
connect; db.go:20-113 ``DB`` wrapper logging every query + histogram;
db.go:116-175 ``Tx``; db.go:206-301 reflection Select binder / rowsToStruct
with tags; query_builder.go dialect builders; health.go; dialects
sql.go:167-187). Dialects: sqlite (stdlib, always available), mysql /
postgres via optional drivers (gated import — zero-egress image ships
none; the seam is identical so they drop in).
"""

from __future__ import annotations

import dataclasses
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Type

SUPPORTED_DIALECTS = ("sqlite", "mysql", "postgres")


class SQLError(Exception):
    pass


def _placeholder(dialect: str) -> str:
    return "?" if dialect == "sqlite" else "%s"


class _Cursor:
    """Row access shared by DB and Tx."""

    def __init__(self, db: "DB", conn):
        self._db = db
        self._conn = conn

    def _observe(self, query: str, start: float) -> None:
        elapsed = time.perf_counter() - start
        self._db.metrics.record_histogram("app_sql_stats", elapsed,
                                          type=query.split(None, 1)[0].lower())
        self._db.logger.debug("SQL %s in %.2fms", query, elapsed * 1e3)

    def execute(self, query: str, *args) -> int:
        """INSERT/UPDATE/DELETE/DDL → affected row count."""
        start = time.perf_counter()
        try:
            cursor = self._conn.execute(query, args)
            self._observe(query, start)
            return cursor.rowcount
        except Exception as exc:
            self._db.logger.error("SQL exec failed: %s (%r)", query, exc)
            self._db._on_query_error()
            raise SQLError(str(exc)) from exc

    def select(self, query: str, *args) -> List[Dict[str, Any]]:
        """SELECT → list of dict rows."""
        start = time.perf_counter()
        try:
            cursor = self._conn.execute(query, args)
            columns = [c[0] for c in cursor.description or []]
            rows = [dict(zip(columns, row)) for row in cursor.fetchall()]
            self._observe(query, start)
            return rows
        except Exception as exc:
            self._db.logger.error("SQL select failed: %s (%r)", query, exc)
            self._db._on_query_error()
            raise SQLError(str(exc)) from exc

    def query_row(self, query: str, *args) -> Optional[Dict[str, Any]]:
        rows = self.select(query, *args)
        return rows[0] if rows else None

    def bind(self, entity_class: Type, query: str, *args) -> List[Any]:
        """Reflection binder: SELECT rows → entity instances, matching
        column names to dataclass fields (db.go:260-301 ``rowsToStruct``)."""
        rows = self.select(query, *args)
        if dataclasses.is_dataclass(entity_class):
            names = {f.name for f in dataclasses.fields(entity_class)}
            return [entity_class(**{k: v for k, v in row.items()
                                    if k in names}) for row in rows]
        out = []
        for row in rows:
            obj = entity_class()
            for key, value in row.items():
                setattr(obj, key, value)
            out.append(obj)
        return out


class Tx(_Cursor):
    """Transaction handle (db.go:116-175)."""

    def commit(self) -> None:
        self._conn.commit()
        self._db._release(self)

    def rollback(self) -> None:
        self._conn.rollback()
        self._db._release(self)

    def __enter__(self) -> "Tx":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.commit()
        else:
            self.rollback()


class DB(_Cursor):
    """Connection owner. sqlite runs one serialized connection guarded by a
    lock (handlers run in worker threads); autocommit for plain exec,
    explicit ``begin()`` for transactions.

    A maintenance thread mirrors the reference's two background goroutines
    (sql.go:108-132 ``retryConnection``, sql.go:189-202 ``pushDBMetrics``):
    every ``DB_RETRY_FREQUENCY`` seconds (default 10) it pushes connection
    gauges and pings; a dead backend is reconnected in place — callers
    keep using the same DB object and recover without an app restart. A
    failing query wakes the loop immediately instead of waiting out the
    interval."""

    def __init__(self, config, logger, metrics):
        self.logger = logger
        self.metrics = metrics
        self.dialect = (config.get_or_default("DB_DIALECT", "sqlite")
                        .lower())
        if self.dialect not in SUPPORTED_DIALECTS:
            raise SQLError(f"unsupported DB_DIALECT {self.dialect!r} "
                           f"(supported: {SUPPORTED_DIALECTS})")
        self.database = config.get_or_default("DB_NAME", ":memory:")
        self.placeholder = _placeholder(self.dialect)
        self._config = config
        self._lock = threading.RLock()
        super().__init__(self, self._connect())
        logger.info("SQL connected: dialect=%s db=%s", self.dialect,
                    self.database)
        self.retry_frequency = config.get_float("DB_RETRY_FREQUENCY", 10.0)
        self._inuse = 0
        self._closed = False
        self._wake = threading.Event()
        self._maintenance = threading.Thread(
            target=self._maintenance_loop, daemon=True,
            name="sql-maintenance")
        self._maintenance.start()

    def _connect(self):
        if self.dialect == "sqlite":
            return sqlite3.connect(self.database, check_same_thread=False,
                                   isolation_level=None)  # autocommit
        return self._connect_server(self._config)

    def _on_query_error(self) -> None:
        """Wake the maintenance loop now — a failing statement (direct or
        inside a transaction) should start recovery immediately, not at
        the next interval."""
        self._wake.set()

    # -- maintenance (reconnect + stats push) -------------------------------
    def _maintenance_loop(self) -> None:
        while True:
            self._wake.wait(timeout=self.retry_frequency)
            self._wake.clear()
            if self._closed:
                return
            up = self._ping()
            self.metrics.set_gauge("app_sql_open_connections",
                                   1.0 if up else 0.0)
            self.metrics.set_gauge("app_sql_inuse_connections",
                                   float(self._inuse))
            if not up and not self._closed:
                if self.dialect == "sqlite" and self.database == ":memory:":
                    # an in-memory database IS the connection — swapping
                    # in a fresh one would silently replace every table
                    # with nothing; surface the failure instead
                    self.logger.error(
                        "SQL :memory: connection unhealthy; not replacing "
                        "(reconnect would silently lose all data)")
                    continue
                self.logger.info("retrying SQL database connection")
                self._reconnect()

    def _ping(self) -> bool:
        try:
            with self._lock:
                self._conn.execute("SELECT 1")
            return True
        except Exception:
            return False

    def _reconnect(self) -> None:
        try:
            fresh = self._connect()
            with self._lock:
                if self._closed:      # close() raced us: don't leak fresh
                    fresh.close()
                    return
                old, self._conn = self._conn, fresh
            try:
                old.close()
            except Exception:
                pass
            self.metrics.set_gauge("app_sql_open_connections", 1.0)
            self.logger.info("SQL reconnected: dialect=%s db=%s",
                             self.dialect, self.database)
        except Exception as exc:
            self.logger.error("SQL reconnect failed: %r", exc)

    def _connect_server(self, config):
        host = config.get_or_default("DB_HOST", "localhost")
        if self.dialect == "mysql":
            try:
                import pymysql  # optional driver
            except ImportError as exc:
                raise SQLError(
                    "mysql dialect needs the pymysql driver installed") \
                    from exc
            return pymysql.connect(
                host=host, user=config.get("DB_USER"),
                password=config.get("DB_PASSWORD") or "",
                database=self.database,
                port=config.get_int("DB_PORT", 3306), autocommit=True)
        try:
            import psycopg2  # optional driver
        except ImportError as exc:
            raise SQLError(
                "postgres dialect needs the psycopg2 driver installed") \
                from exc
        conn = psycopg2.connect(
            host=host, user=config.get("DB_USER"),
            password=config.get("DB_PASSWORD") or "",
            dbname=self.database, port=config.get_int("DB_PORT", 5432))
        conn.autocommit = True
        return conn

    # serialize sqlite access across worker threads; a failure wakes the
    # maintenance loop so reconnection starts now, not next interval
    def execute(self, query: str, *args) -> int:
        with self._lock:
            self._inuse += 1
            try:
                return super().execute(query, *args)
            except SQLError:
                self._wake.set()
                raise
            finally:
                self._inuse -= 1

    def select(self, query: str, *args) -> List[Dict[str, Any]]:
        with self._lock:
            self._inuse += 1
            try:
                return super().select(query, *args)
            except SQLError:
                self._wake.set()
                raise
            finally:
                self._inuse -= 1

    def begin(self) -> Tx:
        self._lock.acquire()
        self._inuse += 1
        try:
            self._conn.execute("BEGIN")
        except Exception:
            self._inuse -= 1
            self._lock.release()
            self._wake.set()
            raise
        return Tx(self, self._conn)

    def _release(self, tx: Tx) -> None:
        self._inuse -= 1
        self._lock.release()

    def health_check(self) -> Dict[str, Any]:
        try:
            with self._lock:
                self._conn.execute("SELECT 1").fetchone()
            return {"status": "UP",
                    "details": {"dialect": self.dialect,
                                "database": self.database}}
        except Exception as exc:
            return {"status": "DOWN", "details": {"error": repr(exc)}}

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        if getattr(self, "_maintenance", None) is not None:
            self._maintenance.join(timeout=2.0)
        # under the lock: a maintenance ping past the _closed check must
        # not race the close, and _reconnect's _closed re-check (also
        # under the lock) guarantees no fresh connection leaks after this
        with self._lock:
            try:
                self._conn.close()
            except Exception:
                pass


def new_sql(config, logger, metrics) -> DB:
    return DB(config, logger, metrics)
