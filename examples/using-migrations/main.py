"""Migrations example — parity with reference examples/using-migrations:
versioned, transactional schema bootstrap + CRUD scaffolding on top."""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# self-contained demo: sqlite in memory + in-process redis
os.environ.setdefault("DB_DIALECT", "sqlite")
os.environ.setdefault("DB_NAME", ":memory:")
os.environ.setdefault("REDIS_HOST", "memory")

from gofr_tpu import new_app
from gofr_tpu.migration import Migration


def create_employee_table(ds):
    ds.sql.execute(
        "CREATE TABLE employee (id INTEGER PRIMARY KEY, name TEXT, "
        "department TEXT)")


def seed_employees(ds):
    ds.sql.execute("INSERT INTO employee VALUES (?, ?, ?)", 1, "ada",
                   "compute")
    ds.redis.set("employee:seeded", "true")


@dataclasses.dataclass
class Employee:
    id: int = 0
    name: str = ""
    department: str = ""


app = new_app()
app.migrate({
    1: Migration(up=create_employee_table),
    2: Migration(up=seed_employees),
})
app.add_rest_handlers(Employee)

if __name__ == "__main__":
    app.run()
