"""Datasource layer: per-store clients behind narrow interface seams.

Capability parity with ``pkg/gofr/datasource`` (shared Health contract
health.go:8-11; File contracts file.go:10-63; provider interfaces for
Mongo/Cassandra/Clickhouse). Every datasource exposes ``health_check()``
returning ``{"status": "UP"|"DOWN", "details": {...}}`` so the container can
aggregate deep health.
"""

UP = "UP"
DOWN = "DOWN"


def health(status: str, **details) -> dict:
    return {"status": status, "details": details}
