"""Pub/sub datasource layer.

Capability parity with ``pkg/gofr/datasource/pubsub`` (interface.go:11-30
Publisher/Subscriber/Client/Committer contracts; message.go:13-107 Message
implementing the transport-agnostic Request contract) with backends:

- ``INMEM``  — in-process broker (test double + single-process apps); the
  analog of testing pub/sub without a broker (SURVEY.md §4).
- ``MQTT``   — pure-Python MQTT 3.1.1 wire client (reference: pubsub/mqtt).
- ``KAFKA``  — pure-Python Kafka wire-protocol client (reference: pubsub/kafka).
- ``GOOGLE`` — gated: requires google-cloud-pubsub, absent in this image.
"""

from __future__ import annotations

from gofr_tpu.datasource.pubsub.base import Message, PubSub

__all__ = ["Message", "PubSub", "new_pubsub"]


def new_pubsub(backend: str, config, logger, metrics, tracer=None) -> PubSub:
    """Backend switch from config (reference: container/container.go:92-143)."""
    backend = backend.upper()
    if backend in ("INMEM", "MEMORY"):
        from gofr_tpu.datasource.pubsub.inmem import InMemoryBroker
        return InMemoryBroker(logger, metrics, tracer=tracer)
    if backend == "MQTT":
        from gofr_tpu.datasource.pubsub.mqtt import MQTTClient
        return MQTTClient(config, logger, metrics, tracer=tracer)
    if backend == "KAFKA":
        from gofr_tpu.datasource.pubsub.kafka import KafkaClient
        return KafkaClient(config, logger, metrics, tracer=tracer)
    if backend == "GOOGLE":
        from gofr_tpu.datasource.pubsub.google import GoogleClient
        return GoogleClient(config, logger, metrics, tracer=tracer)
    raise ValueError(f"unknown PUBSUB_BACKEND {backend!r}")
