"""GT012: workload capture must stay shape-only — no request content.

The workload plane's whole contract (ISSUE 17) is that a traffic trace
is safe to export, check into a bench artifact, and ship between
machines BECAUSE it contains only the workload's *shape*: token counts,
timings, class labels. One convenient ``event["prompt"] = prompt`` and
the trace silently becomes a user-data store — a privacy and retention
problem no amount of histogramming fixes after the fact. This rule is
the static guard on that invariant.

Scope: modules whose filename stem contains ``workload`` or that live
under a ``workload/`` directory (the recorder, the workloadz endpoint,
anything the plane grows later). ``scope_all=True`` widens to every
module (fixture tests).

What it flags — a *content-named* identifier (``prompt``, ``tokens``,
``token_ids``, ``text``, ``body``, ``payload``, ``completion``, …)
reaching a store:

1. anywhere in scope, into persistent state: ``self.X = value``,
   ``self.X[...] = value``, or a grow call (``self.X.append(...)``,
   ``.extend``/``.insert``/``.add``/``.setdefault``/``.appendleft``) —
   plus module-level names, which live as long as the process;
2. inside an export-shaped function (name matching ``export`` /
   ``snapshot`` / ``serialize`` / ``to_dict`` / ``to_json`` / ``dump``),
   into ANY target — including locals and ``return`` values, because an
   export function's locals *are* the serialized artifact.

Also flagged: a content-named **string key** in a dict literal or
subscript store at those sites (``{"prompt": p}``, ``row["text"] = v``)
— renaming the local does not launder the content.

What clears it: wrapping the content in a sanctioned shape-extractor —
``len()`` / ``min()`` / ``max()`` / ``sum()`` / ``bool()`` / ``int()`` /
``float()`` / ``hash()``. ``len(prompt)`` is a length; ``prompt`` is the
user's data. The scan does not descend into sanctioned calls, so
``event.prompt_len = len(prompt)`` is clean by construction.

Suppress a deliberate exception with ``# graftcheck: ignore[GT012]`` on
the offending line.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

_SCOPE_DIRS = {"workload"}
_SCOPE_STEM = "workload"
_EXPORT_NAME = re.compile(
    r"(export|snapshot|serialize|to_dict|to_json|dump)")
_CONTENT_NAMES = {
    "prompt", "prompts", "prompt_ids", "prompt_tokens", "prompt_text",
    "tokens", "token_ids", "output_ids", "input_ids",
    "text", "texts", "body", "request_body", "content", "contents",
    "message", "messages", "raw", "payload", "completion", "completions",
}
# shape extractors: the value that leaves these is a number, not content
_SANCTIONED = {"len", "min", "max", "sum", "bool", "int", "float", "hash"}
_GROW_CALLS = {"append", "appendleft", "extend", "insert", "add",
               "setdefault"}


def _in_scope(relpath: str) -> bool:
    parts = relpath.split("/")
    if _SCOPE_DIRS & set(parts[:-1]):
        return True
    return _SCOPE_STEM in parts[-1].rsplit(".", 1)[0]


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _content_refs(value: ast.AST) -> List[Tuple[str, int]]:
    """Content-named terminal identifiers reachable in ``value`` without
    passing through a sanctioned shape-extractor call. Matches bare
    names, attribute tails, content-named string subscript keys, and
    content-named dict-literal keys."""
    refs: List[Tuple[str, int]] = []
    stack: List[ast.AST] = [value]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            if _call_name(node) in _SANCTIONED:
                continue          # len(prompt) et al: shape, not content
            stack.extend(ast.iter_child_nodes(node))
            continue
        if isinstance(node, ast.Name):
            if node.id in _CONTENT_NAMES:
                refs.append((node.id, node.lineno))
            continue
        if isinstance(node, ast.Attribute):
            if node.attr in _CONTENT_NAMES:
                refs.append((node.attr, node.lineno))
            else:
                stack.append(node.value)
            continue
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and \
                        isinstance(key.value, str) and \
                        key.value in _CONTENT_NAMES:
                    refs.append((key.value, key.lineno))
            stack.extend(v for v in node.values if v is not None)
            continue
        if isinstance(node, ast.Subscript):
            if isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str) and \
                    node.slice.value in _CONTENT_NAMES:
                refs.append((node.slice.value, node.lineno))
            stack.append(node.value)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return refs


def _key_tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _owner_function(module: ModuleInfo,
                    node: ast.AST) -> Optional[ast.AST]:
    cursor = module.parents.get(node)
    while cursor is not None:
        if isinstance(cursor, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cursor
        cursor = module.parents.get(cursor)
    return None


class WorkloadContentLeakRule(Rule):
    rule_id = "GT012"
    title = "workload-content-leak"
    severity = "error"

    def __init__(self, scope_all: bool = False):
        self.scope_all = bool(scope_all)

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        if not self.scope_all and not _in_scope(module.relpath):
            return []
        seen: Set[Tuple[str, int]] = set()
        findings: List[Finding] = []

        def flag(value: ast.AST, where: str) -> None:
            for name, line in _content_refs(value):
                if (name, line) in seen:
                    continue
                seen.add((name, line))
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=line,
                    message=(
                        f"'{name}' reaches {where} — the workload plane "
                        f"is shape-only: store len()/counts/labels, "
                        f"never token ids, prompt strings, or request "
                        f"bodies (a trace must stay safe to export)"),
                    severity=self.severity,
                    key=f"workload content leak '{name}'",
                ))

        for node in ast.walk(module.tree):
            fn = _owner_function(module, node)
            exporting = fn is not None and bool(
                _EXPORT_NAME.search(fn.name))

            # persistent stores: self.X / module-level targets,
            # anywhere in scope; export functions: ANY target — the
            # locals there become the serialized artifact
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                if value is None:
                    continue
                for target in targets:
                    base = (target.value
                            if isinstance(target, ast.Subscript)
                            else target)
                    persistent = _is_self_attr(base) or (
                        isinstance(base, ast.Name) and fn is None)
                    if persistent or exporting:
                        where = (f"persistent store "
                                 f"'{_key_tail(base)}'" if persistent
                                 else f"export path '{fn.name}'")
                        flag(value, where)
                        if isinstance(target, ast.Subscript):
                            flag(target, where)
                        break
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _GROW_CALLS:
                receiver = node.func.value
                persistent = _is_self_attr(receiver)
                if persistent or exporting:
                    where = (f"persistent store "
                             f"'{_key_tail(receiver)}'" if persistent
                             else f"export path '{fn.name}'")
                    for arg in [*node.args,
                                *[kw.value for kw in node.keywords]]:
                        flag(arg, where)
            elif exporting and isinstance(node, ast.Return) and \
                    node.value is not None:
                flag(node.value, f"export path '{fn.name}' return value")

        findings.sort(key=lambda f: f.line)
        return findings
