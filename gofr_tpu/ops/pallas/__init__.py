"""Pallas TPU kernels for the hot ops (see pallas_guide.md)."""

from gofr_tpu.ops.pallas.decode_attention import flash_decode_attention
from gofr_tpu.ops.pallas.fallback import resolve_interpret
from gofr_tpu.ops.pallas.flash_attention import flash_attention
from gofr_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_decode_attention, ragged_paged_verify_attention,
    ragged_supported)

__all__ = ["flash_attention", "flash_decode_attention",
           "ragged_paged_decode_attention", "ragged_paged_verify_attention",
           "ragged_supported", "resolve_interpret"]
