#!/usr/bin/env python
"""Tier-1 disagg smoke: a 2-role cluster in ONE process, in-proc
transport, tiny model on forced host devices.

Drives the exact tentpole path end-to-end — prefill replica exports KV,
the payload round-trips the kv_wire codec, the decode replica adopts it
as page-table entries, the router relays the stream — and asserts the
two acceptance properties cheap enough to gate every commit on:

1. greedy tokens identical to monolithic serving,
2. ZERO prefill dispatches on the decode replica, and drain returns the
   decode pool's free list to its idle level.

Prints ``disagg smoke: OK`` and exits 0, or raises with the failing
property. Budget: a few seconds on 8 host CPU devices.
"""

import asyncio
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    import jax

    from gofr_tpu.container import new_mock_container
    from gofr_tpu.models import llama
    from gofr_tpu.tpu.cluster import (ROLE_DECODE, ClusterRegistry,
                                      DisaggRouter, InProcTransport,
                                      NoReplicaAvailable)
    from gofr_tpu.tpu.generate import GenerationEngine

    cfg = llama.config("tiny")
    params = llama.init(cfg, jax.random.PRNGKey(0))

    def build(paged):
        container = new_mock_container()
        kwargs = dict(paged_kv=True) if paged else {}
        return GenerationEngine(cfg, params, max_slots=2, max_len=32,
                                prompt_buckets=(8,), kv_page=4,
                                logger=container.logger,
                                metrics=container.metrics, **kwargs)

    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    budget = 6

    async def monolithic():
        engine = build(True)
        await engine.start()
        try:
            return [await asyncio.wait_for(
                engine.generate(p, max_new_tokens=budget), 60.0)
                for p in prompts]
        finally:
            await engine.stop()

    async def disagg():
        prefill_eng, decode_eng = build(False), build(True)
        cluster = ClusterRegistry()
        cluster.register("p0", "prefill", InProcTransport(prefill_eng))
        cluster.register("d0", "decode", InProcTransport(decode_eng))
        router = DisaggRouter(cluster)
        await decode_eng.start()
        try:
            idle_pages = decode_eng._pool.free_pages
            outs = [await asyncio.wait_for(
                router.generate(p, max_new_tokens=budget), 60.0)
                for p in prompts]
            stats = decode_eng.stats()
            assert stats["prefill_bucket_tokens"] == 0, \
                f"decode replica ran prefill: {stats['prefill_bucket_tokens']}"
            assert stats["kv_adoptions"] == len(prompts)
            # drain: routing stops, pages come back to the idle level
            assert await cluster.drain("d0", timeout_s=30.0)
            try:
                cluster.pick(ROLE_DECODE)
            except NoReplicaAvailable:
                pass
            else:
                raise AssertionError("DRAINING replica still routable")
            for _ in range(200):
                if decode_eng._pool.free_pages == idle_pages:
                    break
                await asyncio.sleep(0.02)
            assert decode_eng._pool.free_pages == idle_pages, \
                (decode_eng._pool.free_pages, idle_pages)
            return outs
        finally:
            await decode_eng.stop()

    ref = asyncio.run(monolithic())
    outs = asyncio.run(disagg())
    assert outs == ref, f"token identity broke: {outs} != {ref}"
    print("disagg smoke: OK")


if __name__ == "__main__":
    main()
