from gofr_tpu.http.errors import (
    EntityAlreadyExists,
    EntityNotFound,
    HTTPError,
    InvalidParam,
    InvalidRoute,
    MissingParam,
    PanicRecovery,
    RequestTimeout,
)
from gofr_tpu.http.request import Request
from gofr_tpu.http.responder import Responder
from gofr_tpu.http.response import FileResponse, Raw, Redirect, Response
from gofr_tpu.http.router import Router

__all__ = [
    "EntityAlreadyExists",
    "EntityNotFound",
    "HTTPError",
    "InvalidParam",
    "InvalidRoute",
    "MissingParam",
    "PanicRecovery",
    "RequestTimeout",
    "Request",
    "Responder",
    "Response",
    "Raw",
    "FileResponse",
    "Redirect",
    "Router",
]
