"""GT006 kv-transfer-sync: KV pool leaves materialized on the event loop.

Disaggregated serving (ISSUE 8) moves whole prompts' KV between
replicas, and the tempting implementation is exactly the wrong one:
``np.asarray(pool.leaves["k"])`` / ``jax.device_get(...)`` inline in an
async handler. A KV handoff is megabytes per request — a 7B prompt's
pages are tens of MB — so one sync device→host copy on the loop stalls
*every* co-resident request for the duration of a PCIe/ICI transfer,
not the microseconds GT001's generic ``.item()`` case suggests. The
same goes for :mod:`~gofr_tpu.tpu.kv_wire` ``pack``/``unpack`` called
inline: both walk every leaf buffer (``tobytes``/``frombuffer``) and
are pure host CPU burn.

GT001 already flags bare ``np.asarray`` in async-reachable code; this
rule exists because KV-leaf materialization deserves its own id and
message — the fix (stage through ``run_in_executor`` like the engine's
``prefill_export``/``adopt_kv`` closures) and the blast radius (all
in-flight streams, per transfer) are specific, and a baseline that
waives generic GT001 hits must not silently waive multi-megabyte KV
copies with them.

Detection, over functions reachable from an ``async def`` without a
thread hop (callgraph ``loop_reachable``; executor-passed callables get
no edge and are naturally exempt):

- ``jax.device_get`` / ``np.asarray`` / ``np.array`` whose argument
  references KV pool leaves — an attribute access ending in ``.leaves``
  or a name/attribute containing ``pool``,
- ``.tobytes()`` on such a leaf expression (the serialization copy),
- any call resolving to ``kv_wire.pack`` / ``kv_wire.unpack``.

Suppress a deliberate inline use with ``# graftcheck: ignore[GT006]``
plus a justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from gofr_tpu.analysis.callgraph import CallGraph
from gofr_tpu.analysis.engine import Finding, ModuleInfo, Rule

# device→host materializers: flagged only when fed a KV-leaf expression
MATERIALIZERS = {"jax.device_get", "numpy.asarray", "numpy.array"}

# kv_wire entry points that walk every leaf buffer on the calling thread
_WIRE_SUFFIXES = ("kv_wire.pack", "kv_wire.unpack")


def _mentions_pool_leaves(node: ast.AST) -> bool:
    """Does this expression reference KV pool leaves? Matches attribute
    chains ending in ``.leaves`` (``pool.leaves``, ``self._pool.leaves``,
    ``payload.leaves``) and names/attributes containing ``pool``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            if sub.attr == "leaves" or "pool" in sub.attr:
                return True
        elif isinstance(sub, ast.Name) and "pool" in sub.id:
            return True
    return False


class KVTransferSyncRule(Rule):
    rule_id = "GT006"
    title = "kv-transfer-sync"
    severity = "error"

    def check_module(self, module: ModuleInfo) -> Iterable[Finding]:
        graph = CallGraph(module)
        chains = graph.loop_reachable()
        findings: List[Finding] = []
        for qualname, chain in chains.items():
            fn = graph.functions[qualname]
            for node in graph.body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._kv_sync(module, node)
                if hit is None:
                    continue
                label, why = hit
                via = (" via " + " -> ".join(chain[1:])
                       if len(chain) > 1 else "")
                findings.append(Finding(
                    rule=self.rule_id,
                    path=module.relpath,
                    line=node.lineno,
                    message=(
                        f"kv-transfer-sync: {label} inside '{qualname}' "
                        f"materializes KV pool leaves on the event loop "
                        f"(async root '{chain[0]}'{via}) — {why}; stage "
                        f"the copy in a run_in_executor closure like the "
                        f"engine's prefill_export/adopt_kv paths"),
                    severity=self.severity,
                    key=f"{label} in {qualname}",
                ))
        return findings

    def _kv_sync(self, module: ModuleInfo,
                 call: ast.Call) -> Optional[Tuple[str, str]]:
        dotted = module.dotted(call.func)
        if dotted is not None:
            for suffix in _WIRE_SUFFIXES:
                if dotted == suffix or dotted.endswith("." + suffix):
                    return (f"{suffix}(...)",
                            "serializing KV leaves walks every page "
                            "buffer on the calling thread")
            if dotted in MATERIALIZERS and call.args and \
                    _mentions_pool_leaves(call.args[0]):
                return (f"{dotted}(...) on pool leaves",
                        "a whole prompt's KV pages cross device->host "
                        "synchronously (megabytes per request)")
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "tobytes" \
                and _mentions_pool_leaves(func.value):
            return (".tobytes() on pool leaves",
                    "the serialization copy of every KV page runs on "
                    "the calling thread")
        return None
