"""In-process pub/sub broker.

The test double and single-process backend — the role miniredis/mocked Kafka
readers play in the reference's test strategy (SURVEY.md §4). Topics are
asyncio queues; consumer groups see each message once (queue semantics, like
a Kafka consumer group with one partition).

Trace propagation (ISSUE 2): ``publish`` runs inside a ``pubsub.publish``
span and injects its W3C ``traceparent`` as a message header, which
``subscribe`` surfaces via ``Message.header("traceparent")`` — the
subscriber loop continues the publisher's trace exactly as HTTP ingress
does for inbound requests.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict
from typing import Dict, Optional

from gofr_tpu.datasource import UP, health
from gofr_tpu.datasource.pubsub.base import Message, PubSub


class InMemoryBroker(PubSub):
    def __init__(self, logger=None, metrics=None, maxsize: int = 65536,
                 tracer=None):
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self.maxsize = maxsize
        self._queues: Dict[str, asyncio.Queue] = {}
        self._published = 0
        self._delivered = 0
        self._closed = False

    def _queue(self, topic: str) -> asyncio.Queue:
        queue = self._queues.get(topic)
        if queue is None:
            queue = asyncio.Queue(maxsize=self.maxsize)
            self._queues[topic] = queue
        return queue

    def publish(self, topic: str, payload: bytes, key: bytes = b"") -> None:
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_publish_total_count",
                                           topic=topic)
        headers: Dict[str, str] = {}
        span = None
        if self.tracer is not None:
            from gofr_tpu.trace import format_traceparent
            span = self.tracer.start_span("pubsub.publish")
            span.set_attribute("topic", topic)
            span.set_attribute("backend", "INMEM")
            headers["traceparent"] = format_traceparent(span)
        try:
            self._queue(topic).put_nowait((payload, key, headers))
            self._published += 1
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_pubsub_publish_success_count", topic=topic)
        except asyncio.QueueFull:
            if span is not None:
                span.set_status("ERROR")
            if self.logger is not None:
                self.logger.error("inmem broker: topic %s full, dropping", topic)
        finally:
            if span is not None:
                span.finish()

    async def subscribe(self, topic: str) -> Optional[Message]:
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_total_count",
                                           topic=topic)
        if self._closed:
            return None
        payload, key, headers = await self._queue(topic).get()
        self._delivered += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_pubsub_subscribe_success_count",
                                           topic=topic)
        return Message(topic, payload, key, metadata=dict(headers),
                       committer=lambda: None)

    def create_topic(self, topic: str) -> None:
        self._queue(topic)

    def delete_topic(self, topic: str) -> None:
        self._queues.pop(topic, None)

    def health_check(self) -> dict:
        return health(UP, backend="INMEM", topics=len(self._queues),
                      published=self._published, delivered=self._delivered)

    def close(self) -> None:
        self._closed = True
